"""Spread-aware perf-regression detection over the history store.

For every measurement series (history key) the baseline is the BEST
prior value — max for higher-is-better metrics (sweep rate), min for
lower-is-better (chain wall-clock). The newest entry (or an un-recorded
candidate payload) regresses when it falls short of that baseline by
more than

    max(threshold_pct, k * spread_pct)

where ``spread_pct`` is the larger of the candidate's and the baseline's
recorded rep spread (``bench_lib.repeat_best`` puts it on every official
record). The spread term is the executable form of BASELINE.md's tunnel
warning: the axon tunnel can inflate or deflate a single run, and the
best-of-N spread is the measured noise floor for exactly this config —
a 20% kernel drop on a 0.5%-spread series pages; 8% jitter on a
12%-spread series does not.

Findings carry the per-series arithmetic so the report is auditable,
and ``improved`` / ``insufficient-history`` verdicts are reported (not
just regressions) so a green check is distinguishable from a vacuous
one.
"""
from __future__ import annotations

import dataclasses

from .history import SECTION_METRICS, Entry, HistoryStore, entry_key

DEFAULT_THRESHOLD_PCT = 10.0
DEFAULT_SPREAD_K = 2.0

# Per-section noise floors that beat the global threshold. The same-run
# CPU sample load-drifts 0.8-1.8 MH/s on a shared box — BASELINE.md
# demoted it from the headline for exactly this reason — so its series
# only gates catastrophic host regressions, not scheduler weather.
# sim_adversarial runs in-process on the same shared host CPU, so its
# steps/sec inherits the identical load spread: same 60% floor — the
# sentinel gates engine regressions (an accidental O(n^2) bus), not
# scheduler weather.
SECTION_FLOOR_PCT = {"cpu_np8": 60.0, "sim_adversarial": 60.0}


@dataclasses.dataclass(frozen=True)
class Finding:
    key: str
    section: str
    metric: str
    direction: str
    verdict: str          # "regression" | "ok" | "improved"
                          # | "insufficient-history"
    candidate: float | None = None
    baseline: float | None = None
    baseline_at: str | None = None
    delta_pct: float | None = None     # positive = worse, by direction
    allowed_pct: float | None = None   # max(threshold, k*spread)
    spread_pct: float | None = None

    def to_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}

    def render(self) -> str:
        if self.verdict == "insufficient-history":
            return f"{self.key}: insufficient history (1 entry)"
        arrow = {"regression": "REGRESSION", "improved": "improved",
                 "ok": "ok"}[self.verdict]
        return (f"{self.key}: {arrow} {self.metric}={self.candidate:g} "
                f"vs baseline {self.baseline:g} "
                f"(delta {self.delta_pct:+.1f}%, positive = worse; "
                f"allowed {self.allowed_pct:.1f}%)")


def _delta_worse_pct(direction: str, baseline: float,
                     candidate: float) -> float:
    """How much worse the candidate is than the baseline, in percent of
    the baseline; negative = better."""
    scale = max(abs(baseline), 1e-12)
    if direction == "higher":
        return 100.0 * (baseline - candidate) / scale
    return 100.0 * (candidate - baseline) / scale


def _judge(key: str, baseline_pool: list[Entry], candidate: Entry,
           threshold_pct: float, k: float) -> Finding:
    metric, direction = candidate.metric
    if not baseline_pool:
        return Finding(key=key, section=candidate.section, metric=metric,
                       direction=direction or "",
                       verdict="insufficient-history",
                       candidate=candidate.value)
    pick = max if direction == "higher" else min
    best = pick(baseline_pool, key=lambda e: e.value)
    delta = _delta_worse_pct(direction, best.value, candidate.value)
    spread = max(candidate.spread_pct, best.spread_pct)
    allowed = max(threshold_pct, k * spread,
                  SECTION_FLOOR_PCT.get(candidate.section, 0.0))
    verdict = ("regression" if delta > allowed
               else "improved" if delta < 0 else "ok")
    return Finding(key=key, section=candidate.section, metric=metric,
                   direction=direction, verdict=verdict,
                   candidate=candidate.value, baseline=best.value,
                   baseline_at=best.recorded_at,
                   delta_pct=round(delta, 2),
                   allowed_pct=round(allowed, 2),
                   spread_pct=round(spread, 2))


def check_history(store: HistoryStore,
                  threshold_pct: float = DEFAULT_THRESHOLD_PCT,
                  k: float = DEFAULT_SPREAD_K) -> list[Finding]:
    """Judges the NEWEST entry of every series against the best of the
    rest. Newest by ``recorded_at`` (ISO-8601 Z strings sort
    lexicographically; the stable sort keeps file order for ties), NOT
    by file position — a late backfill (``record --seed-bench-rounds``
    after live appends) lands at the end of the file but carries its
    historical timestamp, and must become baseline, not candidate.
    Series whose section has direction None are skipped."""
    findings: list[Finding] = []
    for key, entries in sorted(store.by_key().items()):
        if entries[0].metric[1] is None:
            continue
        *prior, newest = sorted(entries, key=lambda e: e.recorded_at)
        findings.append(_judge(key, prior, newest, threshold_pct, k))
    return findings


def check_candidate(store: HistoryStore, section: str, payload: dict,
                    threshold_pct: float = DEFAULT_THRESHOLD_PCT,
                    k: float = DEFAULT_SPREAD_K) -> Finding:
    """Judges an un-recorded payload (the merge-gate shape: measure,
    check, only record when accepted) against the FULL history of its
    series."""
    spec = SECTION_METRICS.get(section)
    if spec is None or spec[1] is None:
        checked = sorted(s for s, (_, d) in SECTION_METRICS.items() if d)
        raise ValueError(f"section {section!r} is not regression-checked; "
                         f"have {checked}")
    if spec[0] not in payload:
        raise ValueError(f"payload lacks {section!r}'s metric {spec[0]!r}")
    cand = Entry(section=section, key=entry_key(section, payload),
                 recorded_at="", source="candidate", payload=dict(payload))
    pool = [e for e in store.entries(section) if e.key == cand.key]
    return _judge(cand.key, pool, cand, threshold_pct, k)


def regressions(findings: list[Finding]) -> list[Finding]:
    return [f for f in findings if f.verdict == "regression"]
