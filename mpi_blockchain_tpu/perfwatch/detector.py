"""Spread-aware perf-regression detection over the history store.

For every measurement series (history key) the baseline is the BEST
prior value — max for higher-is-better metrics (sweep rate), min for
lower-is-better (chain wall-clock). The newest entry (or an un-recorded
candidate payload) regresses when it falls short of that baseline by
more than

    max(threshold_pct, k * spread_pct)

where ``spread_pct`` is the larger of the candidate's and the baseline's
recorded rep spread (``bench_lib.repeat_best`` puts it on every official
record). The spread term is the executable form of BASELINE.md's tunnel
warning: the axon tunnel can inflate or deflate a single run, and the
best-of-N spread is the measured noise floor for exactly this config —
a 20% kernel drop on a 0.5%-spread series pages; 8% jitter on a
12%-spread series does not.

Findings carry the per-series arithmetic so the report is auditable,
and ``improved`` / ``insufficient-history`` verdicts are reported (not
just regressions) so a green check is distinguishable from a vacuous
one.
"""
from __future__ import annotations

import dataclasses

from .history import SECTION_METRICS, Entry, HistoryStore, entry_key

DEFAULT_THRESHOLD_PCT = 10.0
DEFAULT_SPREAD_K = 2.0

# Per-section noise floors that beat the global threshold. The same-run
# CPU sample load-drifts 0.8-1.8 MH/s on a shared box — BASELINE.md
# demoted it from the headline for exactly this reason — so its series
# only gates catastrophic host regressions, not scheduler weather.
# sim_adversarial runs in-process on the same shared host CPU, so its
# steps/sec inherits the identical load spread: same 60% floor — the
# sentinel gates engine regressions (an accidental O(n^2) bus), not
# scheduler weather.
SECTION_FLOOR_PCT = {"cpu_np8": 60.0, "sim_adversarial": 60.0}

# Sections gated by an ABSOLUTE bound on the metric value itself, not a
# relative drop from the best prior: {section: max allowed value}.
# trace_overhead is the telemetry observer-effect budget — always-on
# tracing may cost at most 3% of sweep throughput (ISSUE 10 acceptance;
# measured by blocktrace/overhead.py, wired through `make trace-smoke`).
# trace_block_observe bounds the PER-BLOCK critical-path observation
# (microseconds per observe_block_metrics call, measured in-situ) —
# block-cadence work gets its own budget instead of polluting the
# per-round sweep number with block-rate assumptions; ~90 us on the
# reference box, 300 us budget.
# pipeline_bubble bounds the pipelined miner's measured bubble_fraction
# (share of the mine's wall clock with NO dispatch in flight) at 0.15 —
# the ROADMAP item 1 acceptance: the async double-buffered dispatch must
# keep the device busy behind host winner-validation / append /
# checkpoint work (measured by meshwatch/bubble.py, wired through
# `make pipeline-smoke`).
# collective_skew bounds the 4-rank cpu-world rendezvous skew
# (max_skew_ms of the skew-smoke's mesh-skew report). The analyzer
# normalizes per-rank clock offsets first, so process-startup stagger
# never counts — what remains is per-round scheduler jitter on a shared
# host, which is weather, not signal: the bound only catches a
# pathological wedge (a rank stalling SECONDS inside the lockstep step).
# compile_cache bounds recompiles_after_warmup of the fixed-seed
# instrumented mine (dispatchwatch via `make compile-smoke`) at 0 — the
# exactly-once contract: every jitted sweep callable compiles once into
# its seam cache and is reused forever after; ANY post-warmup recompile
# is trace-cache churn (the runtime twin of the SHD003 divergent-trace
# class), never weather.
# serve bounds the serve smoke's p99 submit latency (ms) over loopback
# while a live miner consumes the rebuilt templates (`make serve-smoke`,
# service/__main__). 2000 ms is deliberately generous — per-request
# admission is microseconds of host work, so the bound catches a wedged
# or queueing door (the exact overload failure the admission contract
# forbids), never shared-box scheduler weather.
SECTION_BOUNDS = {"trace_overhead": 3.0, "trace_block_observe": 300.0,
                  "pipeline_bubble": 0.15, "collective_skew": 10000.0,
                  "compile_cache": 0.0, "serve": 2000.0}


@dataclasses.dataclass(frozen=True)
class Finding:
    key: str
    section: str
    metric: str
    direction: str
    verdict: str          # "regression" | "ok" | "improved"
                          # | "insufficient-history"
    candidate: float | None = None
    baseline: float | None = None
    baseline_at: str | None = None
    delta_pct: float | None = None     # positive = worse, by direction
    allowed_pct: float | None = None   # max(threshold, k*spread) | bound
    spread_pct: float | None = None
    # WHICH allowance won the max (the threshold that actually applied):
    # "threshold" | "spread" | "section-floor" | "absolute-bound".
    basis: str | None = None

    def to_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}

    def render(self) -> str:
        """The text verdict, carrying the candidate-vs-baseline delta
        AND the threshold that applied — the gate's arithmetic must be
        auditable from the terminal, not only from --json."""
        if self.verdict == "insufficient-history":
            return f"{self.key}: insufficient history (1 entry)"
        arrow = {"regression": "REGRESSION", "improved": "improved",
                 "ok": "ok"}[self.verdict]
        basis = f" [{self.basis}]" if self.basis else ""
        if self.basis == "absolute-bound":
            return (f"{self.key}: {arrow} {self.metric}="
                    f"{self.candidate:g} vs bound {self.allowed_pct:g} "
                    f"(absolute budget, no baseline){basis}")
        return (f"{self.key}: {arrow} {self.metric}={self.candidate:g} "
                f"vs baseline {self.baseline:g} "
                f"(delta {self.delta_pct:+.1f}%, positive = worse; "
                f"allowed {self.allowed_pct:.1f}%{basis})")


def _delta_worse_pct(direction: str, baseline: float,
                     candidate: float) -> float:
    """How much worse the candidate is than the baseline, in percent of
    the baseline; negative = better."""
    scale = max(abs(baseline), 1e-12)
    if direction == "higher":
        return 100.0 * (baseline - candidate) / scale
    return 100.0 * (candidate - baseline) / scale


def _judge(key: str, baseline_pool: list[Entry], candidate: Entry,
           threshold_pct: float, k: float) -> Finding:
    metric, direction = candidate.metric
    if not baseline_pool:
        return Finding(key=key, section=candidate.section, metric=metric,
                       direction=direction or "",
                       verdict="insufficient-history",
                       candidate=candidate.value)
    pick = max if direction == "higher" else min
    best = pick(baseline_pool, key=lambda e: e.value)
    delta = _delta_worse_pct(direction, best.value, candidate.value)
    spread = max(candidate.spread_pct, best.spread_pct)
    floor = SECTION_FLOOR_PCT.get(candidate.section, 0.0)
    allowed = max(threshold_pct, k * spread, floor)
    basis = ("section-floor" if allowed == floor and floor > threshold_pct
             else "spread" if allowed == k * spread
             and k * spread > threshold_pct
             else "threshold")
    verdict = ("regression" if delta > allowed
               else "improved" if delta < 0 else "ok")
    return Finding(key=key, section=candidate.section, metric=metric,
                   direction=direction, verdict=verdict,
                   candidate=candidate.value, baseline=best.value,
                   baseline_at=best.recorded_at,
                   delta_pct=round(delta, 2),
                   allowed_pct=round(allowed, 2),
                   spread_pct=round(spread, 2), basis=basis)


def _judge_bound(key: str, candidate: Entry) -> Finding:
    """Absolute-bound sections (SECTION_BOUNDS): the metric VALUE must
    stay under the budget — no baseline, no spread, no history needed."""
    metric, _ = candidate.metric
    bound = SECTION_BOUNDS[candidate.section]
    verdict = "regression" if candidate.value > bound else "ok"
    return Finding(key=key, section=candidate.section, metric=metric,
                   direction="bounded", verdict=verdict,
                   candidate=candidate.value,
                   allowed_pct=bound, basis="absolute-bound")


def check_history(store: HistoryStore,
                  threshold_pct: float = DEFAULT_THRESHOLD_PCT,
                  k: float = DEFAULT_SPREAD_K) -> list[Finding]:
    """Judges the NEWEST entry of every series against the best of the
    rest. Newest by ``recorded_at`` (ISO-8601 Z strings sort
    lexicographically; the stable sort keeps file order for ties), NOT
    by file position — a late backfill (``record --seed-bench-rounds``
    after live appends) lands at the end of the file but carries its
    historical timestamp, and must become baseline, not candidate.
    Series whose section has direction None are skipped."""
    findings: list[Finding] = []
    for key, entries in sorted(store.by_key().items()):
        ordered = sorted(entries, key=lambda e: e.recorded_at)
        newest = ordered[-1]
        if newest.section in SECTION_BOUNDS:
            findings.append(_judge_bound(key, newest))
            continue
        if entries[0].metric[1] is None:
            continue
        findings.append(_judge(key, ordered[:-1], newest,
                               threshold_pct, k))
    return findings


def check_candidate(store: HistoryStore, section: str, payload: dict,
                    threshold_pct: float = DEFAULT_THRESHOLD_PCT,
                    k: float = DEFAULT_SPREAD_K) -> Finding:
    """Judges an un-recorded payload (the merge-gate shape: measure,
    check, only record when accepted) against the FULL history of its
    series."""
    spec = SECTION_METRICS.get(section)
    if spec is None or (spec[1] is None and section not in SECTION_BOUNDS):
        checked = sorted(s for s, (_, d) in SECTION_METRICS.items()
                         if d or s in SECTION_BOUNDS)
        raise ValueError(f"section {section!r} is not regression-checked; "
                         f"have {checked}")
    if spec[0] not in payload:
        raise ValueError(f"payload lacks {section!r}'s metric {spec[0]!r}")
    cand = Entry(section=section, key=entry_key(section, payload),
                 recorded_at="", source="candidate", payload=dict(payload))
    if section in SECTION_BOUNDS:
        return _judge_bound(cand.key, cand)
    pool = [e for e in store.entries(section) if e.key == cand.key]
    return _judge(cand.key, pool, cand, threshold_pct, k)


def regressions(findings: list[Finding]) -> list[Finding]:
    return [f for f in findings if f.verdict == "regression"]
