"""meshwatch — per-rank telemetry shards, mesh-wide aggregation, and the
dispatch pipeline profiler.

Every observability layer before this one (registry, causal logs,
perfwatch server) is process-local: in an 8-rank world, rank 0's
``/metrics`` says nothing about ranks 1–7. meshwatch closes that gap
with three pieces (docs/observability.md §Mesh shards,
docs/perfwatch.md §Mesh healthz / §Pipeline report):

* **shard** — each rank atomically writes a rank-stamped shard file
  (registry snapshot + heartbeats + event/causal tails + pipeline
  records) into a shared directory on a background flusher
  (``--mesh-obs DIR`` / ``MPIBT_MESH_OBS`` on mine/sim/bench). A clean
  exit writes a ``final`` shard; a SIGKILL'd rank leaves a non-final
  shard whose age keeps growing — that asymmetry IS the dead-rank
  signal.
* **aggregate** — merges shards into one mesh view: counters summed
  across ranks, gauges/histograms kept per-rank under a ``rank`` label,
  heartbeats compared; ``mesh_health`` names stale/missing ranks
  (``mesh_rank_stale`` event + ``mesh_live_ranks`` gauge), feeding the
  mesh-aware ``/healthz`` served by ``meshwatch watch``.
* **pipeline** — times every miner dispatch's segments (enqueue, device
  in-flight, validate, append, checkpoint) into a bounded ring, computes
  per-dispatch overlap and the mesh's device bubble fraction (the number
  the async-dispatch roadmap item must drive to ~0), and exports a
  wall-clock Perfetto timeline with one track per rank and stage.

CLI: ``python -m mpi_blockchain_tpu.meshwatch {merge,report,watch,smoke}``
(``make meshwatch-smoke`` gates on ``smoke``). Standard library only;
importing this package never pulls in jax.
"""
from __future__ import annotations

from .aggregate import (merge_shards, mesh_health,  # noqa: F401
                        rank_status, read_shards,
                        recommended_action, render_mesh_prometheus)
from .pipeline import (PipelineProfiler, pipeline_report,  # noqa: F401
                       profiler, reset_profiler, to_chrome_trace)
from .shard import ShardWriter, install, installed, uninstall  # noqa: F401
