"""Mesh aggregator: merge per-rank shards into ONE mesh-wide view.

Merge semantics (docs/observability.md §Mesh shards):

* **counters are summed** across ranks per (name, labels) — a counter is
  a rate source and the mesh-wide rate is the sum (``hashes_tried_total``
  over 8 ranks is the mesh's hash rate numerator);
* **gauges and histograms stay per-rank** under a ``rank`` label —
  averaging a height gauge or pooling latency reservoirs would destroy
  exactly the per-rank attribution this subsystem exists for;
* **heartbeats are compared**, not merged: each rank's freshest
  heartbeat age (at shard-write time) plus the shard's own age is that
  rank's staleness.

Dead/straggler detection: a cleanly-exited rank wrote a ``final`` shard
with exit status 0 ("finished"); a final shard with a nonzero/"error"
exit status is **failed** — the rank died deliberately and said so, and
must never read as cleanly done. A rank is **stale** in either of two
ways, because the shard flusher is an independent daemon thread and a
wedged miner does NOT stop it:

* ``dead-shard`` — the newest shard is non-final and older than the
  stall budget (``MPIBT_MESH_STALL`` seconds, default 10): the whole
  process is gone (SIGKILL, OOM);
* ``no-progress`` — the shard is FRESH but the rank's freshest
  heartbeat age (as carried in the shard, plus the shard's own age)
  exceeds the progress budget (``MPIBT_HEALTHZ_STALL``, default 30 —
  the same budget the per-process ``/healthz`` watchdog uses), or the
  rank has run that long without ever producing a heartbeat: the
  process is alive but the work is wedged — the straggler case.

An expected rank (by ``world_size``) with no shard at all is
**missing**. Any stale, failed, or missing rank flips ``mesh_health``
to 503, names the ranks, emits one
``mesh_rank_stale``/``mesh_rank_failed`` event per transition, and
sets the ``mesh_live_ranks`` gauge — the signal the "dead chip shrinks
the mesh" degradation path acts on.
"""
from __future__ import annotations

import json
import pathlib
import time

from ..telemetry import emit_event, gauge
from ..telemetry.events import env_number
from .shard import SHARD_GLOB

#: Stall budget for shard age (seconds). Shards flush every
#: MPIBT_MESH_OBS_INTERVAL (default 1 s), so 10x that is a dead rank,
#: not a slow writer.
DEFAULT_MESH_STALL_S = env_number("MPIBT_MESH_STALL", 10.0, cast=float,
                                  minimum=1e-2)


def read_shards(directory) -> list[dict]:
    """Every parseable shard in ``directory``, sorted by rank. Malformed
    or torn files are skipped — including a non-integer ``rank`` —
    (writes are atomic, but a reader must survive a half-provisioned
    directory; one bad file must never take down every scrape)."""
    shards: list[dict] = []
    directory = pathlib.Path(directory)
    for path in sorted(directory.glob(SHARD_GLOB)):
        try:
            payload = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(payload, dict):
            continue
        try:
            payload["rank"] = int(payload["rank"])
        except (KeyError, TypeError, ValueError):
            continue
        shards.append(payload)
    shards.sort(key=lambda s: s["rank"])
    return shards


def _expected_world(shards: list[dict]) -> int:
    """The expected rank set's size: the largest declared world, but
    never smaller than the highest rank actually seen — a shard from
    rank N proves at least N+1 ranks exist regardless of what was
    declared. The ONE copy; rank_status and merge_shards must agree or
    /healthz's missing_ranks and /metrics' mesh_rank_up drift apart."""
    if not shards:
        return 0
    return max([int(s.get("world_size", 1)) for s in shards]
               + [int(s["rank"]) + 1 for s in shards])


def _metric_key(name: str, labels: dict) -> str:
    if not labels:
        return name
    body = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{body}}}"


def merge_shards(shards: list[dict]) -> dict:
    """The mesh-wide view of a shard set (pure function, no side
    effects): counters summed, gauges/histograms per-rank, heartbeats
    per-rank."""
    counters: dict[str, dict] = {}
    gauges: dict[str, dict] = {}
    histograms: dict[str, dict] = {}
    heartbeats: dict[str, dict] = {}
    for shard in shards:
        rank = str(int(shard["rank"]))
        heartbeats[rank] = dict(shard.get("heartbeats", {}))
        for name, samples in (shard.get("registry") or {}).items():
            for sample in samples:
                kind = sample.get("kind")
                labels = dict(sample.get("labels", {}))
                key = _metric_key(name, labels)
                if kind == "counter":
                    slot = counters.setdefault(
                        key, {"name": name, "labels": labels,
                              "total": 0, "by_rank": {}})
                    slot["total"] += sample.get("value", 0)
                    slot["by_rank"][rank] = sample.get("value", 0)
                elif kind == "gauge":
                    slot = gauges.setdefault(
                        key, {"name": name, "labels": labels,
                              "by_rank": {}})
                    slot["by_rank"][rank] = {
                        "value": sample.get("value"),
                        "age_s": sample.get("age_s")}
                elif kind == "histogram":
                    slot = histograms.setdefault(
                        key, {"name": name, "labels": labels,
                              "by_rank": {}})
                    slot["by_rank"][rank] = {
                        k: v for k, v in sample.items()
                        if k not in ("kind", "labels")}
    return {
        "version": 1,
        "ranks": [int(s["rank"]) for s in shards],
        "world_size": _expected_world(shards),
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
        "heartbeats": heartbeats,
    }


# ---- rank liveness --------------------------------------------------------


def recommended_action(status: str, stale_reason: str | None = None) -> str:
    """The machine-readable recovery verdict for one rank's
    classification — the ONE mapping the elastic supervisor
    (resilience/elastic.py) and every ``/healthz`` reader share, so
    "what should happen to this rank" is decided once, not per caller:

    * ``none``    — ``ok``/``finished``: leave it alone.
    * ``restart`` — ``stale`` with ``no-progress``: the process is
      ALIVE (its shard flusher still writes) but the work is wedged —
      restarting it is the remedy; evicting a live rank that later
      recovers would re-overlap the stripes it still sweeps.
    * ``evict``   — ``stale`` with ``dead-shard`` (the process is gone:
      SIGKILL, OOM), ``failed`` (it exited deliberately and badly — it
      left the mesh), or ``missing`` (expected, never wrote a shard;
      the supervisor applies its own startup grace before acting).
    """
    if status in ("ok", "finished"):
        return "none"
    if status == "stale":
        return "restart" if stale_reason == "no-progress" else "evict"
    if status in ("failed", "missing"):
        return "evict"
    return "none"


def rank_status(shards: list[dict], stall_s: float | None = None,
                now: float | None = None,
                heartbeat_stall_s: float | None = None) -> dict:
    """Per-rank liveness: ``ok`` (fresh shard AND fresh progress),
    ``finished`` (final shard, exit status 0), ``failed`` (final shard
    with a nonzero exit status — the rank exited deliberately but
    badly), ``stale`` (``stale_reason`` = ``dead-shard`` for a stopped
    writer, ``no-progress`` for a live writer whose heartbeats stopped
    — the flusher thread survives a wedged miner, so shard age alone
    cannot catch stragglers), plus ``missing`` entries for
    expected-but-absent ranks."""
    from ..perfwatch.server import DEFAULT_STALL_S

    stall_s = float(stall_s if stall_s is not None else DEFAULT_MESH_STALL_S)
    heartbeat_stall_s = float(heartbeat_stall_s
                              if heartbeat_stall_s is not None
                              else DEFAULT_STALL_S)
    now = time.time() if now is None else now
    world = _expected_world(shards)
    ranks: dict[str, dict] = {}
    for shard in shards:
        rank = str(int(shard["rank"]))
        shard_age = max(now - float(shard.get("written_at", 0.0)), 0.0)
        beat_ages = [b.get("age_s") for b in
                     (shard.get("heartbeats") or {}).values()
                     if b.get("age_s") is not None]
        freshest = (min(beat_ages) + shard_age) if beat_ages else None
        final = bool(shard.get("final"))
        exit_status = shard.get("exit_status")
        failed = final and exit_status not in (0, None)
        stale_reason = None
        if not final:
            if shard_age > stall_s:
                stale_reason = "dead-shard"
            elif freshest is not None and freshest > heartbeat_stall_s:
                stale_reason = "no-progress"
            elif freshest is None and shard.get("started_at") is not None \
                    and now - float(shard["started_at"]) > heartbeat_stall_s:
                # Running that long without EVER heartbeating: wedged
                # before its first unit of work (a hung device init).
                stale_reason = "no-progress"
        state = ("failed" if failed
                 else "finished" if final
                 else "stale" if stale_reason else "ok")
        ranks[rank] = {
            "status": state,
            "stale_reason": stale_reason,
            "recommended_action": recommended_action(state, stale_reason),
            "final": final,
            "exit_status": exit_status,
            "shard_age_s": round(shard_age, 3),
            "heartbeat_age_s": (None if freshest is None
                                else round(freshest, 3)),
            "pid": shard.get("pid"),
            "seq": shard.get("seq"),
        }
    present = {int(r) for r in ranks}
    for rank in range(world):
        if rank not in present:
            ranks[str(rank)] = {"status": "missing",
                                "stale_reason": None,
                                "recommended_action":
                                    recommended_action("missing"),
                                "final": False,
                                "exit_status": None,
                                "shard_age_s": None,
                                "heartbeat_age_s": None,
                                "pid": None, "seq": None}
    return {"world_size": world, "stall_s": stall_s,
            "heartbeat_stall_s": heartbeat_stall_s, "ranks": ranks}


# mesh_rank_stale fires once per transition into staleness, not on every
# scrape; keyed by (directory, rank) so two watched meshes don't cross.
_stale_announced: set[tuple[str, str]] = set()


def mesh_health(directory, stall_s: float | None = None,
                now: float | None = None,
                shards: list[dict] | None = None,
                heartbeat_stall_s: float | None = None
                ) -> tuple[int, dict]:
    """(http status, payload) for the mesh-aware ``/healthz``.

    200 while every expected rank is ``ok`` or ``finished``; 503 the
    moment any rank is stale, failed, or missing — with the offending
    ranks named so the degradation path knows exactly which chip to
    drop.
    """
    if shards is None:
        shards = read_shards(directory)
    if not shards:
        return 503, {"status": "no-shards", "healthy": False,
                     "directory": str(directory), "ranks": {},
                     "stale_ranks": [], "failed_ranks": [],
                     "missing_ranks": [],
                     "live_ranks": 0, "world_size": 0,
                     "skew": {}, "memory": {}, "incidents": [],
                     "compiles": {}, "service": {}}
    status = rank_status(shards, stall_s=stall_s, now=now,
                         heartbeat_stall_s=heartbeat_stall_s)
    ranks = status["ranks"]
    stale = sorted((int(r) for r, v in ranks.items()
                    if v["status"] == "stale"))
    failed = sorted((int(r) for r, v in ranks.items()
                     if v["status"] == "failed"))
    missing = sorted((int(r) for r, v in ranks.items()
                      if v["status"] == "missing"))
    live = sorted((int(r) for r, v in ranks.items()
                   if v["status"] == "ok"))
    gauge("mesh_live_ranks",
          help="ranks with a fresh, non-final shard").set(len(live))
    dir_key = str(directory)
    for rank in stale:
        if (dir_key, f"stale:{rank}") not in _stale_announced:
            _stale_announced.add((dir_key, f"stale:{rank}"))
            emit_event({"event": "mesh_rank_stale", "rank": rank,
                        "reason": ranks[str(rank)]["stale_reason"],
                        "shard_age_s": ranks[str(rank)]["shard_age_s"],
                        "heartbeat_age_s":
                            ranks[str(rank)]["heartbeat_age_s"],
                        "stall_s": status["stall_s"]})
    for rank in failed:
        if (dir_key, f"failed:{rank}") not in _stale_announced:
            _stale_announced.add((dir_key, f"failed:{rank}"))
            emit_event({"event": "mesh_rank_failed", "rank": rank,
                        "exit_status":
                            ranks[str(rank)]["exit_status"]})
    for rank in list(live) + [int(r) for r, v in ranks.items()
                              if v["status"] == "finished"]:
        _stale_announced.discard((dir_key, f"stale:{rank}"))  # recovered
        _stale_announced.discard((dir_key, f"failed:{rank}"))
    healthy = not stale and not failed and not missing
    # The meshprof joins: live-rank rendezvous skew (straggler named per
    # site) and per-rank device-memory watermarks. Additive keys — every
    # pre-existing field keeps its shape (the /healthz schema pin).
    from ..meshprof.analyzer import analyze_skew, skew_summary

    memory = {str(s.get("rank")): s["memory"] for s in shards
              if isinstance(s.get("memory"), dict) and s.get("memory")}
    payload = {
        "status": "ok" if healthy else "degraded",
        "healthy": healthy,
        "world_size": status["world_size"],
        "stall_s": status["stall_s"],
        "heartbeat_stall_s": status["heartbeat_stall_s"],
        "live_ranks": len(live),
        "stale_ranks": stale,
        "failed_ranks": failed,
        "missing_ranks": missing,
        "ranks": ranks,
        "skew": skew_summary(analyze_skew(shards)),
        "memory": memory,
        # Open chainwatch incidents across the mesh, rank-stamped.
        # Additive like skew/memory: [] when no rank carries any, and
        # every pre-existing key keeps its shape (the schema pin in
        # tests/test_meshwatch.py).
        "incidents": mesh_incidents(shards),
        # Per-rank compile census (dispatchwatch carriage): divergent
        # compile counts across ranks are the desync smell single-chip
        # CI can't reproduce — flagged here before the hang.
        "compiles": mesh_compiles(shards),
        # Per-rank blockserve door stats (service carriage): mempool
        # saturation and closed accept gates, {} on serviceless meshes.
        "service": mesh_service(shards),
    }
    return (200 if healthy else 503), payload


def mesh_incidents(shards: list[dict]) -> list[dict]:
    """Every open incident carried by a shard set, each stamped with
    the reporting rank, ordered (rank, incident_seq). Pure function —
    the ``/incidents`` endpoint and ``perfwatch incidents`` share it."""
    out: list[dict] = []
    for shard in shards:
        for inc in shard.get("incidents") or ():
            if isinstance(inc, dict):
                out.append({**inc, "rank": int(shard["rank"])})
    out.sort(key=lambda i: (i["rank"], i.get("incident_seq", 0)))
    return out


def mesh_compiles(shards: list[dict]) -> dict:
    """Mesh-wide compile-census view off the shard ``compiles``
    carriage: per-rank backend-compile totals (with the per-site
    breakdown), the min/max across reporting ranks and a ``divergent``
    flag when they disagree — the every-rank-must-compile-the-same-
    programs invariant a multi-chip bring-up is accepted against.
    ``{}`` when no rank carries a census (cold-backend mesh). Pure
    function — ``/healthz`` and ``perfwatch compiles`` share it."""
    by_rank: dict[str, dict] = {}
    for shard in shards:
        sites = (shard.get("compiles") or {}).get("sites") or {}
        if not sites:
            continue
        by_rank[str(int(shard["rank"]))] = {
            "total": sum(int(st.get("compiles", 0))
                         for st in sites.values()),
            "sites": {site: int(st.get("compiles", 0))
                      for site, st in sorted(sites.items())},
        }
    if not by_rank:
        return {}
    totals = [v["total"] for v in by_rank.values()]
    return {"by_rank": dict(sorted(by_rank.items(),
                                   key=lambda kv: int(kv[0]))),
            "max": max(totals), "min": min(totals),
            "divergent": max(totals) != min(totals)}


def mesh_service(shards: list[dict]) -> dict:
    """Mesh-wide blockserve view off the shard ``service`` carriage:
    per-rank door stats plus the mesh totals the saturation triage
    reads first — summed mempool depth, summed sheds by reason, and
    which ranks' accept gates are closed. ``{}`` when no rank carries a
    door (the serviceless shape the schema pin fixes). Pure function —
    ``/healthz`` shares it with tests."""
    by_rank: dict[str, dict] = {}
    for shard in shards:
        svc = shard.get("service") or {}
        if not svc:
            continue
        by_rank[str(int(shard["rank"]))] = svc
    if not by_rank:
        return {}
    shed: dict[str, int] = {}
    for svc in by_rank.values():
        for reason, n in (svc.get("shed_total") or {}).items():
            shed[reason] = shed.get(reason, 0) + int(n)
    return {"by_rank": dict(sorted(by_rank.items(),
                                   key=lambda kv: int(kv[0]))),
            "depth": sum(int((v.get("mempool") or {}).get("depth", 0))
                         for v in by_rank.values()),
            "shed_total": dict(sorted(shed.items())),
            "gates_closed": sorted(
                int(r) for r, v in by_rank.items()
                if not (v.get("accept_gate") or {}).get("open", True))}


# ---- Prometheus rendering -------------------------------------------------


def _prom_labels(labels: dict, rank: str | None = None) -> str:
    from ..telemetry.registry import _escape_label_value

    labels = dict(labels)
    # A metric registered through the rank_* helpers already carries its
    # own rank label — that one is authoritative (it was stamped at
    # registration time); appending the shard's rank too would emit a
    # duplicate label name, which Prometheus rejects outright.
    if rank is not None and "rank" not in labels:
        labels["rank"] = rank
    items = sorted(labels.items())
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(str(v))}"'
                    for k, v in items)
    return "{" + body + "}"


def _prom_value(v) -> str:
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return f"{v:.9g}" if isinstance(v, float) else str(v)


def render_mesh_prometheus(view: dict, health: dict | None = None) -> str:
    """Prometheus text for a merged view: counters summed (no rank
    label), gauges/histogram summaries per-rank under ``rank``, plus the
    mesh liveness series when a health payload is supplied."""
    lines: list[str] = []
    seen: set[str] = set()
    for key in sorted(view.get("counters", {})):
        c = view["counters"][key]
        if c["name"] not in seen:
            seen.add(c["name"])
            lines.append(f"# TYPE {c['name']} counter")
        lines.append(f"{c['name']}{_prom_labels(c['labels'])} "
                     f"{_prom_value(c['total'])}")
    for key in sorted(view.get("gauges", {})):
        g = view["gauges"][key]
        if g["name"] not in seen:
            seen.add(g["name"])
            lines.append(f"# TYPE {g['name']} gauge")
        for rank in sorted(g["by_rank"], key=int):
            sample = g["by_rank"][rank]
            if sample.get("age_s") is None:   # never set on that rank
                continue
            lines.append(f"{g['name']}{_prom_labels(g['labels'], rank)} "
                         f"{_prom_value(sample['value'])}")
    for key in sorted(view.get("histograms", {})):
        h = view["histograms"][key]
        if h["name"] not in seen:
            seen.add(h["name"])
            lines.append(f"# TYPE {h['name']} summary")
        for rank in sorted(h["by_rank"], key=int):
            snap = h["by_rank"][rank]
            for q_key, q_label in (("p50", "0.5"), ("p95", "0.95"),
                                   ("p99", "0.99")):
                if snap.get(q_key) is not None:
                    lines.append(
                        f"{h['name']}"
                        f"{_prom_labels(dict(h['labels'], quantile=q_label), rank)} "
                        f"{_prom_value(snap[q_key])}")
            lines.append(f"{h['name']}_count"
                         f"{_prom_labels(h['labels'], rank)} "
                         f"{_prom_value(snap.get('count', 0))}")
            lines.append(f"{h['name']}_sum"
                         f"{_prom_labels(h['labels'], rank)} "
                         f"{_prom_value(snap.get('sum', 0))}")
    if health is not None:
        lines.append("# TYPE mesh_live_ranks gauge")
        lines.append(f"mesh_live_ranks {health.get('live_ranks', 0)}")
        lines.append("# TYPE mesh_rank_up gauge")
        for rank, info in sorted(health.get("ranks", {}).items(),
                                 key=lambda kv: int(kv[0])):
            up = 1 if info["status"] in ("ok", "finished") else 0
            lines.append(f'mesh_rank_up{{rank="{rank}"}} {up}')
    return "\n".join(lines) + ("\n" if lines else "")
