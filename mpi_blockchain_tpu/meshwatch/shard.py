"""Per-rank telemetry shard writer.

One rank = one JSON file in a shared directory (``rank_0003.json``),
rewritten atomically (tmp + ``os.replace`` — a reader never sees a torn
shard) by a daemon flusher thread every ``MPIBT_MESH_OBS_INTERVAL``
seconds (default 1.0) and once more at close. The shard carries:

* identity: ``rank``, ``world_size``, ``pid``, a per-rank write ``seq``;
* ``written_at`` (wall clock) — shard age is the liveness signal the
  aggregator compares against the stall budget;
* ``final`` — True only on the exit write, with ``exit_status`` (the
  CLI's return code, or "error" for an uncaught exception) alongside.
  A rank that exits says goodbye — and HOW it exited travels with the
  goodbye, so a clean rc-0 rank reads ``finished`` while an rc-2 one
  reads ``failed``. A SIGKILL'd rank cannot say goodbye at all, so its
  last shard stays non-final and ages — that asymmetry is how
  ``mesh_health`` tells "done" from "dead" without any coordinator.
  Failure paths that keep the process alive use ``abort()`` (stop the
  flusher, NO final write) so the frozen shard ages into staleness
  instead of being refreshed forever;
* ``heartbeats`` — every ``*_heartbeat`` gauge's value + age at write;
* ``registry`` — the full registry snapshot (counters summed by the
  aggregator, gauges/histograms kept per-rank);
* ``events_tail`` / ``causal_tail`` — bounded tails of the event ring
  and of any flight-recorder-registered network's causal logs;
* ``pipeline`` — the dispatch pipeline profiler's record tail
  (``meshwatch report --dir`` reads these);
* ``skew_spans`` — the newest rendezvous skew spans (``meshprof``: the
  mesh-skew analyzer joins them across shards on (site, round));
* ``memory`` — per-device memory watermarks (empty on ranks that never
  imported jax);
* ``incidents`` — the rank's open chainwatch incidents (empty while
  the watchdog is disarmed); the flush tick is also one of chainwatch's
  two rule-evaluation cadences;
* ``compiles`` — the rank's dispatchwatch compile snapshot (per-site
  census + event tail; ``{}`` on ranks that never observed a compile),
  so divergent per-rank compile counts surface in ``mesh_health``
  before the desync hang they precede;
* ``service`` — the rank's blockserve door stats (mempool depth, shed
  totals, accept-gate state; ``{}`` on serviceless ranks), so the mesh
  ``/healthz`` can show saturation and closed doors per rank.

Wall-clock timestamps are deliberate here (unlike the causal logs):
staleness is a wall-clock question, and shards never participate in the
byte-identical-dump determinism contract.
"""
from __future__ import annotations

import contextlib
import json
import os
import pathlib
import tempfile
import threading
import time

from ..telemetry import default_registry, heartbeat_snapshot, set_mesh_rank
from ..telemetry.events import env_number, recent_with_seq

SHARD_VERSION = 1
SHARD_PREFIX = "rank_"
SHARD_GLOB = SHARD_PREFIX + "*.json"

#: Background flush cadence (seconds). Cheap: one snapshot + one small
#: file write per tick.
DEFAULT_INTERVAL_S = env_number("MPIBT_MESH_OBS_INTERVAL", 1.0, cast=float,
                                minimum=1e-2)

EVENTS_TAIL_N = 64     # newest event-ring records carried per shard
CAUSAL_TAIL_N = 64     # newest causal records per sim node
PIPELINE_TAIL_N = 512  # newest pipeline dispatch records


def shard_path(directory, rank: int) -> pathlib.Path:
    return pathlib.Path(directory) / f"{SHARD_PREFIX}{int(rank):04d}.json"


class ShardWriter:
    """Writes this process's telemetry shard; start() arms the flusher."""

    def __init__(self, directory, rank: int = 0, world_size: int = 1,
                 interval_s: float | None = None, registry=None):
        self.directory = pathlib.Path(directory)
        self.rank = int(rank)
        self.world_size = max(int(world_size), 1)
        self.interval_s = float(interval_s if interval_s is not None
                                else DEFAULT_INTERVAL_S)
        self._registry = registry
        self._seq = 0
        self._started_at = time.time()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Guards _seq: payload() runs from both the flusher thread and
        # the closing caller (chainlint CONC001 + THR002 hold this
        # discipline; the flusher interval wait and the bounded close
        # join are committed WAITBUDGET.json sites).
        self._lock = threading.Lock()

    @property
    def path(self) -> pathlib.Path:
        return shard_path(self.directory, self.rank)

    # ---- payload ---------------------------------------------------------

    def _causal_tails(self) -> dict:
        """Causal-log tails of every flight-recorder-registered network
        (sim runs); {} when none is registered (mine/bench runs)."""
        from ..telemetry import flight_recorder

        tails: dict = {}
        for net in flight_recorder.registered_networks():
            try:
                logs = net.causal_logs()
            except (AttributeError, RuntimeError):
                continue    # a half-built network must not kill a flush
            for log in logs:
                tails[str(log.node_id)] = log.events()[-CAUSAL_TAIL_N:]
        return tails

    def payload(self, final: bool = False,
                status: int | str | None = None) -> dict:
        reg = (self._registry if self._registry is not None
               else default_registry())
        beats = heartbeat_snapshot(reg)
        with self._lock:
            self._seq += 1
            seq = self._seq
        from ..chainwatch import evaluate as chainwatch_evaluate
        from ..chainwatch import open_incidents
        from ..dispatchwatch import compile_snapshot
        from ..meshprof.memory import memory_snapshot
        from ..meshprof.spans import SKEW_TAIL_N, spans_tail
        from ..service import service_stats
        from .pipeline import profiler

        # The shard-flush tick is one of chainwatch's two sanctioned
        # evaluation cadences (the other: observe_block_metrics). This
        # runs on the flusher daemon thread — off the mining hot path —
        # so the full rule sweep is forced, no throttle. Disarmed/off
        # processes pay a flag check.
        chainwatch_evaluate(source="flush", force=True)
        return {
            "version": SHARD_VERSION,
            "rank": self.rank,
            "world_size": self.world_size,
            "pid": os.getpid(),
            "seq": seq,
            "final": bool(final),
            # Only meaningful on the final write: 0/None reads as
            # `finished`, anything else as `failed` (aggregate.py).
            "exit_status": status if final else None,
            "written_at": time.time(),
            # When this rank started: lets the aggregator flag a rank
            # that never produced a heartbeat (wedged before its first
            # unit of work) once the progress budget elapses.
            "started_at": self._started_at,
            "heartbeats": beats,
            "registry": reg.snapshot(),
            "events_tail": [
                {"seq": s, **r}
                for s, r in recent_with_seq(n=EVENTS_TAIL_N)],
            "causal_tail": self._causal_tails(),
            "pipeline": profiler().records(tail=PIPELINE_TAIL_N),
            # Rendezvous skew spans + device-memory watermarks (the
            # meshprof carriage: the mesh-skew analyzer joins the spans
            # across shards on (site, round); memory stays {} on ranks
            # that never imported jax).
            "skew_spans": spans_tail(SKEW_TAIL_N),
            "memory": memory_snapshot(),
            # Open chainwatch incidents ride the shard (same carriage
            # model as skew_spans/memory: [] while disarmed) so the
            # aggregator's /healthz and /incidents views see them.
            "incidents": open_incidents(),
            # Dispatchwatch compile census rides the same carriage ({}
            # on cold-backend ranks) so mesh_health can flag divergent
            # per-rank compile counts before the desync hang.
            "compiles": compile_snapshot(),
            # Blockserve door stats ({} on serviceless ranks): mempool
            # depth, shed totals and accept-gate state ride to the mesh
            # aggregator's /healthz `service` view.
            "service": service_stats(),
        }

    # ---- writing ---------------------------------------------------------

    def write(self, final: bool = False,
              status: int | str | None = None) -> pathlib.Path:
        """One atomic shard write: tmp in the same directory + replace."""
        self.directory.mkdir(parents=True, exist_ok=True)
        data = json.dumps(self.payload(final=final, status=status),
                          sort_keys=True, default=str)
        fd, tmp = tempfile.mkstemp(prefix=f".{SHARD_PREFIX}{self.rank}-",
                                   suffix=".tmp", dir=str(self.directory))
        try:
            with os.fdopen(fd, "w") as f:
                f.write(data)
            os.replace(tmp, self.path)
        except BaseException:
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            raise
        return self.path

    def start(self) -> pathlib.Path:
        """First write (so the shard exists before any work) + flusher."""
        set_mesh_rank(self.rank)
        path = self.write()
        self._thread = threading.Thread(
            target=self._loop, name=f"meshwatch-shard-{self.rank}",
            daemon=True)
        self._thread.start()
        return path

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.write()
            except OSError:
                # A transient FS error must not kill the flusher; the
                # next tick retries (a persistently failing shard just
                # reads as stale mesh-side, which is the right signal).
                pass

    def rebind(self, rank: int, world_size: int | None = None) -> None:
        """Re-stamp this writer's rank identity and move to the new
        shard path. Called after ``jax.distributed.initialize`` resolves
        the REAL process index — the CLI arms the writer before the
        world exists, so an auto-detected launch (no ``--process-id``)
        would otherwise have every host clobbering ``rank_0000.json``.
        The abandoned file is NOT deleted: on shared storage it may be
        the legitimate shard of whichever rank actually resolves to the
        old id, and that rank's flusher overwrites it anyway."""
        rank = int(rank)
        if world_size is not None:
            self.world_size = max(int(world_size), 1)
        if rank != self.rank:
            self.rank = rank
        set_mesh_rank(rank)
        # A flusher tick racing this mutation can write one transitional
        # shard; the next tick (and this write) correct it. Same
        # tolerance as the flusher loop: a transient FS error here must
        # not kill the run (this is called inside distributed init).
        try:
            self.write()
        except OSError:
            pass

    def close(self, status: int | str | None = None) -> None:
        """Stop the flusher and write the ``final`` shard, carrying the
        exit status (0/None = finished, anything else = failed) so a
        rank that exited BADLY never reads as cleanly done. Idempotent."""
        self._stop_flusher()
        try:
            self.write(final=True, status=status)
        except OSError:
            pass

    def abort(self) -> None:
        """Stop the flusher WITHOUT a final write. For failure paths in
        long-lived processes: the shard freezes at its last refresh and
        ages past the stall budget — the failed rank reads ``stale``,
        which is the truth. (A dying process can just not call close();
        this exists for callers that stay alive after the failure.)"""
        self._stop_flusher()

    def _stop_flusher(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# ---- the process-default writer (CLI arming point) ------------------------

_writer: ShardWriter | None = None


def install(directory, rank: int = 0, world_size: int = 1,
            interval_s: float | None = None) -> ShardWriter:
    """Arms the process shard writer (replacing any previous one). On a
    failed first write nothing stays armed — a later ``rebind_installed``
    / ``uninstall`` must not trip over a writer that never worked."""
    global _writer
    if _writer is not None:
        _writer.close()
        _writer = None
    writer = ShardWriter(directory, rank=rank, world_size=world_size,
                         interval_s=interval_s)
    writer.start()
    _writer = writer
    return writer


def installed() -> ShardWriter | None:
    return _writer


def rebind_installed(rank: int, world_size: int | None = None) -> None:
    """Re-stamp the installed writer's rank (no-op when none is armed).
    ``parallel/distributed.py`` calls this right after the jax world
    resolves, so shard files carry the real process index even when the
    launcher could not know it."""
    if _writer is not None:
        _writer.rebind(rank, world_size)


def uninstall(status: int | str | None = None) -> None:
    """Final flush (stamped with the run's exit status) + disarm — every
    CLI exit path calls this."""
    global _writer
    if _writer is not None:
        _writer.close(status=status)
        _writer = None
