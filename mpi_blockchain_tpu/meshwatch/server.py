"""Mesh-aware HTTP endpoint over a shard directory.

The per-process perfwatch server answers for ONE rank; this one answers
for the mesh: every scrape re-reads the shard directory, merges, and
serves

* ``/healthz`` — ``aggregate.mesh_health``: 200 while every expected
  rank is fresh or finished, 503 naming stale/failed/missing ranks;
* ``/metrics`` — the merged Prometheus view (counters summed,
  gauges/histograms per-rank under ``rank``, ``mesh_live_ranks`` /
  ``mesh_rank_up`` liveness series);
* ``/ranks`` — the per-rank liveness JSON (status, stale reason, shard
  age, heartbeat age, pid);
* ``/incidents`` — every open chainwatch incident carried by the
  shards, rank-stamped (the live-SLO view; ``/healthz`` carries the
  same list under its additive ``incidents`` key).

Run it with ``python -m mpi_blockchain_tpu.meshwatch watch --dir DIR``.
The lifecycle scaffolding (bind, daemon serve thread, idempotent
``close()``, hardened ``_send``) is inherited from perfwatch's
``MetricsServer`` — one copy, hardened once; this server only swaps in
its own routes and stays out of the perfwatch active-server registry
(it observes a directory, not this process's registry).
"""
from __future__ import annotations

import json

from ..perfwatch.server import MetricsServer, _Handler
from .aggregate import merge_shards, mesh_health, mesh_incidents, \
    read_shards, render_mesh_prometheus


class _MeshHandler(_Handler):
    def do_GET(self) -> None:  # noqa: N802 (stdlib signature)
        ctx = self.server_ctx
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            code, payload = mesh_health(ctx.directory,
                                        stall_s=ctx.mesh_stall_s)
            self._send(code, json.dumps(payload, sort_keys=True) + "\n",
                       "application/json")
        elif path == "/metrics":
            shards = read_shards(ctx.directory)
            _, health = mesh_health(ctx.directory,
                                    stall_s=ctx.mesh_stall_s,
                                    shards=shards)
            body = render_mesh_prometheus(merge_shards(shards), health)
            self._send(200, body,
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/ranks":
            _, health = mesh_health(ctx.directory,
                                    stall_s=ctx.mesh_stall_s)
            self._send(200, json.dumps(health.get("ranks", {}),
                                       sort_keys=True) + "\n",
                       "application/json")
        elif path == "/incidents":
            incidents = mesh_incidents(read_shards(ctx.directory))
            self._send(200, json.dumps({"incidents": incidents,
                                        "count": len(incidents)},
                                       sort_keys=True) + "\n",
                       "application/json")
        else:
            self._send(404, json.dumps({
                "error": f"unknown path {path!r}",
                "endpoints": ["/healthz", "/incidents", "/metrics",
                              "/ranks"]}) + "\n",
                "application/json")


class MeshServer(MetricsServer):
    """Threaded endpoint over a shard directory; scrape-time merging."""

    handler_cls = _MeshHandler
    register_active = False     # observes a directory, not this process

    def __init__(self, directory, port: int = 0, host: str = "127.0.0.1",
                 stall_s: float | None = None):
        super().__init__(port=port, host=host)
        self.directory = directory
        # None defers to aggregate's MPIBT_MESH_STALL default — distinct
        # from the base class's per-process healthz budget.
        self.mesh_stall_s = stall_s

    def url(self, path: str = "/healthz") -> str:
        return super().url(path)
