"""CLI: python -m mpi_blockchain_tpu.meshwatch
        {merge,report,watch,smoke,bubble,pipeline-smoke,skew-smoke}

    # one mesh-wide view of a shard directory (counters summed,
    # gauges/histograms per-rank), with rank liveness
    python -m mpi_blockchain_tpu.meshwatch merge --dir /tmp/mesh

    # dispatch pipeline report (+ wall-clock Perfetto trace) from the
    # shards' profiler records
    python -m mpi_blockchain_tpu.meshwatch report --dir /tmp/mesh \\
        --trace pipeline_trace.json

    # serve the mesh-aware /healthz /metrics /ranks until interrupted
    python -m mpi_blockchain_tpu.meshwatch watch --dir /tmp/mesh --port 0

``smoke`` is the CI shape (``make meshwatch-smoke``): launch a 4-rank
virtual-cpu world with ``--mesh-obs``, SIGKILL one rank mid-run, then
prove the merged view sums the per-rank counters, names exactly the
killed rank as stale, and renders a non-empty pipeline report + trace.

``pipeline-smoke`` is the ROADMAP-item-1 gate (``make pipeline-smoke``):
the fixed-seed instrumented mine's pipelined ``bubble_fraction`` stays
inside the SECTION_BOUNDS budget (<= 0.15), the pipelined chain is
byte-identical to the sequential oracle, and ``device`` dominates every
block's critical path; ``bubble`` prints the raw measurement payload.

``skew-smoke`` is the meshprof gate (``make skew-smoke``): two same-seed
4-rank ``--elastic`` cpu worlds must join the SAME (site, round, rank)
skew shape (the structural half of the report is deterministic; the
millisecond values are weather), and the report's ``max_skew_ms`` must
pass the ``collective_skew`` absolute budget.
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys

from .aggregate import merge_shards, mesh_health, read_shards, \
    render_mesh_prometheus
from .pipeline import pipeline_report, to_chrome_trace


def _shard_pipeline_records(shards: list[dict]) -> list[dict]:
    """Every shard's profiler-record tail, concatenated (records carry
    their rank, so cross-rank analysis needs no extra bookkeeping)."""
    records: list[dict] = []
    for shard in shards:
        records.extend(shard.get("pipeline") or [])
    return records


def cmd_merge(args) -> int:
    shards = read_shards(args.dir)
    code, health = mesh_health(args.dir, stall_s=args.stall_s,
                               shards=shards)
    view = merge_shards(shards)
    if args.prometheus:
        sys.stdout.write(render_mesh_prometheus(view, health))
    else:
        print(json.dumps({"event": "meshwatch_merge",
                          "dir": str(args.dir),
                          "health": health, "view": view},
                         sort_keys=True, default=str))
    if args.check and code != 200:
        return 1
    return 0


def cmd_report(args) -> int:
    if args.dir:
        records = _shard_pipeline_records(read_shards(args.dir))
    else:
        from .pipeline import profiler
        records = profiler().records()
    report = pipeline_report(records)
    out = {"event": "meshwatch_report",
           "source": str(args.dir) if args.dir else "in-process",
           "pipeline": report}
    if args.trace:
        trace = to_chrome_trace(records)
        pathlib.Path(args.trace).write_text(
            json.dumps(trace, sort_keys=True))
        out["trace"] = {"path": str(args.trace),
                        "events": len(trace["traceEvents"])}
    print(json.dumps(out, sort_keys=True))
    return 0


def cmd_watch(args) -> int:
    if args.once:
        code, payload = mesh_health(args.dir, stall_s=args.stall_s)
        print(json.dumps(payload, sort_keys=True))
        return 0 if code == 200 else 1
    from .server import MeshServer

    srv = MeshServer(args.dir, port=args.port, host=args.host,
                     stall_s=args.stall_s)
    port = srv.start()
    print(json.dumps({"event": "meshwatch_watch", "dir": str(args.dir),
                      "host": args.host, "port": port,
                      "endpoints": ["/healthz", "/metrics", "/ranks"]}),
          flush=True)
    try:
        import threading
        threading.Event().wait()            # serve until interrupted
    except KeyboardInterrupt:
        pass
    finally:
        srv.close()
    return 0


# ---- smoke ----------------------------------------------------------------


def _spawn_rank(rank: int, world: int, obs_dir: str, difficulty: int,
                blocks: int, extra: tuple = ()):
    import os
    import subprocess

    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "MPIBT_MESH_RANK": str(rank),
           "MPIBT_MESH_WORLD": str(world),
           "MPIBT_MESH_OBS_INTERVAL": "0.2"}
    argv = [sys.executable, "-m", "mpi_blockchain_tpu", "mine",
            "--backend", "cpu", "--difficulty", str(difficulty),
            "--blocks", str(blocks), "--mesh-obs", obs_dir,
            *extra]
    return subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def cmd_smoke(args) -> int:
    """The make meshwatch-smoke gate: 4-rank world, one SIGKILL'd."""
    import signal
    import tempfile
    import time

    from .shard import shard_path

    world, victim = 4, 2
    with tempfile.TemporaryDirectory() as tmp:
        obs = str(pathlib.Path(tmp) / "mesh")
        survivors = [_spawn_rank(r, world, obs, difficulty=10, blocks=20)
                     for r in range(world) if r != victim]
        # The victim mines a long chain so it is still sweeping when the
        # signal lands — a real mid-run death, not a post-exit one.
        victim_proc = _spawn_rank(victim, world, obs, difficulty=20,
                                  blocks=4000)
        try:
            deadline = time.monotonic() + 60
            vpath = shard_path(obs, victim)
            while time.monotonic() < deadline:
                shards = {s["rank"]: s for s in read_shards(obs)}
                beats = shards.get(victim, {}).get("heartbeats", {})
                # Kill only once the victim's shard PROVES it was mining
                # (a heartbeat in flight) — the mid-run death the stale
                # detection exists for, not a pre-start one.
                if vpath.exists() and any("miner_heartbeat" in k
                                          for k in beats):
                    break
                time.sleep(0.1)
            else:
                print("meshwatch-smoke: victim never heartbeat",
                      file=sys.stderr)
                return 1
            victim_proc.send_signal(signal.SIGKILL)
            victim_proc.wait(timeout=30)
            for p in survivors:
                out, err = p.communicate(timeout=120)
                if p.returncode != 0:
                    print(f"meshwatch-smoke: survivor rank failed "
                          f"rc={p.returncode}: {err[-800:]}",
                          file=sys.stderr)
                    return 1
        finally:
            for p in survivors + [victim_proc]:
                if p.poll() is None:
                    p.kill()
                    p.wait()
        time.sleep(0.6)    # let the victim's shard age past the budget
        shards = read_shards(obs)
        view = merge_shards(shards)
        code, health = mesh_health(obs, stall_s=0.5, shards=shards)

        # 1. counters sum: merged hashes_tried_total == sum of per-rank.
        hashed = [v for k, v in view["counters"].items()
                  if v["name"] == "hashes_tried_total"]
        if not hashed or any(
                v["total"] != sum(v["by_rank"].values()) for v in hashed):
            print(f"meshwatch-smoke: counter sum broken: {hashed}",
                  file=sys.stderr)
            return 1
        rank_set = {r for v in hashed for r in v["by_rank"]}
        if not {"0", "1", "3"} <= rank_set:
            print(f"meshwatch-smoke: survivor counters missing: "
                  f"{sorted(rank_set)}", file=sys.stderr)
            return 1

        # 2. the killed rank — and ONLY it — reads stale; survivors
        #    finished (final shards are not stale).
        if code != 503 or health["stale_ranks"] != [victim]:
            print(f"meshwatch-smoke: expected stale rank [{victim}], "
                  f"got {health['stale_ranks']} (code {code})",
                  file=sys.stderr)
            return 1
        finished = [r for r, v in health["ranks"].items()
                    if v["status"] == "finished"]
        if sorted(int(r) for r in finished) != [0, 1, 3]:
            print(f"meshwatch-smoke: survivors not finished: {finished}",
                  file=sys.stderr)
            return 1

        # 3. per-rank heartbeats individually visible in the merged view.
        beats = {r for r, b in view["heartbeats"].items()
                 if any("miner_heartbeat" in k for k in b)}
        if not {"0", "1", "2", "3"} <= beats:
            print(f"meshwatch-smoke: heartbeats missing: {sorted(beats)}",
                  file=sys.stderr)
            return 1

        # 4. the pipeline report renders with real dispatch segments.
        records = _shard_pipeline_records(shards)
        report = pipeline_report(records)
        if not report["dispatch_count"] or report["bubble_fraction"] is None:
            print(f"meshwatch-smoke: empty pipeline report: {report}",
                  file=sys.stderr)
            return 1
        trace = to_chrome_trace(records)
        pids = {e["pid"] for e in trace["traceEvents"]
                if e["ph"] in ("X", "b")}
        if len(pids) < 2:
            print(f"meshwatch-smoke: trace rows missing: {sorted(pids)}",
                  file=sys.stderr)
            return 1

    print(json.dumps({
        "event": "meshwatch_smoke", "ok": True,
        "ranks": sorted(int(r) for r in rank_set),
        "stale_ranks": health["stale_ranks"],
        "hashes_total": sum(v["total"] for v in hashed),
        "pipeline_dispatches": report["dispatch_count"],
        "bubble_fraction": report["bubble_fraction"],
    }, sort_keys=True))
    return 0


def cmd_bubble(args) -> int:
    """Measure the pipeline_bubble bench payload (before/after
    bubble_fraction of the fixed-seed instrumented mine) and print it —
    `perfwatch record --section pipeline_bubble` appends it to
    PERF_HISTORY.jsonl (the measure -> gate -> record shape)."""
    import logging

    from .bubble import measure_pipeline_bubble

    # The audit mines through the real checkpoint seam: its
    # block_mined/checkpoint_saved log lines are noise on a
    # measurement's stdout.
    logging.getLogger("mpi_blockchain_tpu").setLevel(logging.WARNING)
    payload = measure_pipeline_bubble()
    print(json.dumps({"event": "pipeline_bubble", **payload},
                     sort_keys=True))
    return 0


def cmd_pipeline_smoke(args) -> int:
    """The make pipeline-smoke gate (ROADMAP item 1 acceptance):

    1. the fixed-seed instrumented mine's PIPELINED ``bubble_fraction``
       passes the SECTION_BOUNDS absolute budget (<= 0.15), judged
       through the perfwatch detector like every bounded section
       (best-of-<=3: a real regression cannot produce a clean read, a
       scheduler-weather spike cannot produce three dirty ones);
    2. the pipelined chain is byte-identical to the sequential oracle's
       (``chain_identical`` — the determinism half of the acceptance);
    3. ``device`` is the dominant per-block critical-path stage on
       every mined block of the pipelined leg (the blocktrace form:
       host work hides behind the in-flight dispatch).
    """
    import logging

    from ..perfwatch.detector import check_candidate
    from ..perfwatch.history import DEFAULT_HISTORY_NAME, HistoryStore
    from .bubble import measure_pipeline_bubble

    logging.getLogger("mpi_blockchain_tpu").setLevel(logging.WARNING)
    repo_root = pathlib.Path(__file__).resolve().parent.parent.parent
    store = HistoryStore(repo_root / DEFAULT_HISTORY_NAME)
    for attempt in range(3):
        payload = measure_pipeline_bubble()
        finding = check_candidate(store, "pipeline_bubble", payload)
        if not payload["chain_identical"]:
            # Determinism is not weather: one broken chain fails the
            # gate outright, no retry.
            print(f"pipeline-smoke: pipelined chain diverged from the "
                  f"sequential oracle: {payload}", file=sys.stderr)
            return 1
        ok = (finding.verdict != "regression"
              and payload["device_dominant_blocks"] == payload["blocks"])
        if ok:
            break
        print(f"pipeline-smoke: read {attempt + 1} dirty "
              f"(bubble {payload['bubble_fraction']}, device-dominant "
              f"{payload['device_dominant_blocks']}/{payload['blocks']})",
              file=sys.stderr)
    if finding.verdict == "regression":
        print(f"pipeline-smoke: bubble over budget: {finding.render()}",
              file=sys.stderr)
        return 1
    if payload["device_dominant_blocks"] != payload["blocks"]:
        print(f"pipeline-smoke: device not dominant on every block "
              f"({payload['device_dominant_blocks']}/"
              f"{payload['blocks']})", file=sys.stderr)
        return 1
    print(json.dumps({
        "event": "pipeline_smoke", "ok": True,
        "bubble_fraction": payload["bubble_fraction"],
        "bubble_fraction_sequential":
            payload["bubble_fraction_sequential"],
        "host_overlapped_fraction": payload["host_overlapped_fraction"],
        "device_dominant_blocks": payload["device_dominant_blocks"],
        "blocks": payload["blocks"],
        "verdict": finding.verdict,
    }, sort_keys=True))
    return 0


def _skew_world(world: int, blocks: int, difficulty: int) -> list[dict]:
    """One same-seed ``--elastic`` cpu world: every rank steps the same
    heights in lockstep (the ``block.step`` skew spans), mines its
    stripe, writes its shard, exits 0. Returns the final shard set."""
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        obs = str(pathlib.Path(tmp) / "mesh")
        procs = [_spawn_rank(r, world, obs, difficulty=difficulty,
                             blocks=blocks, extra=("--elastic",))
                 for r in range(world)]
        try:
            for p in procs:
                out, err = p.communicate(timeout=180)
                if p.returncode != 0:
                    raise RuntimeError(
                        f"skew-smoke rank failed rc={p.returncode}: "
                        f"{err[-800:]}")
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
        return read_shards(obs)


def cmd_skew_smoke(args) -> int:
    """The make skew-smoke gate (meshprof):

    1. **determinism** — two same-seed 4-rank elastic cpu worlds
       produce mesh-skew reports with the byte-identical STRUCTURAL
       shape (world, per-site rounds x ranks: the (site, round) join is
       deterministic; the millisecond values are scheduler weather and
       deliberately excluded), and re-analyzing one shard set twice is
       byte-identical (``analyze_skew`` is a pure function). A
       determinism failure fails outright — never retried;
    2. **bound** — the report's ``max_skew_ms`` passes the
       ``collective_skew`` SECTION_BOUNDS budget through the perfwatch
       detector, best-of-<=3 (clock offsets are normalized out, so a
       failure means a rank stalled SECONDS inside the lockstep step,
       not that the processes started staggered).
    """
    import json as _json

    from ..meshprof.analyzer import analyze_skew, skew_shape
    from ..perfwatch.detector import check_candidate
    from ..perfwatch.history import DEFAULT_HISTORY_NAME, HistoryStore

    world, blocks, difficulty = 4, 8, 8
    repo_root = pathlib.Path(__file__).resolve().parent.parent.parent
    store = HistoryStore(repo_root / DEFAULT_HISTORY_NAME)
    try:
        shard_runs = [_skew_world(world, blocks, difficulty)
                      for _ in range(2)]
    except RuntimeError as e:
        print(f"skew-smoke: {e}", file=sys.stderr)
        return 1
    reports = [analyze_skew(s) for s in shard_runs]

    # 1a. pure re-analysis: same shards -> byte-identical report.
    if _json.dumps(analyze_skew(shard_runs[0]), sort_keys=True) != \
            _json.dumps(reports[0], sort_keys=True):
        print("skew-smoke: analyze_skew is not deterministic over the "
              "same shards", file=sys.stderr)
        return 1
    # 1b. cross-run structural determinism.
    shapes = [_json.dumps(skew_shape(r), sort_keys=True)
              for r in reports]
    if shapes[0] != shapes[1]:
        print(f"skew-smoke: same-seed runs joined different shapes:\n"
              f"  {shapes[0]}\n  {shapes[1]}", file=sys.stderr)
        return 1
    step = reports[0]["sites"].get("block.step")
    if (step is None or step["ranks"] != list(range(world))
            or step["rounds"] < blocks or reports[0]["straggler_rank"] < 0):
        print(f"skew-smoke: block.step did not join all {world} ranks "
              f"x {blocks} rounds: {skew_shape(reports[0])}",
              file=sys.stderr)
        return 1

    # 2. bound gate, best-of-<=3 (the first two runs count as reads).
    report = None
    for attempt, rep in enumerate(reports + [None]):
        if rep is None:
            try:
                rep = analyze_skew(_skew_world(world, blocks, difficulty))
            except RuntimeError as e:
                print(f"skew-smoke: {e}", file=sys.stderr)
                return 1
        report = rep
        payload = {"max_skew_ms": rep["max_skew_ms"],
                   "straggler_rank": rep["straggler_rank"],
                   "backend": "cpu", "mesh": f"elastic{world}",
                   "n_blocks": blocks, "world": world}
        finding = check_candidate(store, "collective_skew", payload)
        if finding.verdict != "regression":
            break
        print(f"skew-smoke: read {attempt + 1} dirty "
              f"(max_skew_ms {rep['max_skew_ms']})", file=sys.stderr)
    if finding.verdict == "regression":
        print(f"skew-smoke: skew over budget: {finding.render()}",
              file=sys.stderr)
        return 1
    step = report["sites"]["block.step"]
    print(json.dumps({
        "event": "skew_smoke", "ok": True,
        "world": world, "blocks": blocks,
        "site": "block.step",
        "rounds": step["rounds"],
        "straggler_rank": step["straggler_rank"],
        "straggler_lag_ms": step["straggler_lag_ms"],
        "max_skew_ms": report["max_skew_ms"],
        "idle_chip_ms": step["idle_chip_ms"],
        "verdict": finding.verdict,
    }, sort_keys=True))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_blockchain_tpu.meshwatch",
        description="per-rank telemetry shards, mesh aggregation, and "
                    "the dispatch pipeline profiler")
    sub = parser.add_subparsers(dest="command", required=True)

    p_mrg = sub.add_parser("merge", help="merge a shard directory into "
                                         "one mesh view + health")
    p_mrg.add_argument("--dir", required=True, metavar="DIR",
                       help="the --mesh-obs shard directory")
    p_mrg.add_argument("--stall-s", type=float, default=None,
                       help="rank staleness budget (default "
                            "MPIBT_MESH_STALL or 10)")
    p_mrg.add_argument("--prometheus", action="store_true",
                       help="emit the merged Prometheus text instead of "
                            "JSON")
    p_mrg.add_argument("--check", action="store_true",
                       help="exit 1 when any rank is stale/missing")
    p_mrg.set_defaults(fn=cmd_merge)

    p_rep = sub.add_parser("report", help="dispatch pipeline report "
                                          "(overlap/bubble) + Perfetto "
                                          "trace")
    p_rep.add_argument("--dir", default=None, metavar="DIR",
                       help="shard directory (default: the in-process "
                            "profiler)")
    p_rep.add_argument("--trace", default=None, metavar="PATH",
                       help="also write a wall-clock Chrome trace "
                            "(one track per rank and stage; view at "
                            "ui.perfetto.dev)")
    p_rep.set_defaults(fn=cmd_report)

    p_wch = sub.add_parser("watch", help="serve the mesh-aware /healthz "
                                         "/metrics /ranks")
    p_wch.add_argument("--dir", required=True, metavar="DIR")
    p_wch.add_argument("--port", type=int, default=0,
                       help="0 = ephemeral (announced on stdout)")
    p_wch.add_argument("--host", default="127.0.0.1")
    p_wch.add_argument("--stall-s", type=float, default=None)
    p_wch.add_argument("--once", action="store_true",
                       help="print the health JSON once and exit 0/1")
    p_wch.set_defaults(fn=cmd_watch)

    p_smk = sub.add_parser("smoke", help="the make meshwatch-smoke gate")
    p_smk.set_defaults(fn=cmd_smoke)

    p_bub = sub.add_parser("bubble", help="measure the pipeline_bubble "
                                          "bench payload (before/after "
                                          "bubble_fraction of the fixed-"
                                          "seed instrumented mine)")
    p_bub.set_defaults(fn=cmd_bubble)

    p_psm = sub.add_parser("pipeline-smoke",
                           help="the make pipeline-smoke gate: bubble "
                                "budget + oracle-identical chain + "
                                "device-dominant blocks")
    p_psm.set_defaults(fn=cmd_pipeline_smoke)

    p_ssm = sub.add_parser("skew-smoke",
                           help="the make skew-smoke gate: deterministic "
                                "4-rank mesh-skew join + the "
                                "collective_skew absolute budget")
    p_ssm.set_defaults(fn=cmd_skew_smoke)

    args = parser.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        sys.stderr.close()
        return 0


if __name__ == "__main__":
    sys.exit(main())
