"""Dispatch pipeline profiler: where does a mine dispatch's wall go?

The miner loop's hot cycle is dispatch-shaped: build inputs (*enqueue*),
wait on the device (*device*), then host work (*validate*, *append*,
*checkpoint*). The span summaries already say how much total time each
layer ate; what they cannot say is whether those times OVERLAPPED —
the fused loop dispatches batch i+1 before validating batch i, and the
async-dispatch roadmap item is judged on exactly that overlap. This
module records every dispatch as absolute-timestamped segments in a
bounded ring and derives:

* **device busy** — the union of every dispatch's ``device`` window
  (from dispatch issue to result materialization: the host-visible
  in-flight interval, the ``block_until_ready`` seam);
* **bubble fraction** — ``1 - device_busy / wall``: the share of the
  run's wall clock with NO dispatch in flight, i.e. the device idling
  behind host work. This is the number async pipelining must drive to
  ~0 (docs/perfwatch.md §Pipeline report);
* **overlap** — host-segment time that coincides with a device window:
  host work successfully hidden behind device compute. Reported
  per-dispatch (this dispatch's device window ∩ all host segments) and
  globally (``host_overlapped_fraction``).

Timestamps are ``time.time()``-anchored monotonic floats: monotonic
within a process (one anchor per profiler), wall-comparable across
ranks on the same host — which is what lets ``meshwatch report`` lay
every rank's dispatches on one Perfetto timeline (one process row per
rank, one thread row per stage). Cross-host timelines inherit the
hosts' clock skew; the forensics logical-time trace is the skew-free
alternative.

Records are plain dicts (JSON-able as-is) so shards can carry them
verbatim:

    {"dispatch": 3, "rank": 0, "meta": {...},
     "segments": [{"stage": "device", "t0": ..., "t1": ...}, ...]}
"""
from __future__ import annotations

import threading
import time

from ..blocktrace.context import current_trace
from ..telemetry import mesh_rank
from ..telemetry.registry import telemetry_disabled

#: Canonical stage names, in pipeline order. ``device`` is the in-flight
#: window; ``collective`` is a guarded rendezvous wait
#: (resilience/elastic.guarded_collective) — blocked-on-the-fabric time,
#: distinct from device compute; everything else is host work.
STAGES = ("enqueue", "device", "collective", "validate", "append",
          "checkpoint")
#: Stages that are NOT host work — device compute, and the collective
#: fabric wait (blocked-on-the-fabric is neither compute nor work) —
#: so the overlap report must not price them as host busy time (a
#: rendezvous spanning a device window would otherwise read as perfect
#: host/device pipelining). Every other stage, known or custom, counts
#: as host work.
NON_HOST_STAGES = ("device", "collective")

RING_SIZE = 4096


class DispatchRecord:
    """One dispatch's timed segments. Thread-compatible: the miner loop
    mutates a record from one thread at a time."""

    def __init__(self, profiler: "PipelineProfiler", dispatch_id: int,
                 rank: int, meta: dict):
        self._profiler = profiler
        self.record = {"dispatch": dispatch_id, "rank": rank,
                       "meta": meta, "segments": []}

    def add_segment(self, stage: str, t0: float, t1: float) -> None:
        seg = {"stage": str(stage), "t0": float(t0), "t1": float(t1)}
        # A segment recorded inside a blocktrace scope carries its exact
        # block identity — how a fused batch's per-block validate/append
        # segments stay individually attributable (blocktrace/
        # critical_path.py attribution rule 1).
        trace = current_trace()
        if trace is not None:
            seg["height"] = trace.height
            if trace.template:
                seg["template"] = trace.template
        self.record["segments"].append(seg)

    def segment(self, stage: str, chained: bool = True):
        """``with rec.segment("append"): ...`` times one segment.

        Chained: the segment opens at this record's previous segment's
        end (when that end is in the past), not at entry time — the
        few-microsecond host orchestration between stages belongs to
        the dispatch, and charging it to the *following* stage keeps
        the per-block gap accounting (blocktrace) structurally zero
        inside a dispatch instead of polluted by instrumentation seams.
        ``chained=False`` opts out for segments that are NOT the next
        stage of a sequential pipeline (a collective wait concurrent
        with other work must start at its true entry time, not be
        backdated to the previous stage boundary).
        """
        return _SegmentCtx(self, stage, chained=chained)

    def now(self) -> float:
        return self._profiler.now()


class _SegmentCtx:
    def __init__(self, rec: DispatchRecord, stage: str,
                 chained: bool = True):
        self._rec, self._stage = rec, stage
        self._chained = chained
        self._t0 = 0.0

    def __enter__(self):
        now = self._rec.now()
        if not self._chained:
            self._t0 = now
            return self
        segs = self._rec.record["segments"]
        last_end = max((s["t1"] for s in segs), default=None)
        self._t0 = (last_end if last_end is not None and last_end <= now
                    else now)
        return self

    def __exit__(self, *exc):
        self._rec.add_segment(self._stage, self._t0, self._rec.now())
        return False


class _NullDispatchRecord:
    """The do-nothing record ``dispatch()`` hands out while telemetry is
    off (MPIBT_TELEMETRY_OFF): segments vanish, ``now()`` stays real —
    callers use it for their own arithmetic (the fused drain's latency
    math), not just for segments."""

    record = {"dispatch": -1, "rank": 0, "meta": {}, "segments": []}

    def add_segment(self, stage: str, t0: float, t1: float) -> None:
        pass

    def segment(self, stage: str, chained: bool = True):
        return _NULL_SEGMENT_CTX

    def now(self) -> float:
        return time.time()


class _NullSegmentCtx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_RECORD = _NullDispatchRecord()
_NULL_SEGMENT_CTX = _NullSegmentCtx()


class PipelineProfiler:
    """Bounded ring of dispatch records + the timestamp anchor."""

    def __init__(self, capacity: int = RING_SIZE):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._records: list[DispatchRecord] = []
        self._next_id = 0
        # One anchor per profiler: time.time() sampled once against
        # perf_counter, so timestamps are monotonic (perf_counter) yet
        # wall-scaled (comparable across same-host ranks).
        self._anchor = time.time() - time.perf_counter()

    def now(self) -> float:
        return self._anchor + time.perf_counter()

    def dispatch(self, **meta) -> DispatchRecord:
        """Open a new dispatch record (ring-bounded). Inside a
        ``blocktrace.trace_block`` scope the meta's ``height`` defaults
        from the trace context when the call site passed none."""
        if telemetry_disabled():
            return _NULL_RECORD
        # Device-memory watermark sample at the dispatch boundary — the
        # one per-sweep host touchpoint the overhead self-audit already
        # prices. Throttled inside (a hot loop pays a clock read), and a
        # pure no-op on processes that never imported jax.
        from ..meshprof.memory import sample_memory

        sample_memory()
        meta = dict(meta)
        trace = current_trace()
        if trace is not None and meta.get("height") is None:
            meta["height"] = trace.height
        with self._lock:
            rec = DispatchRecord(self, self._next_id, mesh_rank(),
                                 meta)
            self._next_id += 1
            self._records.append(rec)
            if len(self._records) > self._capacity:
                del self._records[:len(self._records) - self._capacity]
            return rec

    def segment_on_last(self, stage: str, chained: bool = True):
        """Context manager timing a segment onto the newest record —
        the seam for work that happens outside the miner (the CLI's
        periodic checkpoint save). Opens a fresh record when none
        exists yet. ``chained`` as in ``DispatchRecord.segment``."""
        if telemetry_disabled():
            return _NULL_SEGMENT_CTX
        with self._lock:
            rec = self._records[-1] if self._records else None
        if rec is None:
            rec = self.dispatch(kind=stage)
        return rec.segment(stage, chained=chained)

    def records(self, tail: int | None = None) -> list[dict]:
        """Copies of the ringed records; ``tail`` bounds the copy to the
        newest n BEFORE copying — the shard flusher runs this every
        second, so it must not deep-copy 4096 records to keep 512."""
        with self._lock:
            recs = (self._records if tail is None
                    else self._records[-tail:])
            return [dict(r.record, segments=list(r.record["segments"]))
                    for r in recs]

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._next_id = 0


def strip_block_identity(record: dict, keep_k: int | None = None,
                         segments: bool = False) -> None:
    """Strips the block identity from a dispatch record that was
    abandoned — a fused recovery bail-out's in-flight batches, or the
    pipelined miner's discarded speculative dispatches. The heights an
    abandoned dispatch was stamped for WILL be mined by a live dispatch,
    and the critical-path join must never merge a dead dispatch's slices
    into the real block's waterfall: the work stays visible as
    ``unattributed``, never silently dropped, never double-counted
    (blocktrace attribution rules, docs/observability.md §blocktrace).

    ``keep_k``: the fused partial-batch case — the first ``keep_k``
    blocks of the batch WERE appended, so the meta keeps its height with
    ``k`` clamped to the appended prefix instead of losing identity
    entirely. ``segments=True`` additionally strips per-segment
    ``height``/``template`` stamps (the miner's speculative dispatches
    record their segments inside ``trace_block`` scopes; the fused
    bail-out keeps its exact drain-side stamps — that work is real).

    Everything is REBOUND to fresh dicts, never mutated in place: the
    meshwatch shard flusher thread shallow-copies records and may be
    json-serializing the old dicts concurrently (rebinding is atomic
    under the GIL; an in-place ``del`` would crash its iteration).
    Key-guarded so the telemetry-off shared null record is never
    written."""
    meta = record.get("meta") or {}
    if "height" in meta:
        meta = dict(meta)
        if keep_k:
            meta["k"] = keep_k
        else:
            del meta["height"]
        record["meta"] = meta
    if segments:
        segs = record.get("segments") or []
        if any("height" in s or "template" in s for s in segs):
            record["segments"] = [
                {k: v for k, v in s.items()
                 if k not in ("height", "template")} for s in segs]


# ---- the process-default profiler ----------------------------------------

_default = PipelineProfiler()


def profiler() -> PipelineProfiler:
    return _default


def reset_profiler() -> PipelineProfiler:
    """Fresh default profiler (test/CLI isolation)."""
    global _default
    _default = PipelineProfiler()
    return _default


# ---- interval math --------------------------------------------------------


def _union(intervals: list[tuple[float, float]]) -> list[tuple[float, float]]:
    """Merged, sorted, non-overlapping intervals."""
    merged: list[list[float]] = []
    for t0, t1 in sorted(i for i in intervals if i[1] > i[0]):
        if merged and t0 <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], t1)
        else:
            merged.append([t0, t1])
    return [(a, b) for a, b in merged]


def _length(union: list[tuple[float, float]]) -> float:
    return sum(b - a for a, b in union)


def _intersect(a: list[tuple[float, float]],
               b: list[tuple[float, float]]) -> float:
    """Total overlap length of two interval unions (two-pointer sweep)."""
    total, i, j = 0.0, 0, 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def _clip(union: list[tuple[float, float]],
          window: tuple[float, float]) -> float:
    return _intersect(union, [window])


# ---- the report -----------------------------------------------------------


def pipeline_report(records: list[dict] | None = None,
                    max_dispatches: int = 64) -> dict:
    """Overlap/bubble analysis of a record set (default: the process
    profiler's). Records spanning several ranks are analyzed PER RANK
    (each rank has its own device to keep busy) and summarized.

    Per rank: ``wall_s`` (first segment start → last end), per-stage
    totals, ``device_busy_s`` (union of device windows),
    ``bubble_fraction`` = 1 − device_busy/wall, ``overlap_s`` =
    |host ∩ device|, ``host_overlapped_fraction`` = overlap/host_busy.
    ``dispatches`` lists the newest ``max_dispatches`` with per-dispatch
    segment seconds and this dispatch's device-window overlap fraction.
    """
    if records is None:
        records = profiler().records()
    by_rank: dict[int, list[dict]] = {}
    for r in records:
        by_rank.setdefault(int(r.get("rank", 0)), []).append(r)

    ranks: dict[str, dict] = {}
    for rank in sorted(by_rank):
        recs = by_rank[rank]
        segs = [s for r in recs for s in r["segments"]]
        if not segs:
            continue
        t_lo = min(s["t0"] for s in segs)
        t_hi = max(s["t1"] for s in segs)
        wall = max(t_hi - t_lo, 1e-12)
        stage_totals = {st: 0.0 for st in STAGES}
        for s in segs:
            stage_totals.setdefault(s["stage"], 0.0)
            stage_totals[s["stage"]] += s["t1"] - s["t0"]
        device_u = _union([(s["t0"], s["t1"]) for s in segs
                           if s["stage"] == "device"])
        # Host busy = host WORK only: collective segments are fabric
        # waits (see NON_HOST_STAGES) — they must neither inflate
        # host_busy nor count as host/device overlap.
        host_u = _union([(s["t0"], s["t1"]) for s in segs
                         if s["stage"] not in NON_HOST_STAGES])
        device_busy = _length(device_u)
        host_busy = _length(host_u)
        overlap = _intersect(device_u, host_u)
        dispatches = []
        for r in recs[-max_dispatches:]:
            d_segs = {s["stage"]: round(s["t1"] - s["t0"], 6)
                      for s in r["segments"]}
            windows = [(s["t0"], s["t1"]) for s in r["segments"]
                       if s["stage"] == "device"]
            d_dev = _length(_union(windows))
            d_overlap = sum(_clip(host_u, w) for w in _union(windows))
            dispatches.append({
                "dispatch": r["dispatch"],
                "meta": r.get("meta", {}),
                "segments_s": d_segs,
                "device_s": round(d_dev, 6),
                "overlap_s": round(d_overlap, 6),
                "overlap_fraction": (round(d_overlap / d_dev, 4)
                                     if d_dev else 0.0),
            })
        ranks[str(rank)] = {
            "dispatch_count": len(recs),
            "wall_s": round(wall, 6),
            "stage_totals_s": {k: round(v, 6)
                               for k, v in stage_totals.items() if v},
            "device_busy_s": round(device_busy, 6),
            "host_busy_s": round(host_busy, 6),
            "bubble_fraction": round(1.0 - device_busy / wall, 4),
            "overlap_s": round(overlap, 6),
            "host_overlapped_fraction": (round(overlap / host_busy, 4)
                                         if host_busy else 0.0),
            "dispatches": dispatches,
        }
    if not ranks:
        return {"ranks": {}, "dispatch_count": 0, "bubble_fraction": None,
                "host_overlapped_fraction": None}
    n = len(ranks)
    return {
        "ranks": ranks,
        "dispatch_count": sum(v["dispatch_count"] for v in ranks.values()),
        # Mesh summary: mean over ranks (each rank's device is its own
        # resource; averaging answers "how idle is a typical chip").
        "bubble_fraction": round(
            sum(v["bubble_fraction"] for v in ranks.values()) / n, 4),
        "host_overlapped_fraction": round(
            sum(v["host_overlapped_fraction"] for v in ranks.values()) / n,
            4),
    }


# ---- Perfetto export ------------------------------------------------------


def to_chrome_trace(records: list[dict] | None = None) -> dict:
    """Wall-clock Chrome trace-event JSON: one process row per rank, one
    thread row per pipeline stage (the forensics exporter's logical-time
    complement — this one answers "how long", that one answers "in what
    order").

    Host stages render as complete slices (``ph: X``) — they are
    sequential on the host thread, so they nest trivially. Device
    windows render as ASYNC slices (``ph: b``/``e``, id = dispatch):
    pipelined dispatches overlap partially on the device track, and the
    trace format only allows sync slices that nest — X events here
    would make the viewer clamp/drop exactly the overlap this export
    exists to show.
    """
    if records is None:
        records = profiler().records()
    segs = [(int(r.get("rank", 0)), r["dispatch"], s)
            for r in records for s in r["segments"]]
    events: list[dict] = []
    if not segs:
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "metadata": {"clock": "wall",
                             "source": "mpi_blockchain_tpu.meshwatch"}}
    epoch = min(s["t0"] for _, _, s in segs)
    ranks = sorted({rank for rank, _, _ in segs})
    for rank in ranks:
        events.append({"ph": "M", "name": "process_name", "pid": rank,
                       "tid": 0, "args": {"name": f"rank {rank}"}})
        for tid, stage in enumerate(STAGES):
            events.append({"ph": "M", "name": "thread_name", "pid": rank,
                           "tid": tid, "args": {"name": stage}})
    tids = {stage: i for i, stage in enumerate(STAGES)}
    for rank, dispatch, s in segs:
        stage = s["stage"]
        ts = round((s["t0"] - epoch) * 1e6, 3)
        dur = round(max(s["t1"] - s["t0"], 1e-7) * 1e6, 3)
        tid = tids.get(stage, len(STAGES))
        if stage == "device":
            # Async events pair by (cat, id) GLOBALLY — not per pid — so
            # the id must be rank-unique or rank 0's begin would pair
            # with rank 1's end (dispatch ids restart at 0 per rank).
            common = {"cat": "pipeline", "name": "device", "pid": rank,
                      "tid": tid, "id": f"r{rank}d{dispatch}",
                      "args": {"dispatch": dispatch}}
            events.append({**common, "ph": "b", "ts": ts})
            events.append({**common, "ph": "e", "ts": round(ts + dur, 3)})
        else:
            events.append({
                "ph": "X", "cat": "pipeline", "name": stage,
                "pid": rank, "tid": tid, "ts": ts, "dur": dur,
                "args": {"dispatch": dispatch},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": {"clock": "wall", "epoch_unix_s": epoch,
                         "source": "mpi_blockchain_tpu.meshwatch"}}
