"""The pipeline-bubble audit: before/after ``bubble_fraction`` from a
fixed-seed instrumented mine (the ``pipeline_bubble`` bench section).

Runs the SAME deterministic cpu-world mine twice — once through the
sequential oracle (``Miner(pipeline=False)``), once through the async
double-buffered pipeline — with per-block checkpoint writes through the
real ``on_block`` seam (the host work the pipeline exists to hide), then
prices both legs with meshwatch's ``pipeline_report``:

* ``bubble_fraction_sequential`` — the BEFORE number: every checkpoint
  write, winner validation and template build serializes with the
  device, so the device idles behind them;
* ``bubble_fraction`` — the AFTER number, the section's headline: the
  same host work overlapped by the speculatively-dispatched next sweep.
  ``detector.SECTION_BOUNDS`` caps it at 0.15 (ROADMAP item 1).

The audit also proves the two legs mined byte-identical chains
(``chain_identical`` — the determinism contract is part of the payload,
not a separate trust), and reports whether the ``device`` stage is the
dominant per-block critical-path stage on every block of the pipelined
leg (``device_dominant_blocks`` vs ``blocks`` — the blocktrace form of
the same acceptance). ``make pipeline-smoke`` gates all three.

The mine is seed-fixed: winner nonces are a pure function of
(payloads, difficulty), so the work per block is identical run to run —
only scheduler weather moves the fractions, which is why the smoke uses
the best-of-N shape the other absolute-bound gates use.
"""
from __future__ import annotations

import pathlib
import tempfile

#: The fixed audit config: difficulty and payload prefix chosen so
#: every block's deterministic winner nonce buys a sweep comfortably
#: above the per-block host work it must hide (with the "sweep" prefix
#: at difficulty 15 the smallest winner across the 12 heights is 7793
#: nonces — several ms of C++ search on any box), blocks enough to
#: average scheduler weather. Winner nonces are a pure function of
#: (prefix, difficulty), so these numbers cannot drift per machine.
AUDIT_DIFFICULTY = 15
AUDIT_BLOCKS = 12
AUDIT_PREFIX = "sweep"


def _audit_workdir() -> tempfile.TemporaryDirectory:
    """A memory-backed workdir when the box has one: the audit's
    checkpoint writes are REAL (atomic tmp+fsync+rename through
    save_chain) but the number under test is the overlap, and disk
    fsync weather on a shared CI box is 10-300 ms noise that would
    drown it."""
    for base in ("/dev/shm", None):
        try:
            return tempfile.TemporaryDirectory(dir=base)
        except OSError:
            continue
    return tempfile.TemporaryDirectory()


def _mine_leg(pipeline: bool, difficulty: int, blocks: int,
              workdir: pathlib.Path) -> dict:
    """One instrumented mine against a fresh profiler; returns the leg's
    pipeline report + chain hashes + per-block critical-path split."""
    from ..blocktrace.critical_path import critical_path_report
    from ..config import MinerConfig
    from ..models.miner import Miner
    from ..utils.checkpoint import save_chain
    from .pipeline import pipeline_report, profiler, reset_profiler

    cfg = MinerConfig(difficulty_bits=difficulty, n_blocks=blocks,
                      backend="cpu", data_prefix=AUDIT_PREFIX)
    ckpt = workdir / ("chain-pipelined.ckpt" if pipeline
                      else "chain-sequential.ckpt")
    miner = Miner(cfg, pipeline=pipeline, log_fn=lambda rec: None)
    reset_profiler()

    def on_block(rec) -> None:
        # The real checkpoint seam, every block: the serialized host
        # work whose overlap (or not) IS the measurement.
        with profiler().segment_on_last("checkpoint"):
            save_chain(miner.node, ckpt, cfg)

    miner.mine_chain(on_block=on_block)
    records = profiler().records()
    report = pipeline_report(records)
    crit = critical_path_report(records)
    dominant = 0
    for h in crit["heights"]:
        stages = crit["blocks"][str(h)]["stages_ms"]
        if stages and max(stages, key=stages.get) == "device":
            dominant += 1
    return {
        "bubble_fraction": report["bubble_fraction"],
        "host_overlapped_fraction": report["host_overlapped_fraction"],
        "dispatches": report["dispatch_count"],
        "heights": crit["heights"],
        "device_dominant_blocks": dominant,
        "chain": miner.chain_hashes(),
    }


def measure_pipeline_bubble(difficulty: int = AUDIT_DIFFICULTY,
                            blocks: int = AUDIT_BLOCKS) -> dict:
    """The ``pipeline_bubble`` bench payload (module docstring)."""
    with _audit_workdir() as tmp:
        workdir = pathlib.Path(tmp)
        seq = _mine_leg(False, difficulty, blocks, workdir)
        pip = _mine_leg(True, difficulty, blocks, workdir)
    return {
        "backend": "cpu",
        "difficulty_bits": difficulty,
        "n_blocks": blocks,
        # The section headline, bounded by SECTION_BOUNDS (<= 0.15).
        "bubble_fraction": pip["bubble_fraction"],
        "host_overlapped_fraction": pip["host_overlapped_fraction"],
        # The BEFORE leg: same seed, sequential oracle.
        "bubble_fraction_sequential": seq["bubble_fraction"],
        "host_overlapped_fraction_sequential":
            seq["host_overlapped_fraction"],
        "dispatches": pip["dispatches"],
        "blocks": len(pip["heights"]),
        "device_dominant_blocks": pip["device_dominant_blocks"],
        "chain_identical": seq["chain"] == pip["chain"],
    }
