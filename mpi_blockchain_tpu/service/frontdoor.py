"""The overload-safe template-service front door.

Three cooperating pieces, each independently testable:

* ``TemplateFeed`` — turns the mempool into per-height payload
  templates through the miner's ``payload_for`` seam. Rebuilds run
  OFF the mine loop (HTTP handler threads after an accepted submit,
  plus the block-mined hook) and swap the current template atomically;
  the pipelined driver's block-boundary re-validation
  (``Miner._speculation_valid``) then discards any speculation built on
  the stale template exactly like a re-stripe. An idle feed (no pending
  txs) reproduces ``config.payload`` byte-for-byte, so a serviceless
  mine and a quiet served mine build identical chains.
* ``ServiceState`` — the admission-control brain: queue-depth and
  miner-heartbeat gates, per-request deadlines
  (``MPIBT_SERVICE_DEADLINE``), the ``service.submit`` fault site under
  the service retry budget, typed shed accounting
  (``service_shed_total{reason}``), and the degradation stamp
  (``ResilientBackend`` step-downs and open ``stale_rank`` incidents
  mark responses ``degraded`` while reads keep serving).
* ``ServiceServer`` — the HTTP skin: perfwatch's hardened
  ``MetricsServer`` lifecycle (daemon serve thread, idempotent close,
  ``_send`` that survives vanished clients) plus ``POST /submit`` and
  ``GET /tx_status`` / ``/chain`` / ``/template`` on top of the
  inherited ``/metrics`` / ``/healthz`` / ``/events``.

Every failure mode has a typed answer: sheds carry a ``shed_reason``,
injected hangs are bounded by ``FaultTimeout`` and the retry budget
(the door answers late, never never), and a lost receipt (``partial``
fault) is recoverable through ``tx_status`` — the serve smoke's
accepted-then-lost conservation check leans on exactly that.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.parse
import zlib

from ..perfwatch.server import MetricsServer, _Handler
from ..resilience import RetryExhausted, injection
from ..resilience.policy import call_with_retry
from ..telemetry import counter, default_registry, heartbeat_snapshot
from ..telemetry.events import emit_event, env_number
from .mempool import Mempool, txid_of

#: Per-request deadline budget (seconds): admission must finish inside
#: it or the work is dropped BEFORE it reaches the miner; each admitted
#: tx also carries it as the template-entry deadline.
ENV_DEADLINE = "MPIBT_SERVICE_DEADLINE"
DEFAULT_DEADLINE_S = 5.0
#: Miner-heartbeat age (seconds) past which the door answers 503: a
#: wedged miner must shed, not queue unboundedly.
ENV_STALL = "MPIBT_SERVICE_STALL"
DEFAULT_STALL_S = 30.0
#: Concurrent in-flight submit bound — the queue-depth breach of the
#: admission contract.
ENV_INFLIGHT = "MPIBT_SERVICE_MAX_INFLIGHT"
DEFAULT_INFLIGHT = 32
#: Most txs a single template embeds.
ENV_TEMPLATE_TXS = "MPIBT_TEMPLATE_TXS"
DEFAULT_TEMPLATE_TXS = 16

_MAX_BODY = 1 << 20   # submit bodies past 1 MiB shed typed, never read


def template_payload(config, height: int, txids) -> bytes:
    """The deterministic template encoding: the serviceless base
    payload, then the embedded txids in template order. With no txs it
    IS ``config.payload(height)`` — the byte-identity anchor the serve
    smoke's sequential-oracle comparison builds on."""
    base = f"{config.data_prefix}:{height}"
    if not txids:
        return base.encode()
    return "|".join((base, *txids)).encode()


def _checksum(txids) -> int:
    return zlib.crc32("|".join(txids).encode())


class TemplateFeed:
    """Mempool -> per-height payload templates, rebuilt off the mine
    loop and self-validated at every block boundary."""

    def __init__(self, mempool: Mempool, config, max_txs: int | None = None,
                 clock=time.monotonic):
        self.mempool = mempool
        self.config = config
        self.max_txs = int(max_txs if max_txs is not None
                           else env_number(ENV_TEMPLATE_TXS,
                                           DEFAULT_TEMPLATE_TXS,
                                           cast=int, minimum=1))
        self._clock = clock
        self._lock = threading.Lock()
        self._txids: tuple[str, ...] = ()
        self._check = _checksum(())
        self._prev: tuple[str, ...] = ()
        self._seq = 0
        self.rebuilds_total = 0
        self.rebuild_failures = 0
        self.corrupt_discards = 0
        #: height -> the payload the LAST boundary read returned — by
        #: construction the bytes the mined block embeds (the pipelined
        #: driver re-reads at every boundary and discards stale
        #: speculation), so the serve smoke can replay the exact chain
        #: through a sequential oracle.
        self.history: dict[int, bytes] = {}
        self._txids_at: dict[int, tuple[str, ...]] = {}

    # ---- rebuild (off the mine loop) -------------------------------------

    def rebuild(self) -> bool:
        """Builds a fresh template from the pool under the
        ``service.rebuild`` fault site + service retry budget. On
        budget exhaustion the PREVIOUS template keeps serving —
        degrade, never drop. Returns whether a fresh build landed."""
        def _build():
            fault = injection.check("service.rebuild")
            txs = self.mempool.take(self.max_txs, self._clock())
            txids = tuple(t.txid for t in txs)
            if fault is not None and fault.kind == "partial":
                # only a prefix of the eligible txs makes the template;
                # the rest stay pending — delayed, never lost.
                txids = txids[:len(txids) // 2]
            chk = _checksum(txids)
            if fault is not None and fault.kind == "corrupt":
                # damage the rebuilt template; the boundary
                # self-validation below discards it like a stale
                # speculation and reverts to the last good template.
                chk ^= 0x5A5A
            return txids, chk
        try:
            txids, chk = call_with_retry(_build, site="service.rebuild")
        except RetryExhausted:
            with self._lock:
                self.rebuild_failures += 1
            counter("service_rebuild_failed_total").inc()
            emit_event({"event": "template_rebuild_failed"})
            return False
        with self._lock:
            if (txids, chk) == (self._txids, self._check):
                return True   # unchanged: no seq bump, no restripe churn
            if self._check == _checksum(self._txids):
                self._prev = self._txids    # last KNOWN-GOOD template
            self._txids, self._check = txids, chk
            self._seq += 1
            self.rebuilds_total += 1
        counter("service_template_rebuilds_total").inc()
        return True

    # ---- the miner-facing seam (block boundary) --------------------------

    def payload_for(self, height: int) -> bytes:
        """Bound onto the miner as its ``payload_for`` hook. Validates
        the current template's checksum first — a corrupt rebuild is
        discarded HERE, at the block boundary, before any candidate
        embeds it."""
        damaged = False
        with self._lock:
            if self._check != _checksum(self._txids):
                self._txids = self._prev
                self._check = _checksum(self._prev)
                self._seq += 1
                self.corrupt_discards += 1
                damaged = True
            txids = self._txids
        if damaged:
            counter("service_template_corrupt_total").inc()
            emit_event({"event": "template_corrupt_discarded",
                        "height": height})
        data = template_payload(self.config, height, txids)
        with self._lock:
            self.history[height] = data
            self._txids_at[height] = txids
            if len(self.history) > 256:    # bounded replay window
                drop = min(self.history)
                self.history.pop(drop, None)
                self._txids_at.pop(drop, None)
        return data

    def note_block(self, height: int) -> None:
        """The block-mined hook: record inclusion truth for the txs the
        landed block embeds, then rebuild so the NEXT template drops
        them (the rebuild is what turns any in-flight speculation into
        a restripe discard at its boundary)."""
        with self._lock:
            txids = self._txids_at.get(height, ())
        if txids:
            self.mempool.mark_included(txids, height)
        self.rebuild()

    def current(self) -> tuple[tuple[str, ...], int]:
        with self._lock:
            return self._txids, self._seq

    def stats(self) -> dict:
        with self._lock:
            return {"seq": self._seq, "txs": len(self._txids),
                    "rebuilds": self.rebuilds_total,
                    "failures": self.rebuild_failures,
                    "corrupt_discards": self.corrupt_discards}


class ServiceState:
    """Admission control + typed shedding + degradation stamping over
    one miner. Binds/unbinds the miner's template seam explicitly."""

    def __init__(self, miner, mempool: Mempool | None = None,
                 feed: TemplateFeed | None = None, *,
                 deadline_s: float | None = None,
                 stall_s: float | None = None,
                 max_inflight: int | None = None,
                 clock=time.monotonic):
        self.miner = miner
        self.mempool = mempool if mempool is not None else Mempool()
        self.feed = (feed if feed is not None
                     else TemplateFeed(self.mempool, miner.config))
        self.deadline_s = float(
            deadline_s if deadline_s is not None
            else env_number(ENV_DEADLINE, DEFAULT_DEADLINE_S,
                            cast=float, minimum=0.001))
        self.stall_s = float(
            stall_s if stall_s is not None
            else env_number(ENV_STALL, DEFAULT_STALL_S,
                            cast=float, minimum=0.1))
        self.max_inflight = int(
            max_inflight if max_inflight is not None
            else env_number(ENV_INFLIGHT, DEFAULT_INFLIGHT,
                            cast=int, minimum=0))
        self._clock = clock
        self._started_at = clock()
        self._lock = threading.Lock()
        self._inflight = 0
        self.shed_totals: dict[str, int] = {}
        self._bound = False

    # ---- miner binding ---------------------------------------------------

    def bind(self) -> None:
        """Routes the miner's template seam through the feed and hooks
        block-mined for inclusion marking. Idempotent."""
        if self._bound:
            return
        miner = self.miner
        orig_block_mined = miner._block_mined

        def _block_mined(rec):
            orig_block_mined(rec)
            self.feed.note_block(rec.height)

        miner.payload_for = self.feed.payload_for
        miner._block_mined = _block_mined
        self.feed.rebuild()
        self._bound = True

    def unbind(self) -> None:
        if not self._bound:
            return
        self.miner.__dict__.pop("payload_for", None)
        self.miner.__dict__.pop("_block_mined", None)
        self._bound = False

    # ---- admission -------------------------------------------------------

    def accept_gate(self, now: float | None = None
                    ) -> tuple[bool, str | None]:
        """The backpressure coupling: the door only accepts while the
        miner demonstrably progresses. Heartbeat-age over the stall
        budget (or no heartbeat at all past the starting grace) flips
        the door to 503 ``miner_stalled``."""
        now = self._clock() if now is None else now
        beats = heartbeat_snapshot(default_registry())
        ages = [b["age_s"] for b in beats.values()
                if b.get("age_s") is not None]
        freshest = min(ages) if ages else None
        if freshest is None:
            uptime = now - self._started_at
            if uptime <= self.stall_s:
                return True, None     # starting grace
            return False, "miner_stalled"
        if freshest > self.stall_s:
            return False, "miner_stalled"
        return True, None

    def submit(self, payload: bytes, fee: int,
               deadline_s: float | None = None
               ) -> tuple[int, dict | None]:
        """One admission attempt: ``(http_code, body)``. ``body`` is
        ``None`` only for the ``partial`` fault kind — the tx IS
        admitted but its receipt is lost in flight; the client recovers
        through ``tx_status``."""
        t0 = self._clock()
        with self._lock:
            self._inflight += 1
            over = self._inflight > self.max_inflight
        try:
            if over:
                return self._shed(503, "queue_depth")
            ok, reason = self.accept_gate(t0)
            if not ok:
                return self._shed(503, reason)
            tid = txid_of(payload)
            try:
                fault = call_with_retry(
                    lambda: injection.check("service.submit", txid=tid),
                    site="service.submit")
            except RetryExhausted:
                # raise/hang kinds past the service retry budget: shed
                # typed — the request answers, the tx never entered.
                return self._shed(503, "retry_exhausted", txid=tid)
            if fault is not None and fault.kind == "corrupt":
                # integrity-damaged in flight: reject before the pool.
                return self._shed(400, "corrupt", txid=tid)
            budget = (self.deadline_s if deadline_s is None
                      else float(deadline_s))
            if self._clock() - t0 >= budget:
                # the request burned its deadline inside admission
                # (e.g. an injected hang): drop before the miner.
                return self._shed(503, "deadline", txid=tid)
            outcome, rec = self.mempool.submit(payload, fee,
                                               deadline_s=budget, now=t0)
            if outcome == "shed":
                return self._shed(429, "mempool_full", txid=tid)
            if outcome == "accepted":
                # the async rebuild: handler thread, never the miner's.
                self.feed.rebuild()
            body = dict(rec.public())
            body["result"] = outcome
            body["depth"] = self.mempool.depth()
            if fault is not None and fault.kind == "partial":
                return 200, None
            return 200, body
        finally:
            with self._lock:
                self._inflight -= 1

    def _shed(self, code: int, reason: str,
              txid: str | None = None) -> tuple[int, dict]:
        with self._lock:
            self.shed_totals[reason] = self.shed_totals.get(reason, 0) + 1
        counter("service_shed_total", reason=reason).inc()
        body = {"error": "shed", "shed_reason": reason,
                "retry_after_s": 0.05}
        if txid is not None:
            body["txid"] = txid
        return code, body

    # ---- reads (stay up while degraded) ----------------------------------

    def tx_status(self, txid: str) -> tuple[int, dict]:
        rec = self.mempool.status(txid)
        if rec is None:
            return 404, {"error": "unknown_txid", "txid": txid}
        return 200, rec.public()

    def chain_view(self, n: int = 16) -> dict:
        node = self.miner.node
        h = node.height
        lo = max(0, h - max(1, n) + 1)
        return {"height": h, "tip_hash": node.tip_hash.hex(),
                "blocks": [{"height": i,
                            "hash": node.block_hash(i).hex()}
                           for i in range(lo, h + 1)],
                **self.degraded_info()}

    def template_view(self) -> dict:
        txids, seq = self.feed.current()
        height = self.miner.node.height + 1
        data = template_payload(self.miner.config, height, txids)
        return {"height": height, "template_seq": seq,
                "tx_count": len(txids), "txids": list(txids),
                "payload_size": len(data), **self.degraded_info()}

    def degraded_info(self) -> dict:
        """The degradation stamp: a stepped-down ResilientBackend
        ladder or an open ``stale_rank`` incident (a rank evicted from
        the mesh) marks responses degraded; serving continues."""
        backend = getattr(self.miner, "backend", None)
        steps = list(getattr(backend, "degradations", None) or [])
        info: dict = {"degraded": bool(steps) or
                      bool(getattr(backend, "degraded", False))}
        if steps:
            info["degraded_to"] = steps[-1].get("to")
        from ..chainwatch.incident import open_incidents
        stale = [i for i in open_incidents()
                 if i.get("rule") == "stale_rank"]
        if stale:
            info["degraded"] = True
            info["stale_rank_incidents"] = len(stale)
        return info

    # ---- observability ---------------------------------------------------

    def stats(self) -> dict:
        """The additive ``service`` payload /healthz, meshwatch shards
        and incident bundles all carry."""
        ok, reason = self.accept_gate()
        with self._lock:
            shed = dict(self.shed_totals)
            inflight = self._inflight
        gate: dict = {"open": ok}
        if reason is not None:
            gate["reason"] = reason
        return {"mempool": self.mempool.snapshot(),
                "shed_total": shed,
                "accept_gate": gate,
                "inflight": inflight,
                "template": self.feed.stats(),
                "deadline_s": self.deadline_s,
                "degraded": self.degraded_info()["degraded"]}


class _ServiceHandler(_Handler):
    _GETS = ("/chain", "/events", "/healthz", "/metrics", "/template",
             "/tx_status")

    def do_GET(self) -> None:  # noqa: N802 (stdlib signature)
        parsed = urllib.parse.urlparse(self.path)
        state: ServiceState = self.server_ctx.state
        path = parsed.path
        if path == "/tx_status":
            q = urllib.parse.parse_qs(parsed.query)
            tid = (q.get("txid") or [""])[0]
            if not tid:
                self._json(400, {"error": "bad_request",
                                 "detail": "txid query param required"})
                return
            code, body = state.tx_status(tid)
            self._json(code, body)
        elif path == "/chain":
            q = urllib.parse.parse_qs(parsed.query)
            try:
                n = max(1, int((q.get("n") or ["16"])[0]))
            except ValueError:
                n = 16
            self._json(200, state.chain_view(n))
        elif path == "/template":
            self._json(200, state.template_view())
        elif path in ("/metrics", "/healthz", "/events"):
            super().do_GET()
        else:
            self._json(404, {"error": f"unknown path {path!r}",
                             "endpoints": list(self._GETS)})

    def do_POST(self) -> None:  # noqa: N802 (stdlib signature)
        parsed = urllib.parse.urlparse(self.path)
        state: ServiceState = self.server_ctx.state
        if parsed.path != "/submit":
            self._json(404, {"error": f"unknown path {parsed.path!r}",
                             "endpoints": ["/submit"]})
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = 0
        if length > _MAX_BODY:
            code, body = state._shed(413, "body_too_large")
            self._json(code, body)
            return
        raw = self.rfile.read(length) if length else b""
        try:
            doc = json.loads(raw.decode() or "{}")
            payload = doc["payload"].encode()
            fee = int(doc["fee"])
        except (json.JSONDecodeError, UnicodeDecodeError, KeyError,
                TypeError, ValueError, AttributeError) as e:
            self._json(400, {"error": "bad_request",
                             "detail": f"{type(e).__name__}: {e}"})
            return
        deadline_s = doc.get("deadline_s")
        try:
            deadline_s = None if deadline_s is None else float(deadline_s)
        except (TypeError, ValueError):
            deadline_s = None
        code, body = state.submit(payload, fee, deadline_s)
        if body is None:
            # partial fault: the receipt is lost in flight — an empty
            # 200 the client must resolve through /tx_status.
            self._send(200, "", "application/json")
            return
        self._json(code, body)

    def _json(self, code: int, payload: dict) -> None:
        self._send(code, json.dumps(payload, sort_keys=True) + "\n",
                   "application/json")


class ServiceServer(MetricsServer):
    """The HTTP front door; lifecycle inherited from MetricsServer."""

    handler_cls = _ServiceHandler
    register_active = False   # its own door, not the metrics announce

    def __init__(self, state: ServiceState, port: int = 0,
                 host: str = "127.0.0.1", stall_s: float | None = None):
        super().__init__(port=port, host=host, stall_s=stall_s)
        self.state = state

    def url(self, path: str = "/template") -> str:
        return super().url(path)
