"""Bounded fee-ordered mempool: two lazy heaps + a txid index.

The admission contract is the whole design:

* **bounded** — at most ``cap`` (``MPIBT_MEMPOOL_CAP``, default 512)
  PENDING transactions ever exist. A submit against a full pool either
  displaces the cheapest pending tx (strictly lower fee than the
  newcomer — the eviction is itself an ordered, observable outcome:
  status ``evicted``, counted) or is shed with the typed reason
  ``mempool_full``. Never an unbounded queue.
* **fee-ordered** — template building drains by ``(-fee, seq)``: highest
  fee first, admission order breaking ties, so two same-seed load runs
  produce the same template sequence (no wall-clock in the order key).
* **status-queryable** — every admitted txid stays answerable through
  ``status()`` after it resolves (included / evicted / expired), in a
  bounded resolved ring (``4*cap`` + change), so "accepted then lost"
  is structurally impossible to hide: the serve smoke queries every
  accepted txid back.

Deadlines are enforced here, at ``take()`` — the only gate between the
pool and the miner — so expired work is dropped BEFORE it reaches a
template, never clawed back after (a tx already embedded in a dispatched
template stays mined; ``mark_included`` then records the truth even if
the deadline lapsed while the block was in flight).

Locking: one mutex, short critical sections, no I/O under it (LCK/THR
discipline); heap entries are lazily invalidated by status so eviction
and expiry never rebuild a heap.
"""
from __future__ import annotations

import dataclasses
import heapq
import threading
import time

from ..core import sha256d
from ..telemetry import counter
from ..telemetry.events import env_number

#: Pending-capacity knob; 0 is legal (every submit sheds — the
#: admission-control test fixture).
ENV_CAP = "MPIBT_MEMPOOL_CAP"
DEFAULT_CAP = 512

PENDING = "pending"
INCLUDED = "included"
EVICTED = "evicted"
EXPIRED = "expired"


def txid_of(payload: bytes) -> str:
    """Transaction identity = double-SHA256 of the raw payload bytes —
    the same digest discipline as the chain itself."""
    return sha256d(payload).hex()


@dataclasses.dataclass
class TxRecord:
    """One transaction's life in the pool. ``payload`` stays server-side;
    ``public()`` is the wire shape every endpoint returns."""
    txid: str
    payload: bytes
    fee: int
    seq: int
    submitted_at: float
    deadline_at: float | None
    status: str = PENDING
    height: int | None = None    # set on inclusion
    reason: str | None = None    # eviction/expiry detail

    def public(self) -> dict:
        out = {"txid": self.txid, "fee": self.fee,
               "size": len(self.payload), "status": self.status}
        if self.height is not None:
            out["height"] = self.height
        if self.reason is not None:
            out["reason"] = self.reason
        return out


class Mempool:
    """The bounded fee-ordered pool. All methods are thread-safe."""

    def __init__(self, cap: int | None = None,
                 clock=time.monotonic):
        self.cap = int(cap if cap is not None
                       else env_number(ENV_CAP, DEFAULT_CAP, cast=int,
                                       minimum=0))
        self._clock = clock
        self._lock = threading.Lock()
        self._index: dict[str, TxRecord] = {}
        self._take_heap: list = []   # (-fee, seq, txid): template order
        self._evict_heap: list = []  # (fee, seq, txid): cheapest first
        self._resolved: list[str] = []   # FIFO forget ring
        self._seq = 0
        self._pending = 0
        self.submitted_total = 0
        self.included_total = 0
        self.evicted_total = 0
        self.expired_total = 0
        self.depth_max = 0

    # ---- admission -------------------------------------------------------

    def submit(self, payload: bytes, fee: int,
               deadline_s: float | None = None,
               now: float | None = None) -> tuple[str, TxRecord | None]:
        """Admission decision: ``("accepted", rec)``,
        ``("duplicate", rec)`` (same txid already known — idempotent,
        not double-counted), or ``("shed", None)`` when the pool is full
        and the newcomer's fee does not beat the cheapest pending tx."""
        now = self._clock() if now is None else now
        tid = txid_of(payload)
        with self._lock:
            known = self._index.get(tid)
            if known is not None:
                return "duplicate", known
            if self._pending >= self.cap:
                victim = self._cheapest_locked()
                if victim is None or victim.fee >= fee:
                    counter("service_mempool_shed_total").inc()
                    return "shed", None
                self._resolve_locked(victim, EVICTED,
                                     reason="displaced by higher fee")
                self.evicted_total += 1
                counter("service_mempool_evicted_total").inc()
            rec = TxRecord(
                txid=tid, payload=bytes(payload), fee=int(fee),
                seq=self._seq, submitted_at=now,
                deadline_at=(None if deadline_s is None
                             else now + float(deadline_s)))
            self._seq += 1
            self._index[tid] = rec
            heapq.heappush(self._take_heap, (-rec.fee, rec.seq, tid))
            heapq.heappush(self._evict_heap, (rec.fee, rec.seq, tid))
            self._pending += 1
            self.submitted_total += 1
            self.depth_max = max(self.depth_max, self._pending)
            counter("service_mempool_admitted_total").inc()
            return "accepted", rec

    # ---- template drain --------------------------------------------------

    def take(self, limit: int, now: float | None = None) -> list[TxRecord]:
        """Up to ``limit`` pending txs in fee order for the NEXT
        template. Expired work is dropped here — before it can reach
        the miner — and never after: takes do not change status, so a
        tx rides every rebuilt template until included or expired."""
        now = self._clock() if now is None else now
        with self._lock:
            picked: list[TxRecord] = []
            requeue: list = []
            while self._take_heap and len(picked) < limit:
                entry = heapq.heappop(self._take_heap)
                rec = self._index.get(entry[2])
                if rec is None or rec.status != PENDING:
                    continue         # lazily invalidated heap entry
                if rec.deadline_at is not None and now >= rec.deadline_at:
                    self._resolve_locked(rec, EXPIRED, reason="deadline")
                    self.expired_total += 1
                    counter("service_deadline_expired_total").inc()
                    continue
                picked.append(rec)
                requeue.append(entry)
            for entry in requeue:    # still pending: future takes see them
                heapq.heappush(self._take_heap, entry)
            return picked

    def mark_included(self, txids, height: int) -> int:
        """Records the chain's truth after a block lands: every listed
        pending (or even already-expired — the chain wins) tx becomes
        ``included`` at ``height``."""
        n = 0
        with self._lock:
            for tid in txids:
                rec = self._index.get(tid)
                if rec is None or rec.status == INCLUDED:
                    continue
                if rec.status == PENDING:
                    self._pending -= 1
                    self._forget_locked(tid)
                rec.status, rec.height, rec.reason = INCLUDED, height, None
                self.included_total += 1
                n += 1
            if n:
                counter("service_mempool_included_total").inc(n)
        return n

    # ---- queries ---------------------------------------------------------

    def status(self, txid: str) -> TxRecord | None:
        with self._lock:
            return self._index.get(txid)

    def depth(self) -> int:
        with self._lock:
            return self._pending

    def snapshot(self) -> dict:
        """The bounded observability view (healthz / shards / incident
        bundles): depth + lifetime totals + the pending fee range."""
        with self._lock:
            fees = [r.fee for r in self._index.values()
                    if r.status == PENDING]
            return {
                "depth": self._pending,
                "cap": self.cap,
                "depth_max": self.depth_max,
                "submitted_total": self.submitted_total,
                "included_total": self.included_total,
                "evicted_total": self.evicted_total,
                "expired_total": self.expired_total,
                "fee_min": min(fees) if fees else None,
                "fee_max": max(fees) if fees else None,
            }

    # ---- internals (lock held) -------------------------------------------

    def _cheapest_locked(self) -> TxRecord | None:
        while self._evict_heap:
            fee, seq, tid = self._evict_heap[0]
            rec = self._index.get(tid)
            if rec is not None and rec.status == PENDING:
                return rec
            heapq.heappop(self._evict_heap)
        return None

    def _resolve_locked(self, rec: TxRecord, status: str,
                        reason: str) -> None:
        rec.status, rec.reason = status, reason
        self._pending -= 1
        self._forget_locked(rec.txid)

    def _forget_locked(self, txid: str) -> None:
        """Resolved records stay queryable in a bounded FIFO ring; the
        oldest fall out once the ring outgrows 4*cap (+ a floor so a
        cap-0 pool still answers recent statuses)."""
        self._resolved.append(txid)
        keep = max(4 * self.cap, 64)
        while len(self._resolved) > keep:
            old = self._resolved.pop(0)
            rec = self._index.get(old)
            if rec is not None and rec.status != PENDING:
                self._index.pop(old, None)
