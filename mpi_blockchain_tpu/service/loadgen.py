"""Seeded load generator for the serve bench/smoke.

The request SET is a pure function of the seed (crc32 over packed
``(seed, i)`` — the faultplan/sim discipline: no global RNG, no wall
clock in any decision), so two same-seed runs submit byte-identical
payloads with identical fees in identical order. What the SERVER does
with them (which concurrent worker lands first, which tx gets evicted)
is the system under test; the generator only promises its side is
deterministic and that every response is accounted: accepted,
duplicate, typed shed, lost receipt (an empty 200 — the ``partial``
fault's signature, resolved later via ``tx_status``), or transport
error. ``untyped_sheds`` counts non-2xx responses WITHOUT a
``shed_reason`` — the smoke pins it at zero.

The report doubles as the ``serve`` bench payload: sustained
``requests_per_sec``, ``p99_latency_ms``, ``shed_fraction`` and the
pool's high-water ``mempool_depth_max`` land in PERF_HISTORY.jsonl
under the SECTION_BOUNDS p99 budget.
"""
from __future__ import annotations

import json
import queue
import struct
import threading
import time
import urllib.error
import urllib.request
import zlib


def requests_for_seed(seed: int, n: int) -> list[dict]:
    """The deterministic request schedule: ``n`` submits with
    crc32-derived fees (1..1000) and per-seed unique payloads."""
    out = []
    for i in range(n):
        h = zlib.crc32(struct.pack("<II", seed & 0xFFFFFFFF, i))
        out.append({"payload": f"tx-{seed & 0xFFFFFFFF:08x}-{i:04d}",
                    "fee": 1 + h % 1000})
    return out


def _post_submit(base_url: str, req: dict, timeout_s: float) -> dict:
    """One submit roundtrip -> {"outcome", "latency_s", ...detail}."""
    body = json.dumps(req).encode()
    http_req = urllib.request.Request(
        base_url.rstrip("/") + "/submit", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(http_req, timeout=timeout_s) as resp:
            raw = resp.read().decode()
            code = resp.status
    except urllib.error.HTTPError as e:
        raw = e.read().decode()
        code = e.code
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        return {"outcome": "error", "latency_s": time.monotonic() - t0,
                "detail": str(e)}
    latency = time.monotonic() - t0
    if not raw.strip():
        # the partial-fault signature: admitted, receipt lost.
        return {"outcome": "receipt_lost", "latency_s": latency,
                "code": code}
    try:
        doc = json.loads(raw)
    except json.JSONDecodeError:
        return {"outcome": "error", "latency_s": latency, "code": code,
                "detail": "unparseable body"}
    if code == 200 and doc.get("result") in ("accepted", "duplicate"):
        return {"outcome": doc["result"], "latency_s": latency,
                "txid": doc.get("txid")}
    reason = doc.get("shed_reason")
    if reason:
        return {"outcome": "shed", "latency_s": latency, "code": code,
                "shed_reason": reason, "txid": doc.get("txid")}
    return {"outcome": "untyped", "latency_s": latency, "code": code,
            "detail": doc}


def p99_ms(latencies_s: list[float]) -> float:
    if not latencies_s:
        return 0.0
    ordered = sorted(latencies_s)
    idx = min(len(ordered) - 1, max(0, int(0.99 * len(ordered))))
    return round(ordered[idx] * 1e3, 3)


def run_load(base_url: str, seed: int, n: int, workers: int = 2,
             timeout_s: float = 10.0,
             mempool_probe=None) -> dict:
    """Drives the seeded schedule through ``workers`` concurrent
    submitters and returns the accounting report. ``mempool_probe``
    (optional callable -> int) is sampled after every response for the
    high-water depth."""
    schedule = requests_for_seed(seed, n)
    work: queue.Queue = queue.Queue()
    for req in schedule:
        work.put(req)
    results: list[dict] = []
    results_lock = threading.Lock()
    depth_max = [0]

    def _worker():
        while True:
            try:
                req = work.get_nowait()
            except queue.Empty:
                return
            res = _post_submit(base_url, req, timeout_s)
            res["fee"] = req["fee"]
            res["payload"] = req["payload"]
            with results_lock:
                results.append(res)
                if mempool_probe is not None:
                    depth_max[0] = max(depth_max[0], int(mempool_probe()))

    t0 = time.monotonic()
    threads = [threading.Thread(target=_worker,
                                name=f"loadgen-{i}", daemon=True)
               for i in range(max(1, workers))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout_s * max(1, n))
    wall_s = max(time.monotonic() - t0, 1e-9)

    by_outcome: dict[str, int] = {}
    shed_reasons: dict[str, int] = {}
    latencies = []
    accepted_txids = []
    for res in results:
        by_outcome[res["outcome"]] = by_outcome.get(res["outcome"], 0) + 1
        latencies.append(res["latency_s"])
        if res["outcome"] == "shed":
            r = res["shed_reason"]
            shed_reasons[r] = shed_reasons.get(r, 0) + 1
        if res["outcome"] in ("accepted", "duplicate") and res.get("txid"):
            accepted_txids.append(res["txid"])
    shed = by_outcome.get("shed", 0)
    lost_payloads = sorted(r["payload"] for r in results
                           if r["outcome"] == "receipt_lost")
    return {
        "receipt_lost_payloads": lost_payloads,
        "requests": len(results),
        "wall_s": round(wall_s, 4),
        "requests_per_sec": round(len(results) / wall_s, 2),
        "p99_latency_ms": p99_ms(latencies),
        "max_latency_ms": round(max(latencies, default=0.0) * 1e3, 3),
        "by_outcome": by_outcome,
        "shed_reasons": shed_reasons,
        "shed_fraction": round(shed / max(1, len(results)), 4),
        "untyped_sheds": by_outcome.get("untyped", 0),
        "errors": by_outcome.get("error", 0),
        "receipt_lost": by_outcome.get("receipt_lost", 0),
        "accepted_txids": accepted_txids,
        "mempool_depth_max": depth_max[0],
        "seed": seed,
    }
