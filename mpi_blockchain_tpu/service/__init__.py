"""blockserve — the overload-safe mempool + template-service front door.

ROADMAP item 3's serving layer, built robustness-first: users submit
fee-carrying transactions over HTTP, a bounded fee-ordered mempool
(``mempool.Mempool``) feeds per-height templates through the miner's
``payload_for`` seam (``frontdoor.TemplateFeed``), and the door itself
(``frontdoor.ServiceState`` + ``ServiceServer``) sheds typed under
overload, bounds every request with a deadline, backpressures on miner
heartbeat age, and stamps degradation instead of going dark.

Process-wide arming mirrors chainwatch/meshwatch: ``install_service``
binds a miner and starts the door, ``service_stats()`` is the additive
observability payload the per-process ``/healthz``, meshwatch shards
and chainwatch incident bundles all carry (``{}`` while no service is
armed — the quiet shape every consumer pins additively).

Smoke/bench entry points live in ``__main__`` (``make serve-smoke``).
"""
from __future__ import annotations

import threading

from .frontdoor import (ServiceServer, ServiceState, TemplateFeed,
                        template_payload)
from .mempool import Mempool, TxRecord, txid_of

__all__ = ["Mempool", "ServiceServer", "ServiceState", "TemplateFeed",
           "TxRecord", "active_service", "install_service",
           "service_stats", "template_payload", "txid_of",
           "uninstall_service"]

_lock = threading.Lock()
_active: list[ServiceState] = []


def install_service(miner, port: int = 0, host: str = "127.0.0.1",
                    **state_kw) -> ServiceState:
    """Binds ``miner``'s template seam, starts the HTTP door, and arms
    the process-wide stats surface. Returns the state with its
    ``server`` attached (``state.server.port`` is the bound port)."""
    state = ServiceState(miner, **state_kw)
    state.bind()
    server = ServiceServer(state, port=port, host=host)
    server.start()
    state.server = server
    with _lock:
        _active.append(state)
    return state


def uninstall_service(state: ServiceState) -> None:
    """Stops the door, unbinds the miner, disarms stats. Idempotent."""
    with _lock:
        if state in _active:
            _active.remove(state)
    server = getattr(state, "server", None)
    if server is not None:
        server.close()
    state.unbind()


def active_service() -> ServiceState | None:
    with _lock:
        return _active[-1] if _active else None


def service_stats() -> dict:
    """The additive ``service`` observability key: the armed service's
    ``stats()``, or ``{}`` when none is armed (the shape healthz /
    shards / bundles carry in a serviceless process)."""
    state = active_service()
    if state is None:
        return {}
    return state.stats()
