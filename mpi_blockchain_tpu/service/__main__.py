"""blockserve CLI: the chaos-gated serve smoke + seeded loadgen.

``smoke`` is the ``make serve-smoke`` gate (ISSUE 20 acceptance). One
in-process world, fully deterministic where it must be:

1. a STRICT fault plan arms ``service.submit`` (hang) and
   ``service.rebuild`` (raise) — both must fire or the run fails;
2. a fee-paying seeded load batch hits a live door over real HTTP while
   a pipelined miner mines against the rebuilt templates, with the
   ResilientBackend's top rung rigged to die mid-run (the forced
   step-down: the door must stamp ``degraded`` and keep serving);
3. the hard, non-weather assertions: every request answers (no hangs,
   max latency inside the deadline budget), every non-2xx carries a
   typed ``shed_reason``, every accepted/receipt-lost tx is
   status-queryable afterwards (zero accepted-then-lost), admission
   conservation holds against the pool bound, and the mined chain is
   byte-identical to a sequential no-service oracle replaying the
   recorded per-height templates;
4. the ``serve`` bench payload (requests/s, p99 latency, shed fraction,
   mempool high-water) is judged against SECTION_BOUNDS through the
   perfwatch detector (``--record`` appends it to PERF_HISTORY.jsonl —
   the measure -> gate -> record shape).
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys
import threading

SMOKE_SEED = 1337
SMOKE_DIFFICULTY = 12
SMOKE_BLOCKS = 6
SMOKE_CAP = 8
SMOKE_TEMPLATE_TXS = 4
SMOKE_BATCH_A = 10     # pre-mine: exercises admission + the hang fault
SMOKE_BATCH_B = 16     # streamed while the miner runs
SMOKE_DEADLINE_S = 5.0


class _FlakyRung:
    """A cpu backend whose dispatches start failing after
    ``fail_after`` calls and never recover — exhausts the dispatch
    retry budget and forces the ladder's mid-run step-down. Until the
    failure it delegates verbatim, so both rungs compute identical
    sweeps and the chain stays byte-identical across the step-down."""

    name = "cpu-flaky"

    def __init__(self, fail_after: int):
        from ..backend.cpu import CpuBackend
        self._inner = CpuBackend()
        self._calls = 0
        self._fail_after = fail_after

    def search(self, header80, difficulty_bits, start_nonce=0,
               max_count=1 << 32):
        self._calls += 1
        if self._calls > self._fail_after:
            raise RuntimeError(
                f"flaky rung wedged (call {self._calls})")
        return self._inner.search(header80, difficulty_bits,
                                  start_nonce, max_count)


def _smoke_world():
    """(miner, state) — the served world under the rigged ladder."""
    from ..backend.cpu import CpuBackend
    from ..config import MinerConfig
    from ..models.miner import Miner
    from ..resilience.dispatch import ResilientBackend
    from . import install_service

    cfg = MinerConfig(difficulty_bits=SMOKE_DIFFICULTY,
                      n_blocks=SMOKE_BLOCKS, backend="cpu",
                      seed=SMOKE_SEED)
    ladder = ResilientBackend(
        [("cpu-flaky", lambda: _FlakyRung(fail_after=3)),
         ("cpu", CpuBackend)], seed=SMOKE_SEED)
    miner = Miner(cfg, backend=ladder, pipeline=True)
    from .mempool import Mempool
    from .frontdoor import TemplateFeed
    pool = Mempool(cap=SMOKE_CAP)
    feed = TemplateFeed(pool, cfg, max_txs=SMOKE_TEMPLATE_TXS)
    state = install_service(miner, port=0, mempool=pool, feed=feed,
                            deadline_s=SMOKE_DEADLINE_S)
    return miner, state


def cmd_smoke(args) -> int:
    import logging

    from ..perfwatch.detector import check_candidate
    from ..perfwatch.history import DEFAULT_HISTORY_NAME, HistoryStore
    from ..perfwatch.server import wait_listening
    from ..resilience import FaultPlanError, injection
    from ..resilience.faultplan import FaultPlan, FaultSpec
    from . import uninstall_service
    from .loadgen import run_load
    from .mempool import txid_of

    logging.getLogger("mpi_blockchain_tpu").setLevel(logging.WARNING)
    repo_root = pathlib.Path(__file__).resolve().parent.parent.parent
    store = HistoryStore(repo_root / DEFAULT_HISTORY_NAME)

    plan = FaultPlan(faults=(
        FaultSpec(site="service.submit", kind="hang", call=2, times=1,
                  seconds=0.05),
        FaultSpec(site="service.rebuild", kind="raise", call=0, times=1),
    ), seed=SMOKE_SEED, strict=True)
    injection.arm(plan)
    miner, state = _smoke_world()
    failures: list[str] = []
    try:
        base_url = state.server.url("/").rstrip("/")
        if not wait_listening(state.server.host, state.server.port):
            print("serve-smoke: door never started listening",
                  file=sys.stderr)
            return 1

        # Phase A — pre-mine admission under faults: the strict hang
        # fires here (call index 2), the rebuild raise fired at bind.
        report_a = run_load(base_url, seed=SMOKE_SEED, n=SMOKE_BATCH_A,
                            workers=2, mempool_probe=state.mempool.depth)

        # Phase B — live serving: stream submits while the pipelined
        # miner mines the rebuilt templates and the rigged rung dies.
        report_b: dict = {}

        def _stream():
            report_b.update(run_load(
                base_url, seed=SMOKE_SEED + 1, n=SMOKE_BATCH_B,
                workers=2, mempool_probe=state.mempool.depth))

        streamer = threading.Thread(target=_stream, name="serve-stream",
                                    daemon=True)
        streamer.start()
        miner.mine_chain(SMOKE_BLOCKS)
        streamer.join(timeout=60)
        if streamer.is_alive():
            failures.append("loadgen stream never finished (a hung "
                            "request escaped its deadline)")

        # ---- hard gates (none of these are weather) ----------------------
        for tag, rep in (("A", report_a), ("B", report_b)):
            if rep.get("untyped_sheds", 1):
                failures.append(f"phase {tag}: non-2xx response without "
                                f"a shed_reason: {rep.get('by_outcome')}")
            if rep.get("errors", 1):
                failures.append(f"phase {tag}: transport errors: "
                                f"{rep.get('by_outcome')}")
            if rep.get("requests") != (SMOKE_BATCH_A if tag == "A"
                                       else SMOKE_BATCH_B):
                failures.append(f"phase {tag}: not every request "
                                f"answered: {rep.get('requests')}")
            if rep.get("max_latency_ms", 1e9) >= SMOKE_DEADLINE_S * 1e3:
                failures.append(f"phase {tag}: request latency "
                                f"{rep.get('max_latency_ms')}ms breached "
                                f"the deadline budget")

        # Zero accepted-then-lost: every admitted (or receipt-lost) tx
        # must still be status-queryable through the live door.
        import urllib.request
        lost = []
        for rep in (report_a, report_b):
            for txid in rep.get("accepted_txids", []):
                with urllib.request.urlopen(
                        f"{base_url}/tx_status?txid={txid}",
                        timeout=5) as resp:
                    if resp.status != 200:
                        lost.append(txid)
        # Receipt-lost txs carried no txid over the wire: recompute it
        # from the schedule payload — the tx WAS admitted (the partial
        # fault loses only the receipt), so its status must still
        # answer through the door.
        for rep in (report_a, report_b):
            for res_payload in rep.get("receipt_lost_payloads", []):
                tid = txid_of(res_payload.encode())
                code, _ = state.tx_status(tid)
                if code != 200:
                    lost.append(tid)
        if lost:
            failures.append(f"accepted-then-lost txids: {lost}")

        # Admission conservation: 10 unique submits into a cap-8 pool
        # must displace or shed at least 2 — and everything admitted is
        # accounted pending/included/evicted/expired.
        snap = state.mempool.snapshot()
        displaced = (sum(report_a["shed_reasons"].values())
                     + snap["evicted_total"])
        if displaced < SMOKE_BATCH_A - SMOKE_CAP:
            failures.append(f"admission conservation broke: "
                            f"{displaced} displaced/shed for "
                            f"{SMOKE_BATCH_A} submits into cap "
                            f"{SMOKE_CAP}: {snap}")
        if snap["included_total"] < 1:
            failures.append(f"no submitted tx was ever mined into a "
                            f"template: {snap}")

        # The forced step-down: degraded stamp + reads stay up.
        if not miner.backend.degraded:
            failures.append("rigged ladder never stepped down")
        tmpl = state.template_view()
        if not tmpl.get("degraded"):
            failures.append(f"template response missing the degraded "
                            f"stamp: {tmpl}")
        chain = state.chain_view(n=SMOKE_BLOCKS)
        if chain["height"] != SMOKE_BLOCKS:
            failures.append(f"served chain height {chain['height']} != "
                            f"{SMOKE_BLOCKS}")

        # Byte-identity vs the sequential no-service oracle.
        recorded = dict(state.feed.history)
        from ..backend.cpu import CpuBackend
        from ..config import MinerConfig
        from ..models.miner import Miner
        oracle = Miner(MinerConfig(difficulty_bits=SMOKE_DIFFICULTY,
                                   n_blocks=SMOKE_BLOCKS, backend="cpu",
                                   seed=SMOKE_SEED),
                       backend=CpuBackend(), pipeline=False)
        oracle.payload_for = lambda h: recorded[h]
        oracle.mine_chain(SMOKE_BLOCKS)
        if oracle.chain_hashes() != miner.chain_hashes():
            failures.append("served chain diverged from the sequential "
                            "no-service oracle")
        chain_identical = oracle.chain_hashes() == miner.chain_hashes()

        # Strict plan exhaustion: both injected faults actually fired.
        try:
            injection.disarm(strict=True)
        except FaultPlanError as e:
            failures.append(str(e))

        # ---- the serve bench payload, gated like every section -----------
        payload = {
            "backend": "cpu",
            "difficulty_bits": SMOKE_DIFFICULTY,
            "n_blocks": SMOKE_BLOCKS,
            "requests": report_b.get("requests", 0),
            "requests_per_sec": report_b.get("requests_per_sec", 0.0),
            "p99_latency_ms": report_b.get("p99_latency_ms", 0.0),
            "shed_fraction": report_b.get("shed_fraction", 0.0),
            "mempool_depth_max": max(
                report_a.get("mempool_depth_max", 0),
                report_b.get("mempool_depth_max", 0)),
            "mempool_cap": SMOKE_CAP,
            "included_total": snap["included_total"],
            "chain_identical": chain_identical,
        }
        finding = check_candidate(store, "serve", payload)
        if finding.verdict == "regression":
            failures.append(f"serve bench over budget: "
                            f"{finding.render()}")
        if failures:
            for f in failures:
                print(f"serve-smoke: {f}", file=sys.stderr)
            return 1
        if args.record:
            store.record("serve", payload, source="serve-smoke")
        print(json.dumps({
            "event": "serve_smoke", "ok": True,
            "faults_fired": 2,
            "degraded_to": miner.backend.rung,
            "sheds": dict(state.shed_totals),
            "verdict": finding.verdict,
            **payload}, sort_keys=True))
        return 0
    finally:
        injection.disarm()
        from . import active_service
        if active_service() is state:
            uninstall_service(state)


def cmd_loadgen(args) -> int:
    from .loadgen import run_load

    report = run_load(args.url, seed=args.seed, n=args.requests,
                      workers=args.workers)
    report.pop("accepted_txids", None)
    print(json.dumps(report, sort_keys=True))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_blockchain_tpu.service",
        description="blockserve front door: chaos-gated serve smoke + "
                    "seeded load generator")
    sub = parser.add_subparsers(dest="command", required=True)

    p_smoke = sub.add_parser(
        "smoke", help="the make serve-smoke gate: faulted, degraded, "
                      "oracle-checked serving")
    p_smoke.add_argument("--record", action="store_true",
                         help="append the serve bench payload to "
                              "PERF_HISTORY.jsonl on success")
    p_smoke.set_defaults(fn=cmd_smoke)

    p_load = sub.add_parser("loadgen", help="drive a seeded submit load "
                                            "at a live door")
    p_load.add_argument("--url", required=True,
                        help="door base URL, e.g. http://127.0.0.1:9100")
    p_load.add_argument("--seed", type=int, default=0)
    p_load.add_argument("--requests", type=int, default=32)
    p_load.add_argument("--workers", type=int, default=2)
    p_load.set_defaults(fn=cmd_loadgen)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
