"""Raw hash-throughput measurement shared by the CLI and bench.py.

Measures pure sweep throughput (difficulty 64 => no winner, no early exit):
the hashes/sec/chip number that is this project's primary metric
(BASELINE.json). The CPU measurement is the mpirun-equivalent denominator —
n_miners C++ ranks (threads running the GIL-free scalar loop), documented in
BASELINE.md as the "mpirun -np N" stand-in since OpenMPI is not in the image.
"""
from __future__ import annotations

import concurrent.futures
import contextlib
import threading
import time

from . import core
from .telemetry import counter, emit_event, gauge, heartbeat, rank_counter
from .telemetry.events import env_number
from .telemetry.spans import span

_IMPOSSIBLE_DIFFICULTY = 64  # no 64-leading-zero-bit hash will be found
_HEADER = bytes(range(80))   # arbitrary fixed header; content is irrelevant

# Per-phase device-init watchdog budget. The round-1 failure mode was a
# 900 s parent timeout with zero attribution ("device init hang?"); now
# each init phase is a structured bench.device_init span/event, and a
# phase exceeding this budget emits a hang event + flight-recorder dump
# BEFORE any parent watchdog kills the process.
DEVICE_INIT_PHASE_TIMEOUT_S = env_number(
    "MPIBT_DEVICE_INIT_TIMEOUT", 300.0, cast=float, minimum=1e-6)


@contextlib.contextmanager
def _device_init_phase(name: str, timeout_s: float | None = None):
    """One attributable device-init phase: a ``bench.device_init`` span,
    a completion event carrying (phase, elapsed_s), and a hang watchdog.

    The watchdog thread fires while the process is still alive, so the
    hang event and the flight-recorder artifact exist even when a parent
    harness (bench.py) subsequently SIGKILLs the wedged child — the dump
    is what makes "timed out after 900s" attributable to a phase.
    """
    from .telemetry import flight_recorder

    timeout_s = (DEVICE_INIT_PHASE_TIMEOUT_S if timeout_s is None
                 else timeout_s)
    # One heartbeat stamp at phase ENTRY: a phase that wedges leaves the
    # gauge stale, so a live /healthz scrape turns unhealthy while the
    # hang is still in flight (the watchdog/flight-recorder path below
    # covers the post-mortem).
    heartbeat("bench_heartbeat").inc()
    t0 = time.perf_counter()

    def _hang() -> None:
        elapsed = round(time.perf_counter() - t0, 1)
        emit_event({"event": "bench.device_init", "phase": name,
                    "status": "hang", "elapsed_s": elapsed,
                    "timeout_s": timeout_s})
        flight_recorder.dump_now(
            f"bench.device_init hang: phase {name!r} still running "
            f"after {elapsed}s (budget {timeout_s}s)")

    watchdog = threading.Timer(timeout_s, _hang)
    watchdog.daemon = True
    watchdog.start()
    status = "done"
    try:
        with span("bench.device_init", phase=name):
            yield
    except BaseException as e:
        # The phase that RAISED must not read as 'done' in a crash dump
        # — the phase label is the attribution this machinery exists for.
        status = f"error: {type(e).__name__}"
        raise
    finally:
        watchdog.cancel()
        emit_event({"event": "bench.device_init", "phase": name,
                    "status": status,
                    "elapsed_s": round(time.perf_counter() - t0, 3)})


def bench_cpu(seconds: float = 3.0, n_miners: int = 1,
              chunk: int = 1 << 18) -> dict:
    """C++ scalar sweep throughput over n_miners threads (GIL released)."""
    # Shared across the GIL-free rank threads — the live thread-safety
    # proof for the registry (tests/test_telemetry.py asserts this
    # counter exactly matches the summed per-rank totals).
    hashes_c = counter("bench_hashes_total",
                       help="nonces hashed by the bench sweep",
                       backend="cpu")

    def one_rank(rank: int) -> int:
        tried = 0
        deadline = time.perf_counter() + seconds
        base = rank * (1 << 28)
        hb = heartbeat("bench_heartbeat")
        # Per-rank attribution rides the rank-aware helper (TEL003):
        # the merged mesh view shows which thread-rank hashed what.
        rank_c = rank_counter("bench_rank_hashes_total",
                              help="nonces hashed per bench rank",
                              rank=rank, backend="cpu")
        while time.perf_counter() < deadline:
            _, t = core.cpu_search(_HEADER, base, chunk,
                                   _IMPOSSIBLE_DIFFICULTY)
            tried += t
            hashes_c.inc(t)
            rank_c.inc(t)
            hb.inc()
            base += chunk
        return tried

    t0 = time.perf_counter()
    if n_miners == 1:
        per_rank = [one_rank(0)]
    else:
        with concurrent.futures.ThreadPoolExecutor(n_miners) as pool:
            per_rank = list(pool.map(one_rank, range(n_miners)))
    total = sum(per_rank)
    wall = time.perf_counter() - t0
    gauge("bench_hashes_per_sec",
          help="last measured sweep throughput",
          backend="cpu").set(total / wall)
    return {"backend": "cpu", "n_miners": n_miners,
            "hashes": total, "wall_s": round(wall, 3),
            "hashes_per_sec": total / wall,
            "hashes_per_sec_per_rank": total / wall / n_miners,
            "per_rank": [{"rank": i, "hashes": t,
                          "hashes_per_sec": round(t / wall, 1)}
                         for i, t in enumerate(per_rank)]}


def bench_tpu(seconds: float = 5.0, batch_pow2: int = 28,
              n_miners: int = 1, kernel: str = "auto",
              depth: int | None = None) -> dict:
    """Device sweep throughput; per-chip rate is the judge's metric.

    batch_pow2 defaults to 28: dispatch overhead (~90 ms/round under the
    axon tunnel) swamps the kernel below ~2^26 nonces/dispatch, and the
    VPU-saturated plateau starts there (see ops/sha256_pallas.py).
    """
    with _device_init_phase("jax_import"):
        import jax
        import numpy as np

    with _device_init_phase("backend_resolve"):
        # The first real device-init trigger: under the axon tunnel THIS
        # is where a wedged init historically hung for the full 900 s.
        platform = jax.default_backend()

    if platform == "cpu":
        # The big-batch default exists to beat dispatch overhead on a real
        # accelerator; on host CPU a 2^28 sweep holds a ~GiB-scale live
        # scan carry and can OOM, so clamp to a size the fallback survives.
        batch_pow2 = min(batch_pow2, 22)
    batch = 1 << batch_pow2
    midstate, tail = core.header_midstate(_HEADER)
    with _device_init_phase("kernel_build"):
        if n_miners > 1:
            from .parallel.mesh import make_mesh_sweep_fn, make_miner_mesh
            mesh = make_miner_mesh(n_miners)
            fn = make_mesh_sweep_fn(mesh, batch, _IMPOSSIBLE_DIFFICULTY,
                                    kernel)
            round_size = batch * n_miners
        else:
            from .ops import select_kernel
            fn, kernel = select_kernel(kernel, batch, _IMPOSSIBLE_DIFFICULTY)
            round_size = batch

    with _device_init_phase("compile_warm"):
        int(fn(midstate, tail, np.uint32(0))[0])  # compile + warm
    # Pipelined measurement: dispatches are async, so keep a bounded window
    # of in-flight rounds and force completion by materializing the oldest
    # result's VALUE (int(...)). A sync per call would bill one host<->device
    # round-trip per batch — under the axon tunnel that is ~50x the compute
    # time — while block_until_ready on a remote-relay platform can return
    # before the queue drains, so value materialization is the only honest
    # completion signal.
    if depth is None:  # keep the in-flight queue under ~1s of compute
        depth = 16 if batch_pow2 < 26 else 4
    pending: list = []
    hb = heartbeat("bench_heartbeat")
    t0 = time.perf_counter()
    tried = 0
    while time.perf_counter() - t0 < seconds:
        pending.append(fn(midstate, tail, np.uint32(tried & 0xFFFFFFFF)))
        tried += round_size
        if len(pending) >= depth:
            int(pending.pop(0)[0])
            hb.inc()
    for r in pending:
        int(r[0])
    wall = time.perf_counter() - t0
    counter("bench_hashes_total",
            help="nonces hashed by the bench sweep", backend="tpu").inc(tried)
    gauge("bench_hashes_per_sec",
          help="last measured sweep throughput", backend="tpu").set(
        tried / wall)
    result = {"backend": "tpu", "n_miners": n_miners, "kernel": kernel,
              "batch_pow2": batch_pow2, "platform": jax.default_backend(),
              "hashes": tried, "wall_s": round(wall, 3),
              "hashes_per_sec": tried / wall,
              "hashes_per_sec_per_chip": tried / wall / n_miners}
    # The committed op census rides the payload (and so the recorded
    # PERF_HISTORY entry): GH/s and ops/nonce trend TOGETHER — a rate
    # regression that coincides with an op-budget cut is attributable
    # from the history alone, and `perfwatch check` computes utilization
    # from the census current at record time, never a stale one.
    census = _committed_census()
    if census is not None:
        result["alu_ops_per_nonce"] = census
    if n_miners > 1:
        # Multichip breakdown: every mesh device sweeps exactly `batch`
        # nonces per round (disjoint stripes by construction), so the
        # per-chip share is exact — recorded per-rank so the multichip
        # bench payload and the merged mesh view agree chip by chip.
        per_chip = tried // n_miners
        devices = list(mesh.devices.flat)
        for i, dev in enumerate(devices):
            rank_counter("bench_rank_hashes_total",
                         help="nonces hashed per bench rank",
                         rank=i, backend="tpu").inc(per_chip)
        result["per_rank"] = [
            {"rank": i, "device": str(dev), "hashes": per_chip,
             "hashes_per_sec": round(per_chip / wall, 1)}
            for i, dev in enumerate(devices)]
    return result


def _committed_census() -> int | None:
    """alu_ops_per_nonce from the committed OPBUDGET.json, or None."""
    from .perfwatch.attribution import committed_census

    ops = (committed_census() or {}).get("alu_ops_per_nonce")
    return ops if isinstance(ops, int) else None


def bench_chain(n_blocks: int = 1000, difficulty_bits: int = 24,
                batch_pow2: int = 24, blocks_per_call: int = 100,
                n_miners: int = 1, kernel: str = "auto",
                mesh=None) -> dict:
    """Wall-clock to mine a full chain — the metric's second half.

    Uses the fused device-resident miner (models/fused.py) and validates
    the resulting chain before reporting. n_miners > 1 (or an explicit
    mesh) runs the sharded mine loop over the ('miners',) mesh.
    """
    import time as _time

    from .config import MinerConfig
    from .models.fused import FusedMiner

    cfg = MinerConfig(difficulty_bits=difficulty_bits, n_blocks=n_blocks,
                      batch_pow2=batch_pow2, backend="tpu",
                      n_miners=n_miners, kernel=kernel)
    miner = FusedMiner(cfg, blocks_per_call=blocks_per_call, mesh=mesh)
    miner.warmup()
    if n_blocks % blocks_per_call:    # the remainder chunk is its own program
        miner.warmup(n_blocks % blocks_per_call)
    t0 = _time.perf_counter()
    miner.mine_chain()
    wall = _time.perf_counter() - t0
    node = miner.node
    if node.height != n_blocks:  # not assert: must survive python -O
        raise RuntimeError(f"mined {node.height}/{n_blocks} blocks")
    # Full PoW + linkage re-validation through the C++ chain loader.
    if not core.Node(difficulty_bits, 0).load(node.save()):
        raise RuntimeError("mined chain failed validation")
    gauge("bench_blocks_per_sec",
          help="last measured full-chain mining rate",
          backend="tpu-fused").set(n_blocks / wall)
    return {"n_blocks": n_blocks, "difficulty_bits": difficulty_bits,
            "n_miners": n_miners, "wall_s": round(wall, 3),
            "blocks_per_sec": n_blocks / wall,
            "tip_hash": node.tip_hash.hex()}


def bench_sharded_pallas(n_blocks: int = 30, difficulty_bits: int = 16,
                         batch_pow2: int = 20, blocks_per_call: int = 10,
                         kernel: str = "pallas") -> dict:
    """Config 4's exact production combination, proven on ONE chip: the
    fused miner through the shard_map branch (psum/pmin winner-select)
    with the Pallas kernel on a 1-device ('miners',) mesh, tip checked
    against the C++ oracle. The single source of this measurement —
    bench.py's device child and experiments/hw_round4.py both call it;
    the warmup/timing discipline lives in bench_chain. kernel is
    overridable only so the CI suite can run the identical code path with
    the jnp kernel on the CPU platform (tests/test_fused.py).
    """
    from .config import MinerConfig
    from .models.miner import Miner
    from .parallel.mesh import make_miner_mesh

    result = bench_chain(n_blocks=n_blocks, difficulty_bits=difficulty_bits,
                         batch_pow2=batch_pow2,
                         blocks_per_call=blocks_per_call, n_miners=1,
                         kernel=kernel, mesh=make_miner_mesh(1))
    oracle = Miner(MinerConfig(difficulty_bits=difficulty_bits,
                               n_blocks=n_blocks, backend="cpu"),
                   log_fn=lambda d: None)
    oracle.mine_chain()
    return {**result, "mesh": "1-device ('miners',)",
            "kernel": kernel,
            "cpu_oracle_tip": oracle.node.tip_hash.hex(),
            "tip_matches_cpu_oracle":
                result["tip_hash"] == oracle.node.tip_hash.hex()}


def bench_tpu_single() -> dict:
    """Config 3's LITERAL preset (difficulty 20, 10 blocks, batch 2^20,
    pallas) through the per-block multi-round searcher, tip checked against
    the CPU oracle. This is the dispatch-latency regression record: the
    round-1 per-round host loop measured 8.2 s / 2.83 MH/s here; the
    round-4 on-device round loop costs ~one dispatch per block. The single
    measurement source — bench.py's device child and
    experiments/hw_round4.py §1 both call it.
    """
    from .config import PRESETS, MinerConfig
    from .models.miner import Miner

    cfg = PRESETS["tpu-single"]
    miner = Miner(cfg, log_fn=lambda d: None)
    # Compile outside the timer (jit is lazy: only a real search call
    # triggers Mosaic), mirroring the round-1 measurement's discipline.
    miner.backend.search(bytes(80), cfg.difficulty_bits,
                         max_count=cfg.batch_size)
    t0 = time.perf_counter()
    miner.mine_chain()
    wall = time.perf_counter() - t0
    oracle = Miner(MinerConfig(difficulty_bits=cfg.difficulty_bits,
                               n_blocks=cfg.n_blocks, backend="cpu"),
                   log_fn=lambda d: None)
    oracle.mine_chain()
    census = _committed_census()
    return {"preset": "tpu-single", "n_blocks": cfg.n_blocks,
            "difficulty_bits": cfg.difficulty_bits,
            "batch_pow2": cfg.batch_pow2, "wall_s": round(wall, 2),
            # Key omitted (never null) without a committed budget — the
            # same shape contract as bench_tpu's payload.
            **({"alu_ops_per_nonce": census} if census is not None else {}),
            "hashes_per_sec": round(miner.hashes_per_sec()),
            "mhs": round(miner.hashes_per_sec() / 1e6, 2),
            "vs_round1_2p83_mhs": round(
                miner.hashes_per_sec() / 2.83e6, 1),
            "tip_hash": miner.node.tip_hash.hex(),
            "tip_matches_cpu_oracle":
                miner.node.tip_hash == oracle.node.tip_hash}


def bench_sim_adversarial(preset: str = "adversarial-bench") -> dict:
    """One timed run of the vectorized adversarial scenario engine — the
    ``sim_adversarial`` bench section. steps/sec is the headline: the
    perfwatch sentinel gates sim throughput with it exactly like it
    gates mining (ISSUE 6). The scenario is a FIXED preset (churn +
    retargeting + selfish/eclipse/flood all live), so the number prices
    the engine, and the summary invariants double as a correctness
    canary — a non-converged or attack-free run records loudly.
    """
    from .sim import SCENARIO_PRESETS, run_scenario

    scenario = SCENARIO_PRESETS[preset]
    t0 = time.perf_counter()
    net, summary = run_scenario(scenario)
    wall = time.perf_counter() - t0
    return {
        "preset": preset,
        "n_nodes": scenario.n_nodes,
        "steps": scenario.steps,
        "wall_s": round(wall, 3),
        "steps_per_sec": round(scenario.steps / wall, 1),
        "converged": summary["converged"],
        "blocks_total": summary["blocks_total"],
        "final_bits": summary["final_bits"],
        "sync_rejections": summary["sync_rejections"],
        "reorgs": summary["reorgs"],
    }


def repeat_best(measure, reps: int = 2, key: str = "hashes_per_sec",
                minimize: bool = False, prior: list | None = None) -> dict:
    """Runs measure() reps times and returns the best run's payload (min
    of `key` if minimize else max), annotated with the rep discipline:
    {"reps", "spread_pct", "all_<key>"}. BASELINE.md's tunnel warning made
    executable: the axon tunnel can inflate a single run >10x, so official
    records are best-of-N with the spread ON the record — one wedged rep
    can no longer poison the number a dashboard (or the cache) pins. If
    payloads carry a tip_hash, all reps must agree (determinism
    contract). `prior` seeds already-measured payloads counted toward
    reps — the device child streams rep 1 the moment it lands and only
    then runs the remaining reps, so a later rep wedging the tunnel can
    never discard a completed measurement."""
    outs = list(prior or [])
    outs += [measure() for _ in range(reps - len(outs))]
    vals = [o[key] for o in outs]
    best = min(vals) if minimize else max(vals)
    tips = {o["tip_hash"] for o in outs if "tip_hash" in o}
    if len(tips) > 1:
        raise RuntimeError(f"non-deterministic tips across reps: {tips}")
    payload = dict(outs[vals.index(best)])
    payload["reps"] = reps
    payload["spread_pct"] = round(
        100.0 * (max(vals) - min(vals)) / max(abs(best), 1e-12), 1)
    payload["all_" + key] = [round(v, 3) if isinstance(v, float) else v
                             for v in vals]
    return payload


def run_bench(backend: str = "tpu", seconds: float = 5.0,
              batch_pow2: int = 28, n_miners: int = 1,
              kernel: str = "auto") -> dict:
    if backend == "cpu":
        return bench_cpu(seconds=seconds, n_miners=n_miners)
    return bench_tpu(seconds=seconds, batch_pow2=batch_pow2,
                     n_miners=n_miners, kernel=kernel)
