"""Command-line entry point — the rebuild of the reference's main()/mpirun
launch form (SURVEY.md §1 layer 7).

    python -m mpi_blockchain_tpu mine --difficulty 16 --blocks 10 --backend cpu
    python -m mpi_blockchain_tpu mine --preset tpu-single
    python -m mpi_blockchain_tpu verify --chain chain.bin --difficulty 16

Where the reference took `mpirun -np N`, the miner count here is --miners N:
CPU ranks for backend=cpu, mesh devices for backend=tpu.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

from .config import ConfigError, MinerConfig, PRESETS
from .resilience import FaultPlanError, RetryExhausted

#: Vectorized-scenario preset names (sim.scenario.SCENARIO_PRESETS),
#: duplicated as a literal so building the arg parser never imports
#: numpy; a test asserts the two stay in sync.
SCENARIO_PRESET_NAMES = ("adversarial-1k", "adversarial-bench",
                         "adversarial-smoke")


def _batch_pow2_arg(s: str):
    if s == "auto":
        return s
    try:
        return int(s)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected an integer or 'auto', got {s!r}") from None


def _add_config_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--preset", choices=sorted(PRESETS),
                   help="named BASELINE config (overrides other flags)")
    p.add_argument("--difficulty", type=int, default=16,
                   help="leading-zero bits (default 16)")
    p.add_argument("--blocks", type=int, default=10)
    p.add_argument("--miners", type=int, default=1,
                   help="CPU ranks / mesh devices (mpirun -np equivalent)")
    p.add_argument("--backend", choices=["cpu", "tpu"], default="cpu")
    p.add_argument("--kernel", choices=["auto", "jnp", "pallas"],
                   default="auto")
    p.add_argument("--batch-pow2", type=_batch_pow2_arg, default=20,
                   help="log2 nonces per device per round, or 'auto' to "
                        "track the difficulty (clamped to [13, 24])")


def _add_metrics_dump_arg(p: argparse.ArgumentParser) -> None:
    p.add_argument("--metrics-dump", metavar="PATH", default=None,
                   help="write a Prometheus text snapshot of the run's "
                        "telemetry registry to PATH on exit")
    p.add_argument("--flight-recorder", metavar="PATH", default=None,
                   help="arm the crash flight recorder: on abnormal exit "
                        "(uncaught exception, sim non-convergence) dump "
                        "events + causal logs + metrics snapshot to PATH "
                        "(env MPIBT_FLIGHT_RECORDER also arms it)")
    p.add_argument("--serve-metrics", metavar="PORT", type=int, default=None,
                   help="serve /metrics, /healthz, /events over HTTP for "
                        "the duration of the run (0 = ephemeral port, "
                        "announced on stderr; env MPIBT_METRICS_PORT also "
                        "enables it)")
    p.add_argument("--mesh-obs", metavar="DIR", default=None,
                   help="write this rank's telemetry shard (registry "
                        "snapshot + heartbeats + pipeline records) into "
                        "DIR on a background flusher, for mesh-wide "
                        "aggregation with python -m "
                        "mpi_blockchain_tpu.meshwatch (env MPIBT_MESH_OBS "
                        "also arms it; rank from --process-id or "
                        "MPIBT_MESH_RANK)")
    p.add_argument("--incident-dir", metavar="DIR", default=None,
                   help="arm the chainwatch live SLO watchdog with an "
                        "incident-bundle directory: anomaly rules run on "
                        "the existing telemetry cadences and a firing "
                        "rule writes a bounded, non-fatal evidence "
                        "bundle into DIR while the run keeps mining "
                        "(env MPIBT_INCIDENT_DIR also arms it; "
                        "--mesh-obs arms the rules without bundles)")
    p.add_argument("--fault-plan", metavar="PATH|seed:N", default=None,
                   help="arm the deterministic fault-injection harness "
                        "with a JSON fault plan (or a seed-derived one); "
                        "env MPIBT_FAULT_PLAN also arms it. Exit codes: "
                        "0 converged (possibly degraded, warned), "
                        "2 retries exhausted, 3 plan invalid/unexhausted "
                        "(docs/resilience.md)")


def _config_from(args) -> MinerConfig:
    if args.preset:
        return PRESETS[args.preset]
    return MinerConfig(difficulty_bits=args.difficulty, n_blocks=args.blocks,
                       batch_pow2=args.batch_pow2, n_miners=args.miners,
                       backend=args.backend, kernel=args.kernel)


def _init_world(args, cfg):
    """Joins the multi-process world when --coordinator is given.

    The reference's `mpirun -np N` across hosts: every process runs this
    same program over one global ('miners',) mesh; XLA routes winner-select
    over ICI/DCN. Returns (cfg, mesh, is_main).
    """
    if not args.coordinator:
        return cfg, None, True
    import jax

    from .parallel.distributed import init_distributed, make_global_miner_mesh
    from .resilience.policy import call_with_retry

    # A wedged coordinator or a slow-to-bind peer is the classic
    # transient launch fault: retry under the distributed.init budget
    # (capped exponential backoff, deterministic jitter) before giving
    # up with RetryExhausted (rc 2).
    call_with_retry(
        lambda: init_distributed(args.coordinator, args.num_processes,
                                 args.process_id),
        site="distributed.init")
    mesh = make_global_miner_mesh()
    cfg = dataclasses.replace(cfg, backend="tpu",
                              n_miners=len(jax.devices()))
    return cfg, mesh, jax.process_index() == 0


def _load_resume(path: str, cfg, mesh):
    """Loads the --resume checkpoint, recovering a torn tail if needed.
    Returns (node, error_or_None, recovery_report)."""
    from .utils.checkpoint import recover_chain

    from .resilience import RetryExhausted as _RetryExhausted
    from .resilience.policy import call_with_retry

    node, err, report = None, None, {}
    try:
        # The checkpoint.read budget covers transient FS errors; real
        # integrity damage is CheckpointError (never retried) and goes
        # through recover_chain's truncation path instead.
        node, report = call_with_retry(
            lambda: recover_chain(path, cfg.difficulty_bits),
            site="checkpoint.read")
    except _RetryExhausted as e:
        err = str(e.last)
    except (OSError, ValueError) as e:
        err = str(e)
    if mesh is not None:
        # Every process must resume from the SAME chain state, or they
        # issue different numbers of collective mine rounds and the world
        # deadlocks. Agree before the first device call; abort everywhere
        # on any failure or divergence.
        import numpy as np
        from jax.experimental import multihost_utils

        tip = node.tip_hash[:8] if node is not None else b"\0" * 8
        state = np.array([err is None,
                          node.height if node is not None else -1,
                          *tip], dtype=np.int64)
        rows = multihost_utils.process_allgather(state)
        if not (rows == rows[0]).all():
            err = (f"resume state diverges across processes "
                   f"(this process: {err or 'ok'})")
    return node, err, report


def _mesh_identity(args) -> "tuple[int, int]":
    """This process's (rank, world_size): the multi-process launch flags
    win; standalone ranks (one process per rank, no coordinator) are
    labeled via MPIBT_MESH_RANK / MPIBT_MESH_WORLD by whatever launched
    them. The single resolution point shared by the meshwatch shard
    writer and ElasticWorld, so the rank a process supervises AS is
    always the rank the oracle observes it UNDER."""
    from .telemetry.events import env_number as _env_number

    rank = getattr(args, "process_id", None)
    if rank is None:
        rank = _env_number("MPIBT_MESH_RANK", 0, cast=int, minimum=0)
    world = getattr(args, "num_processes", None)
    if world is None:
        world = _env_number("MPIBT_MESH_WORLD", 1, cast=int, minimum=1)
    return rank, world


def cmd_mine(args) -> int:
    import contextlib

    from .models.miner import Miner
    from .utils.logging import get_logger

    cfg = _config_from(args)
    if args.verbose:
        get_logger().setLevel("DEBUG")
    if args.serve is not None and args.fused:
        raise ConfigError(
            "--serve needs the per-block miner (drop --fused): the "
            "template feed rebinds Miner.payload_for at block "
            "boundaries, a seam the fused device loop never consults")
    world = None
    if args.elastic:
        if args.coordinator:
            raise ConfigError(
                "--elastic cannot ride a jax.distributed world (its "
                "size is pinned at init and cannot shrink); run elastic "
                "ranks as independent processes sharing --mesh-obs")
        if args.fused:
            raise ConfigError(
                "--elastic needs the per-block miner (drop --fused): "
                "the fused device loop has no per-block supervision "
                "point to evict and re-stripe at")
        from .resilience.elastic import (ElasticMeshBackend, ElasticMiner,
                                         ElasticWorld)
        rank, world_size = _mesh_identity(args)
        obs = args.mesh_obs or os.environ.get("MPIBT_MESH_OBS") or None
        if world_size > 1 and obs is None:
            # Without the shard oracle the supervisor is detection-blind:
            # a SIGKILL'd peer is never evicted and its stripes never
            # re-covered. Seeded mesh.rank_death plans still work (the
            # plan itself names the deaths), so warn rather than refuse.
            print("elastic: multi-rank world has no --mesh-obs/"
                  "MPIBT_MESH_OBS shard oracle — dead peers will not be "
                  "detected or evicted", file=sys.stderr, flush=True)
        world = ElasticWorld(world_size, rank, obs_dir=obs)
        backend = (ElasticMeshBackend(cfg)
                   if cfg.backend == "tpu" and cfg.n_miners > 1 else None)
        miner = ElasticMiner(cfg, world, backend=backend)
        mesh, is_main = None, True
    else:
        cfg, mesh, is_main = _init_world(args, cfg)
        if args.fused:
            from .models.fused import FusedMiner
            miner = FusedMiner(cfg, blocks_per_call=args.blocks_per_call,
                               mesh=mesh)
        elif mesh is not None:   # _init_world forces backend="tpu" here
            from .backend import backend_from_config
            miner = Miner(cfg, backend=backend_from_config(cfg, mesh=mesh))
        else:
            miner = Miner(cfg)
    if args.resume:
        node, err, report = _load_resume(args.resume, cfg, mesh)
        if err is not None:
            print(json.dumps({"event": "chain_mined", "error": err},
                             sort_keys=True))
            return 1
        miner.node = node
        if world is not None and report.get("mesh"):
            # The sidecar's membership restores the SHRUNKEN world: a
            # resumed survivor keeps its re-striped share instead of
            # re-assuming the seed world (and re-overlapping stripes
            # the survivors already re-covered).
            world.restore(report["mesh"])
        # Replay the progress heartbeat at the resumed height BEFORE the
        # first (possibly slow) sweep, so perfwatch /healthz sees the
        # recovery as live progress, not a stall inherited from the
        # crashed run.
        from .telemetry import heartbeat
        from .telemetry.events import emit_event
        heartbeat("miner_heartbeat").set(node.height)
        emit_event({"event": "checkpoint_resumed", "height": node.height,
                    "recovered": report.get("recovered", False),
                    "dropped_bytes": report.get("dropped_bytes", 0)})
        if report.get("recovered"):
            if report.get("dropped_bytes"):
                print(f"resume: torn checkpoint tail truncated to last "
                      f"valid block (height {node.height}, "
                      f"{report['dropped_bytes']} chain bytes dropped)",
                      file=sys.stderr)
            else:
                print(f"resume: checkpoint seal repaired (height "
                      f"{node.height}, no chain bytes lost)",
                      file=sys.stderr)
    # --blocks is the TARGET height, so a resumed run mines the remainder
    # (equal to "blocks to mine" when starting from genesis).
    remaining = max(0, cfg.n_blocks - miner.node.height)
    on_block = None
    if args.checkpoint_every:
        if args.checkpoint_every < 0:
            raise ConfigError(f"--checkpoint-every must be >= 1, "
                              f"got {args.checkpoint_every}")
        if not args.checkpoint:
            raise ConfigError("--checkpoint-every needs --checkpoint PATH "
                              "(where to save)")
        from .meshwatch.pipeline import profiler as _profiler
        from .resilience.policy import call_with_retry
        from .utils.checkpoint import save_chain as _periodic_save
        every = args.checkpoint_every

        def on_block(rec):
            # Retry transient FS errors under the checkpoint.write
            # budget — a periodic save must not kill a long mining run.
            # The save is timed as the dispatch pipeline's `checkpoint`
            # segment: it sits on the critical path between sweeps, so
            # it belongs in the bubble accounting.
            if rec.height % every == 0:
                with _profiler().segment_on_last("checkpoint"):
                    call_with_retry(
                        lambda: _periodic_save(
                            miner.node, args.checkpoint, cfg,
                            mesh=(world.membership() if world is not None
                                  else None)),
                        site="checkpoint.write")
        if not is_main:
            # Multi-process world: every rank mines the identical chain,
            # so only the main process writes the shared checkpoint —
            # N ranks racing os.replace on one path could publish a
            # payload/sidecar pair from different heights.
            on_block = None
    profile_ctx = contextlib.nullcontext()
    if args.profile:
        from .utils.profiling import trace_mining
        profile_ctx = trace_mining(args.profile)
    service_state = service_summary = None
    if args.serve is not None and is_main:
        # Only the main rank opens the door: every process mines the
        # identical chain, so N doors on one --serve port would just
        # race the bind (and the mesh view already aggregates the one
        # armed door through the shard `service` carriage).
        from .service import install_service
        service_state = install_service(miner, port=args.serve)
        print(f"serving chain on http://127.0.0.1:"
              f"{service_state.server.port} "
              f"(/submit /tx_status /chain /template)",
              file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    try:
        with profile_ctx:
            if args.fused:
                # The fused loop appends whole device spans; checkpoint
                # at span boundaries (every span IS >= 1 block of
                # progress).
                def _fused_save(height):
                    with _profiler().segment_on_last("checkpoint"):
                        _periodic_save(miner.node, args.checkpoint, cfg)
                miner.mine_chain(remaining, on_progress=(
                    _fused_save if on_block is not None else None))
            else:
                miner.mine_chain(remaining, on_block=on_block)
    finally:
        if service_state is not None:
            # Stats BEFORE teardown (the summary stamps them), and the
            # door closes on every exit path — a crashed mine must not
            # leave a live socket serving a dead miner.
            from .service import uninstall_service
            service_summary = service_state.stats()
            uninstall_service(service_state)
    wall = time.perf_counter() - t0
    if not is_main:      # non-zero processes mine but stay silent
        return 0
    if args.out:
        with open(args.out, "wb") as f:
            f.write(miner.node.save())
    if args.checkpoint:
        from .utils.checkpoint import save_chain
        save_chain(miner.node, args.checkpoint, cfg,
                   mesh=(world.membership() if world is not None
                         else None))
    summary = {
        "event": "chain_mined",
        "config": dataclasses.asdict(cfg),
        "height": miner.node.height,
        "tip_hash": miner.node.tip_hash.hex(),
        "wall_s": round(wall, 3),
        "fused": args.fused,
    }
    if not args.fused:
        summary.update(hashes_tried=miner.total_hashes(),
                       hashes_per_sec=round(miner.hashes_per_sec()),
                       backend=miner.backend.name)
    if service_summary is not None:
        summary["service"] = service_summary
    from .meshwatch.pipeline import pipeline_report
    from .telemetry.registry import default_registry as _default_registry
    pipe = pipeline_report()
    if pipe["dispatch_count"]:
        # The async-dispatch headline (ROADMAP item 1):
        # host_overlapped_fraction is how much host work hid behind
        # in-flight dispatches; bubble_fraction is the device idle share
        # the pipeline exists to close (docs/perfwatch.md).
        summary["pipeline"] = {
            "host_overlapped_fraction": pipe["host_overlapped_fraction"],
            "bubble_fraction": pipe["bubble_fraction"],
            "speculative_discards": int(
                sum(m.value for m in _default_registry().metrics()
                    if m.name == "speculative_discards_total")),
        }
    if world is not None:
        summary["mesh"] = world.summary()
        if hasattr(miner.backend, "n_live"):   # ElasticMeshBackend
            summary["mesh"]["device_mesh"] = miner.backend.summary()
        if getattr(args, "events_dump", None):
            # Like sim's --events-dump: a dump failure must not mask
            # the run's own outcome.
            try:
                world.dump_causal(args.events_dump,
                                  meta={"target_blocks": cfg.n_blocks,
                                        "difficulty_bits":
                                            cfg.difficulty_bits})
            except OSError as e:
                print(f"events-dump failed: {e}", file=sys.stderr)
    degradations = getattr(getattr(miner, "backend", None),
                           "degradations", [])
    if degradations:
        # "Converged after degradation": rc 0, but loudly — the run
        # finished on a lower ladder rung than it was asked for.
        summary["degraded"] = True
        summary["degraded_to"] = degradations[-1]["to"]
        print(f"warning: backend degraded "
              f"{' -> '.join(d['to'] for d in degradations)} "
              f"after repeated dispatch failure; run converged anyway",
              file=sys.stderr)
    print(json.dumps(summary, sort_keys=True))
    return 0


def cmd_verify(args) -> int:
    """Validates a saved chain file (PoW + linkage + determinism rules).
    Accepts both raw header files (--out) and sealed checkpoints
    (--checkpoint carries an integrity trailer, which is verified)."""
    from . import core
    from .utils.checkpoint import CheckpointError, open_checkpoint

    try:
        with open(args.chain, "rb") as f:
            blob = f.read()
    except OSError as e:
        print(json.dumps({"event": "chain_verified", "valid": False,
                          "error": str(e)}, sort_keys=True))
        return 1
    try:
        # The full integrity gate (trailer + sidecar): a torn sealed
        # checkpoint must read as invalid here, never as a valid
        # shorter chain.
        payload, sealed, _ = open_checkpoint(args.chain, blob)
    except CheckpointError as e:
        print(json.dumps({"event": "chain_verified", "valid": False,
                          "sealed": True, "error": str(e)},
                         sort_keys=True))
        return 1
    node = core.Node(args.difficulty, 0)
    ok = node.load(payload)
    print(json.dumps({
        "event": "chain_verified", "valid": bool(ok),
        "sealed": sealed,
        "height": node.height if ok else None,
        "tip_hash": node.tip_hash.hex() if ok else None,
    }, sort_keys=True))
    return 0 if ok else 1


def _sim_scenario_from(args):
    """Resolves the vectorized-engine scenario: a named scenario preset,
    or an ad-hoc one from --nodes/--steps/strategy/churn/retarget flags.
    Returns None when the legacy (real-chain) bus should run instead."""
    import dataclasses as _dc

    from .sim import (SCENARIO_PRESETS, AdversarySpec, ChurnSchedule,
                      LatencySpec, RetargetRule, Scenario)

    if args.preset in SCENARIO_PRESETS:
        if args.nodes is not None:
            raise ConfigError(
                f"--nodes cannot resize scenario preset {args.preset} "
                f"(its partitions/churn/adversaries are sized to "
                f"{SCENARIO_PRESETS[args.preset].n_nodes} nodes); "
                f"build an ad-hoc scenario with --nodes alone")
        sc = SCENARIO_PRESETS[args.preset]
        # Every explicitly-passed flag OVERRIDES the preset (an
        # explicit 0 wins too — the defaults are None sentinels); a
        # silently-dropped --strategy would be an attack that never ran.
        seed = sc.seed if args.seed is None else args.seed
        steps = sc.steps if args.steps is None else args.steps
        over: dict = {"seed": seed, "steps": steps}
        if args.difficulty is not None:
            over["difficulty_bits"] = args.difficulty
        if args.hashes_per_step is not None:
            over["hashes_per_step"] = args.hashes_per_step
        if args.converge_margin is not None:
            over["converge_margin"] = args.converge_margin
        if args.drop_rate is not None:
            over["drop_rate_pct"] = args.drop_rate
        if args.latency is not None:
            over["latency"] = LatencySpec.parse(args.latency)
        if args.retarget is not None:
            over["retarget"] = RetargetRule.parse(args.retarget)
        if args.strategy:
            over["adversaries"] = tuple(AdversarySpec.parse(s)
                                        for s in args.strategy)
        if args.churn is not None:
            over["churn"] = ChurnSchedule.from_seed(
                seed, sc.n_nodes, steps, args.churn)
        return _dc.replace(sc, **over)
    if args.nodes is None:
        # Legacy bus it is — but vectorized-engine-only flags must not
        # be silently ignored (a "flood attack" that never ran).
        vec_only = [flag for flag, value in (
            ("--strategy", args.strategy), ("--churn", args.churn),
            ("--steps", args.steps),
            ("--latency", args.latency),
            ("--hashes-per-step", args.hashes_per_step),
            ("--converge-margin", args.converge_margin))
            if value is not None and value != []]
        if vec_only:
            raise ConfigError(
                f"{'/'.join(vec_only)} need the vectorized engine: "
                f"pass --nodes N or a scenario preset "
                f"({', '.join(sorted(SCENARIO_PRESETS))})")
        return None
    if args.preset:
        # A legacy MinerConfig preset composed with --nodes would be
        # silently discarded by the vec engine — refuse instead.
        raise ConfigError(
            f"--preset {args.preset} is a legacy mining preset; with "
            f"--nodes use a scenario preset "
            f"({', '.join(sorted(SCENARIO_PRESETS))}) or drop --nodes")
    seed = 0 if args.seed is None else args.seed
    steps = 1000 if args.steps is None else args.steps
    return Scenario(
        n_nodes=args.nodes,
        steps=steps,
        seed=seed,
        difficulty_bits=(16 if args.difficulty is None
                         else args.difficulty),
        hashes_per_step=(32 if args.hashes_per_step is None
                         else args.hashes_per_step),
        retarget=(RetargetRule.parse(args.retarget)
                  if args.retarget else None),
        latency=LatencySpec.parse(args.latency or "1"),
        drop_rate_pct=args.drop_rate or 0,
        churn=ChurnSchedule.from_seed(seed, args.nodes, steps,
                                      args.churn or 0),
        adversaries=tuple(AdversarySpec.parse(s)
                          for s in (args.strategy or [])),
        converge_margin=(1000 if args.converge_margin is None
                         else args.converge_margin),
    )


def _cmd_sim_vec(args, scenario) -> int:
    """The vectorized scenario engine behind ``sim`` (1000-node scale)."""
    from .sim import run_scenario
    from .telemetry import flight_recorder

    held: dict = {}

    def _on_network(net) -> None:
        held["net"] = net
        if flight_recorder.installed():
            flight_recorder.register_network(net)

    t0 = time.perf_counter()
    net, summary = run_scenario(scenario, on_network=_on_network)
    wall = time.perf_counter() - t0
    if args.events_dump:
        try:
            net.dump_causal(args.events_dump,
                            meta={"preset": args.preset})
        except OSError as e:
            print(f"events-dump failed: {e}", file=sys.stderr)
    summary["wall_s"] = round(wall, 3)
    summary["steps_per_sec"] = round(scenario.steps / wall, 1) if wall \
        else None
    print(json.dumps(summary, sort_keys=True))
    if not summary["converged"]:
        flight_recorder.dump_now("vec sim non-convergence at cutoff")
        return 1
    return 0


def cmd_sim(args) -> int:
    """BASELINE config 5 from the command line: adversarial partition+reorg.
    Scenario presets (``--preset adversarial-1k``) and --nodes route to
    the vectorized engine instead of the real-chain bus."""
    from .simulation import run_adversarial
    from .telemetry import flight_recorder

    scenario = _sim_scenario_from(args)
    if scenario is not None:
        return _cmd_sim_vec(args, scenario)
    if args.seed is None:     # legacy bus: plain defaults
        args.seed = 0
    if args.drop_rate is None:
        args.drop_rate = 0

    retarget = None
    if args.retarget:
        from .sim import RetargetRule
        retarget = RetargetRule.parse(args.retarget)
    if args.preset:
        cfg = PRESETS[args.preset]
        target_height = cfg.n_blocks
    else:  # flags always take effect (difficulty defaults to the sim's 8)
        cfg = MinerConfig(
            difficulty_bits=8 if args.difficulty is None else args.difficulty,
            n_blocks=args.blocks, backend=args.backend,
            kernel=args.kernel, batch_pow2=args.batch_pow2)
        target_height = args.blocks

    held: dict = {}

    def _on_network(net) -> None:
        # Before the run starts: a non-converging run raises out of
        # run_adversarial, and the causal logs of the FAILED run are
        # exactly what --events-dump / the flight recorder must capture.
        held["net"] = net
        if flight_recorder.installed():
            flight_recorder.register_network(net)

    def _dump_events() -> None:
        # Like --metrics-dump: a dump failure must not mask the run's
        # own outcome (the sim result line + exit code still stand).
        if args.events_dump and "net" in held:
            try:
                held["net"].dump_causal(args.events_dump, meta={
                    "seed": args.seed, "groups": args.groups,
                    "partition_steps": args.partition_steps,
                    "drop_rate_pct": args.drop_rate,
                    "delay_steps": args.delay_steps,
                    "target_height": target_height,
                    "difficulty_bits": cfg.difficulty_bits})
            except OSError as e:
                print(f"events-dump failed: {e}", file=sys.stderr)

    try:
        net = run_adversarial(config=cfg,
                              partition_steps=args.partition_steps,
                              target_height=target_height,
                              nonce_budget=1 << args.nonce_budget_pow2,
                              delay_steps=args.delay_steps,
                              drop_rate_pct=args.drop_rate,
                              seed=args.seed, n_groups=args.groups,
                              retarget=retarget,
                              on_network=_on_network)
    except RuntimeError as e:  # Network.run: no convergence in max_steps
        if not hasattr(e, "network"):
            # Only Network.run's non-convergence error carries .network;
            # any other RuntimeError (backend/JAX infrastructure failure)
            # must keep its traceback — and reach the excepthook dump —
            # not be misreported as a consensus outcome.
            raise
        # A fault-injection run that never converges is the flight
        # recorder's home turf: dump now (the artifact must exist even
        # though this is a handled rc=1 exit, not a crash).
        flight_recorder.dump_now(f"sim non-convergence: {e}")
        _dump_events()
        print(json.dumps({"event": "sim_done", "converged": False,
                          "error": str(e)}, sort_keys=True))
        return 1
    _dump_events()
    tips = {n.node.tip_hash.hex() for n in net.nodes}
    degradations = [d for n in net.nodes
                    for d in getattr(n.backend, "degradations", [])]
    if degradations:
        print(f"warning: {len(degradations)} backend degradation(s) "
              f"during the sim; converged anyway", file=sys.stderr)
    out = {
        "event": "sim_done",
        "converged": net.converged(),
        "degraded": bool(degradations),
        "steps": net.step_count,
        "heights": [n.node.height for n in net.nodes],
        "tips": sorted(tips),
        "stats": [dataclasses.asdict(n.stats) for n in net.nodes],
        # Exact accounting check: height == mined + accepted + adopted
        # - reorged_away on every node (the suffix-sync stats contract).
        "stats_conserved": all(n.stats.conserved_height() == n.node.height
                               for n in net.nodes),
    }
    print(json.dumps(out, sort_keys=True))
    return 0 if net.converged() else 1


def cmd_info(args) -> int:
    """Topology/world introspection (the reference's rank/size reporting)."""
    import jax

    from .parallel.distributed import world_info

    info = world_info()
    info["platform"] = jax.default_backend()
    info["devices"] = [str(d) for d in jax.devices()]
    print(json.dumps(info, sort_keys=True))
    return 0


def cmd_serve(args) -> int:
    """Mine a chain WHILE serving the blockserve front door: submit /
    tx_status / chain / template over HTTP (docs/serving.md). The
    sugared form of `mine --serve` with the door knobs exposed; exits
    when the chain reaches --blocks (run a large --blocks for a
    long-lived door)."""
    from .models.miner import Miner
    from .service import (Mempool, TemplateFeed, install_service,
                          uninstall_service)

    cfg = _config_from(args)
    miner = Miner(cfg)
    mempool = Mempool(cap=args.mempool_cap)          # None -> env default
    feed = TemplateFeed(mempool, cfg, max_txs=args.template_txs)
    state = install_service(miner, port=args.port, host=args.host,
                            mempool=mempool, feed=feed,
                            deadline_s=args.deadline)
    print(json.dumps({
        "event": "service_started",
        "url": f"http://{args.host}:{state.server.port}",
        "endpoints": ["/submit", "/tx_status", "/chain", "/template",
                      "/metrics", "/healthz", "/events"]},
        sort_keys=True), file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    try:
        miner.mine_chain(cfg.n_blocks)
    finally:
        stats = state.stats()
        uninstall_service(state)
    print(json.dumps({
        "event": "chain_served",
        "height": miner.node.height,
        "tip_hash": miner.node.tip_hash.hex(),
        "wall_s": round(time.perf_counter() - t0, 3),
        "backend": miner.backend.name,
        "service": stats,
    }, sort_keys=True))
    return 0


def cmd_bench(args) -> int:
    from .bench_lib import bench_chain, run_bench

    if args.mode == "chain":
        result = bench_chain(n_blocks=args.blocks,
                             difficulty_bits=args.difficulty,
                             batch_pow2=(args.batch_pow2
                                         if args.batch_pow2 is not None
                                         else 24),
                             blocks_per_call=args.blocks_per_call,
                             n_miners=args.miners, kernel=args.kernel)
    else:
        # The raw sweep has no difficulty to track, so "auto" falls back
        # to the dispatch-amortized default.
        pow2 = args.batch_pow2 if isinstance(args.batch_pow2, int) else 28
        result = run_bench(backend=args.backend, seconds=args.seconds,
                           batch_pow2=pow2,
                           n_miners=args.miners, kernel=args.kernel)
    print(json.dumps(result, sort_keys=True))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="mpi_blockchain_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    p_mine = sub.add_parser("mine", help="mine a chain")
    _add_config_args(p_mine)
    p_mine.add_argument("--out", help="write the chain to this file")
    p_mine.add_argument("--verbose", action="store_true",
                        help="per-block JSON lines")
    p_mine.add_argument("--fused", action="store_true",
                        help="device-resident multi-block mine loop "
                             "(one device call per --blocks-per-call)")
    p_mine.add_argument("--blocks-per-call", type=int, default=16)
    p_mine.add_argument("--checkpoint",
                        help="save the chain + config sidecar here when done "
                             "(atomic write + integrity trailer)")
    p_mine.add_argument("--checkpoint-every", type=int, default=0,
                        metavar="N",
                        help="also save --checkpoint every N mined blocks "
                             "(every device span with --fused), so a "
                             "SIGKILL loses at most N blocks; resume with "
                             "--resume")
    p_mine.add_argument("--resume",
                        help="load this checkpoint (verifying integrity; "
                             "a torn tail is truncated to the last valid "
                             "block) and mine up to --blocks")
    p_mine.add_argument("--profile",
                        help="capture a jax.profiler device trace into this "
                             "logdir (view with ui.perfetto.dev)")
    p_mine.add_argument("--elastic", action="store_true",
                        help="rank-death survival (docs/resilience.md "
                             "§Elastic mesh): this rank sweeps its stripe "
                             "of the nonce space, evicts confirmed-dead "
                             "peers via the --mesh-obs shard oracle, "
                             "re-stripes over the survivors and keeps "
                             "mining; with a multi-device tpu backend the "
                             "sharded dispatch additionally runs under "
                             "the MPIBT_COLLECTIVE_TIMEOUT watchdog and "
                             "the mesh shrinks on suspicion (rank/world "
                             "from --process-id/--num-processes or "
                             "MPIBT_MESH_RANK/MPIBT_MESH_WORLD)")
    p_mine.add_argument("--serve", metavar="PORT", type=int, default=None,
                        help="open the blockserve front door on PORT "
                             "(0 = ephemeral) while mining: /submit "
                             "/tx_status /chain /template "
                             "(docs/serving.md); incompatible with "
                             "--fused")
    p_mine.add_argument("--events-dump", metavar="PATH", default=None,
                        help="with --elastic: write this rank's Lamport-"
                             "stamped causal log (mined blocks + "
                             "membership transitions) to PATH on exit — "
                             "byte-identical across same-seed "
                             "mesh.rank_death runs")
    _add_metrics_dump_arg(p_mine)
    p_mine.add_argument("--coordinator",
                        help="multi-process launch: coordinator host:port "
                             "(run the same command on every host; the "
                             "mpirun -np N equivalent)")
    p_mine.add_argument("--num-processes", type=int, default=None,
                        help="multi-process launch: world size")
    p_mine.add_argument("--process-id", type=int, default=None,
                        help="multi-process launch: this host's rank")
    p_mine.set_defaults(fn=cmd_mine)

    p_verify = sub.add_parser("verify", help="validate a saved chain file")
    p_verify.add_argument("--chain", required=True)
    p_verify.add_argument("--difficulty", type=int, required=True)
    p_verify.set_defaults(fn=cmd_verify)

    p_bench = sub.add_parser(
        "bench", help="raw hashes/sec (--mode sweep) or full-chain "
                      "wall-clock (--mode chain) measurement")
    p_bench.add_argument("--mode", choices=["sweep", "chain"],
                         default="sweep",
                         help="sweep: raw rate for --seconds; chain: mine "
                              "--blocks at --difficulty with the fused "
                              "device miner (--backend/--seconds ignored)")
    p_bench.add_argument("--backend", choices=["cpu", "tpu"], default="tpu")
    p_bench.add_argument("--seconds", type=float, default=5.0)
    # sweep default 28, not 20: below ~2^26 nonces/dispatch the measurement
    # is dominated by per-dispatch overhead, not the kernel (see
    # ops/sha256_pallas.py); bench_tpu clamps to 2^22 on CPU-only hosts.
    # chain default 24: the early-exit sweet spot at difficulty 24.
    # "auto" (chain mode) sizes the batch to the difficulty.
    p_bench.add_argument("--batch-pow2", type=_batch_pow2_arg, default=None)
    p_bench.add_argument("--miners", type=int, default=1)
    p_bench.add_argument("--kernel", choices=["auto", "jnp", "pallas"],
                         default="auto")
    p_bench.add_argument("--blocks", type=int, default=1000,
                         help="chain mode: blocks to mine")
    p_bench.add_argument("--difficulty", type=int, default=24,
                         help="chain mode: leading-zero bits")
    p_bench.add_argument("--blocks-per-call", type=int, default=100)
    _add_metrics_dump_arg(p_bench)
    p_bench.set_defaults(fn=cmd_bench)

    p_serve = sub.add_parser(
        "serve", help="mine while serving the blockserve HTTP front door "
                      "(submit/tx_status/chain/template; docs/serving.md)")
    _add_config_args(p_serve)
    p_serve.add_argument("--port", type=int, default=0,
                         help="door port (0 = ephemeral, announced on "
                              "stderr)")
    p_serve.add_argument("--host", default="127.0.0.1")
    p_serve.add_argument("--deadline", type=float, default=None,
                         metavar="SECONDS",
                         help="per-request deadline (default "
                              "MPIBT_SERVICE_DEADLINE, 5s): expired work "
                              "is dropped before it reaches the miner")
    p_serve.add_argument("--mempool-cap", type=int, default=None,
                         metavar="N",
                         help="bounded mempool capacity (default "
                              "MPIBT_MEMPOOL_CAP, 512)")
    p_serve.add_argument("--template-txs", type=int, default=None,
                         metavar="N",
                         help="max txs folded into one block template "
                              "(default MPIBT_TEMPLATE_TXS, 16)")
    _add_metrics_dump_arg(p_serve)
    p_serve.set_defaults(fn=cmd_serve)

    p_sim = sub.add_parser(
        "sim", help="adversarial simulation: the config-5 partition+reorg "
                    "bus, or the vectorized 1000-node scenario engine "
                    "(--preset adversarial-1k / --nodes N)")
    # Static name list: importing sim.scenario here would pull numpy
    # into EVERY CLI invocation (mine/verify/--help). A test pins this
    # literal against sim.SCENARIO_PRESETS so it cannot drift.
    p_sim.add_argument("--preset",
                       choices=sorted(PRESETS) + sorted(
                           SCENARIO_PRESET_NAMES))
    p_sim.add_argument("--difficulty", type=int, default=None,
                       help="leading-zero bits (default: sim-internal 8)")
    p_sim.add_argument("--blocks", type=int, default=8,
                       help="target height every node must converge to")
    p_sim.add_argument("--backend", choices=["cpu", "tpu"], default="cpu")
    p_sim.add_argument("--kernel", choices=["auto", "jnp", "pallas"],
                       default="auto")
    p_sim.add_argument("--batch-pow2", type=_batch_pow2_arg, default=12)
    p_sim.add_argument("--partition-steps", type=int, default=30,
                       help="steps the 2 groups stay partitioned")
    p_sim.add_argument("--nonce-budget-pow2", type=int, default=8,
                       help="log2 nonces each group tries per sim step")
    p_sim.add_argument("--delay-steps", type=int, default=1,
                       help="delivery delay in sim steps")
    p_sim.add_argument("--drop-rate", type=int, default=None,
                       help="%% of deliveries dropped (seeded, "
                            "deterministic; default 0)")
    p_sim.add_argument("--seed", type=int, default=None,
                       help="seed for the drop/scenario schedules "
                            "(default 0; overrides a scenario preset's "
                            "baked-in seed when given, 0 included)")
    p_sim.add_argument("--groups", type=int, default=2,
                       help="number of competing miner groups")
    p_sim.add_argument("--retarget", metavar="INT[:STEP[:MAX]]",
                       default=None,
                       help="height-scheduled difficulty retargeting: "
                            "+STEP bits every INT blocks, capped at MAX "
                            "(validated on sync adoption, both engines)")
    p_sim.add_argument("--nodes", type=int, default=None,
                       help="vectorized engine: network size (switches "
                            "sim to the batched scenario engine)")
    p_sim.add_argument("--steps", type=int, default=None,
                       help="vectorized engine: scenario horizon in "
                            "steps (default 1000)")
    p_sim.add_argument("--strategy", action="append", metavar="SPEC",
                       help="vectorized engine: adversary strategy, "
                            "repeatable — selfish:node=1,hashrate=8 | "
                            "eclipse:node=2,victim=5,start=50,until=120 "
                            "| flood:node=3,every=25")
    p_sim.add_argument("--churn", type=int, default=None, metavar="N",
                       help="vectorized engine: N seeded crash-restart "
                            "churn events across the horizon")
    p_sim.add_argument("--latency", default=None, metavar="N|LO-HI",
                       help="vectorized engine: delivery delay steps, "
                            "fixed (N, default 1) or seeded uniform "
                            "(LO-HI)")
    p_sim.add_argument("--hashes-per-step", type=int, default=None,
                       help="vectorized engine: per-node hashes/step in "
                            "the mining lottery (default 32)")
    p_sim.add_argument("--converge-margin", type=int, default=None,
                       help="vectorized engine: fault-free "
                            "reconciliation steps granted past the "
                            "horizon (default 1000)")
    p_sim.add_argument("--events-dump", metavar="PATH", default=None,
                       help="write every node's Lamport-stamped causal "
                            "event log to PATH on exit (read with "
                            "python -m mpi_blockchain_tpu.forensics)")
    _add_metrics_dump_arg(p_sim)
    p_sim.set_defaults(fn=cmd_sim)

    p_info = sub.add_parser("info", help="world/topology introspection "
                                         "(rank, size, devices)")
    p_info.set_defaults(fn=cmd_info)

    args = parser.parse_args(argv)
    fr_path = (getattr(args, "flight_recorder", None)
               or os.environ.get("MPIBT_FLIGHT_RECORDER"))
    if fr_path:
        from .telemetry import flight_recorder
        flight_recorder.install(fr_path)
        flight_recorder.register_context(command=args.command)
    fault_arg = getattr(args, "fault_plan", None)
    if fault_arg is None and hasattr(args, "fault_plan"):
        # Env fallback only for subcommands that take the flag
        # (mine/sim/bench) — same scoping rule as MPIBT_METRICS_PORT.
        fault_arg = os.environ.get("MPIBT_FAULT_PLAN") or None
    plan_armed = False
    metrics_port = getattr(args, "serve_metrics", None)
    if metrics_port is None and hasattr(args, "serve_metrics"):
        # Env fallback only for the subcommands that take the flag
        # (mine/sim/bench): verify/info have no run to observe, and an
        # exported MPIBT_METRICS_PORT must not surprise-bind ports there.
        from .telemetry.events import env_number
        metrics_port = env_number("MPIBT_METRICS_PORT", None, cast=int,
                                  minimum=0)
    metrics_server = None
    if metrics_port is not None:
        from .perfwatch.server import MetricsServer
        metrics_server = MetricsServer(port=metrics_port)
        try:
            port = metrics_server.start()
        except (OSError, OverflowError) as e:
            # A taken (or out-of-range) port must not kill the run it
            # was meant to observe.
            print(f"serve-metrics failed: {e}", file=sys.stderr)
            metrics_server = None
        else:
            print(f"serving metrics on http://127.0.0.1:{port} "
                  f"(/metrics /healthz /events)", file=sys.stderr,
                  flush=True)
    mesh_obs = getattr(args, "mesh_obs", None)
    if mesh_obs is None and hasattr(args, "mesh_obs"):
        # Env fallback only for subcommands that take the flag
        # (mine/sim/bench) — same scoping rule as MPIBT_METRICS_PORT.
        mesh_obs = os.environ.get("MPIBT_MESH_OBS") or None
    shard_armed = False
    # The exit status the final shard carries: overwritten on every
    # handled path below; an UNHANDLED exception leaves "error", so a
    # crashed rank can never read as cleanly finished in the mesh view.
    exit_status: int | str = "error"
    if mesh_obs:
        from .meshwatch import shard as _mesh_shard

        rank, world = _mesh_identity(args)
        try:
            _mesh_shard.install(mesh_obs, rank=rank, world_size=world)
        except OSError as e:
            # An unwritable shard dir must not kill the run it observes.
            print(f"mesh-obs failed: {e}", file=sys.stderr)
        else:
            shard_armed = True
            print(f"mesh-obs: rank {rank}/{world} shard -> {mesh_obs}",
                  file=sys.stderr, flush=True)
    incident_dir = getattr(args, "incident_dir", None)
    if incident_dir is None and hasattr(args, "incident_dir"):
        incident_dir = os.environ.get("MPIBT_INCIDENT_DIR") or None
    chainwatch_armed = False
    if incident_dir or shard_armed:
        # The live SLO watchdog: anomaly rules ride the cadences armed
        # above (the shard flush tick, the per-block observe call). An
        # incident directory adds the evidence bundles; a mesh-observed
        # run without one still signals (incident event + counter +
        # shard/healthz carriage) — so --mesh-obs alone arms the rules.
        from . import chainwatch
        chainwatch.install(incident_dir or None)
        chainwatch_armed = True
        if incident_dir:
            print(f"chainwatch: armed, incident bundles -> "
                  f"{incident_dir}", file=sys.stderr, flush=True)
    try:
        if fault_arg:
            from .resilience import injection
            from .resilience.faultplan import FaultPlan
            injection.arm(FaultPlan.parse_arg(fault_arg))
            plan_armed = True
            print(f"fault plan armed: {fault_arg}", file=sys.stderr,
                  flush=True)
        rc = args.fn(args)
        if plan_armed:
            # Strict plans demand every fault actually fired; an
            # unexhausted plan is its own failure class (rc 3), distinct
            # from both convergence (0) and exhausted retries (2). The
            # check only gates SUCCESSFUL runs: a run that already
            # failed (rc != 0) keeps its own exit code — an unfired
            # fault must never mask the run's own failure.
            plan_armed = False
            from .resilience import injection
            injection.disarm(strict=(rc == 0))
        exit_status = rc
        return rc
    except FaultPlanError as e:
        # Before ConfigError: FaultPlanError subclasses it, and CI must
        # be able to tell "bad/unexhausted fault plan" (3) from "bad
        # config / exhausted retries" (2).
        print(json.dumps({"event": "error", "kind": "fault_plan",
                          "error": str(e)}, sort_keys=True))
        exit_status = 3
        return 3
    except RetryExhausted as e:
        # The policy layer gave up after every attempt and every ladder
        # rung: a clean, distinguishable failure — not a traceback.
        print(json.dumps({"event": "error", "kind": "retry_exhausted",
                          "site": e.site, "error": str(e)},
                         sort_keys=True))
        exit_status = 2
        return 2
    except ConfigError as e:
        # Config/topology errors (oversubscribed mesh, bad kernel/batch,
        # invalid checkpoint) surface as one clean JSON line, not a
        # traceback — the launch-form contract of the reference's CLI.
        # Only the dedicated ConfigError class gets this treatment; any
        # other exception (including plain ValueError from a genuine bug)
        # keeps its traceback.
        print(json.dumps({"event": "error", "error": str(e)},
                         sort_keys=True))
        exit_status = 2
        return 2
    finally:
        if plan_armed:
            # Error paths disarm WITHOUT the strict check: an unfired
            # fault must never mask the run's own failure.
            from .resilience import injection
            injection.disarm()
        # Dump on EVERY exit path, rc != 0 and raises included (e.g. a
        # non-converged sim or an exhausted nonce space): the metrics of
        # a failed run are exactly what a post-mortem needs. A dump
        # failure must not mask the run's own outcome.
        if getattr(args, "metrics_dump", None):
            from .telemetry import dump_metrics
            try:
                dump_metrics(args.metrics_dump)
            except OSError as e:
                print(f"metrics-dump failed: {e}", file=sys.stderr)
        # The FINAL shard says goodbye AND how it went: rc 0 reads as
        # `finished` in the merged mesh view, any other rc (or "error"
        # for an uncaught exception passing through here) as `failed` —
        # a badly-exited rank must never look cleanly done. A rank that
        # dies before reaching here is the stale-rank case instead.
        if shard_armed:
            from .meshwatch import shard as _mesh_shard
            _mesh_shard.uninstall(status=exit_status)
        # AFTER the final shard write: the goodbye shard still carries
        # any open incidents; only then does the watchdog disarm.
        if chainwatch_armed:
            from . import chainwatch
            chainwatch.uninstall()
        # The endpoint must release its port on EVERY exit path — an
        # uncaught exception passes through here on its way to the
        # flight-recorder excepthook, and a wedged scrape thread is
        # daemonic so close() cannot hang the exit.
        if metrics_server is not None:
            metrics_server.close()


if __name__ == "__main__":
    sys.exit(main())
