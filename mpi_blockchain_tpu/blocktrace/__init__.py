"""blocktrace — per-block critical-path attribution across ranks.

Every existing lens is an *aggregate*: span summaries say how much time a
layer ate overall, the pipeline report prices overlap and bubble across a
whole run, causal logs order events without wall time, perfwatch history
tracks headline rates. None of them can answer "where did block N's wall
time go, and what was on its critical path?" — the per-unit question the
async-pipelined-dispatch refactor (ROADMAP item 4) and the op-cut work
(item 2) are judged on.

This package closes that gap with three pieces:

* **context** (this module re-exports it) — a thread-local *block trace
  context*: a ``(height, template, rank)`` identity pushed by
  ``trace_block(height, template=...)`` around everything a block
  traverses. The telemetry layer consults it implicitly: pipeline
  profiler segments recorded inside the context carry a ``height`` (so
  a fused batch's per-block validate/append segments are individually
  attributable), and ``emit_event`` stamps a ``trace`` field onto every
  event emitted in scope (retry, degradation, collective-timeout,
  checkpoint events all join the block that suffered them).

* **critical_path** — the mesh-wide analyzer: joins pipeline records
  (in-process or from ``--mesh-obs`` shards) into a per-block waterfall
  — per-stage *exclusive* wall time, the single longest dependency
  chain, a device / collective-wait / host split, and gap accounting
  such that ``sum(stages) + gap == wall`` exactly (no double-count:
  every instant of the block's wall is attributed to at most one
  stage). Deterministic: a pure function of its record set.

* **overhead** — the telemetry self-audit: always-on tracing must stay
  honest, so ``measure_trace_overhead`` prices the instrumentation
  itself (instrumented vs ``MPIBT_TELEMETRY_OFF`` sweep throughput
  delta) as the ``trace_overhead`` bench section, recorded to
  PERF_HISTORY.jsonl and gated (< 3%) by ``perfwatch check``.

Surfaces: ``python -m mpi_blockchain_tpu.perfwatch critical-path``
(text / ``--json`` / ``--trace`` Perfetto export with the critical path
as a highlighted flow) and ``python -m mpi_blockchain_tpu.blocktrace
smoke`` (the ``make trace-smoke`` gate).

Import discipline: this ``__init__`` re-exports ONLY the context layer —
``meshwatch.pipeline`` imports it from inside the telemetry hot path, so
pulling the analyzer (which imports meshwatch back) here would cycle.
Analyzer/overhead callers import their submodules explicitly.
"""
from __future__ import annotations

from .context import (BlockTrace, current_trace, trace_block,  # noqa: F401
                      trace_dict)
