"""Perfetto export of a critical-path report: the pipeline's wall-clock
rank/stage rows (reused verbatim from ``meshwatch.pipeline``) plus one
dedicated **critical path** process row whose slices are each block's
critical-path runs, chained by flow events — the highlighted arrow trail
is the block's longest dependency chain on ui.perfetto.dev.

Flow events pair by (cat, id); one flow per block (id = the height) with
a start (``ph: s``) on the first run, steps (``ph: t``) on each middle
run, and a finish (``ph: f``, ``bp: e``) on the last — each bound to its
run's slice by landing inside it.
"""
from __future__ import annotations

from ..meshwatch.pipeline import to_chrome_trace

#: The critical-path row's pid — far above any real rank.
CRITICAL_PID = 999999


def to_critical_path_trace(report: dict, records: list[dict]) -> dict:
    """Chrome trace-event JSON: base pipeline rows + the critical-path
    row. Deterministic for a deterministic (report, records) pair."""
    trace = to_chrome_trace(records)
    events = trace["traceEvents"]
    epoch = trace.get("metadata", {}).get("epoch_unix_s")
    if epoch is None:       # no segments at all: nothing to highlight
        return trace
    events.append({"ph": "M", "name": "process_name", "pid": CRITICAL_PID,
                   "tid": 0, "args": {"name": "critical path"}})
    for h in report["heights"]:
        block = report["blocks"][str(h)]
        ranks = block["ranks"]
        straggler = str(block["critical_rank"])
        base_us = (ranks[straggler]["t0"] - epoch) * 1e6
        events.append({"ph": "M", "name": "thread_name",
                       "pid": CRITICAL_PID, "tid": int(h),
                       "args": {"name": f"block {h}"}})
        runs = block["critical_path"]
        for i, run in enumerate(runs):
            ts = round(base_us + run["start_ms"] * 1e3, 3)
            dur = round(max(run["ms"], 1e-4) * 1e3, 3)
            events.append({
                "ph": "X", "cat": "critical_path",
                "name": f"critical:{run['stage']}",
                "pid": CRITICAL_PID, "tid": int(h), "ts": ts, "dur": dur,
                "args": {"height": int(h), "rank": run["rank"],
                         "ms": run["ms"]},
            })
            if len(runs) < 2:    # nothing to chain: no dangling flow
                continue
            flow = {"cat": "critical_path", "name": f"block {h}",
                    "id": int(h), "pid": CRITICAL_PID, "tid": int(h)}
            mid_ts = round(ts + dur / 2, 3)
            if i == 0:
                events.append({**flow, "ph": "s", "ts": mid_ts})
            elif i == len(runs) - 1:
                events.append({**flow, "ph": "f", "bp": "e", "ts": mid_ts})
            else:
                events.append({**flow, "ph": "t", "ts": mid_ts})
    return trace
