"""Perfetto export of a critical-path report: the pipeline's wall-clock
rank/stage rows (reused verbatim from ``meshwatch.pipeline``) plus one
dedicated **critical path** process row whose slices are each block's
critical-path runs, chained by flow events — the highlighted arrow trail
is the block's longest dependency chain on ui.perfetto.dev.

Flow events pair by (cat, id); one flow per block (id = the height) with
a start (``ph: s``) on the first run, steps (``ph: t``) on each middle
run, and a finish (``ph: f``, ``bp: e``) on the last — each bound to its
run's slice by landing inside it.

When per-rank ``skew_spans`` (meshprof) are passed, a **collective
rendezvous** process row is added: one thread per rank, one slice per
span, named by its collective site — laid on the same wall axis as the
pipeline rows, the staircase of enters at one (site, round) IS the skew
the analyzer prices.

When ``incidents`` (chainwatch incident records, as carried by shards /
``/incidents``) are passed, an **incidents** annotation row is added:
one instant marker per incident at its ``opened_at`` wall time, named
``incident:<rule>`` — scrub to the marker and the surrounding pipeline /
collective /critical-path slices ARE the evidence window the incident
bundle snapshotted.

When per-rank ``compiles`` (dispatchwatch compile events, as carried by
the shard ``compiles`` key) are passed, an **xla compiles** process row
is added: one slice per observed backend compile, named
``compile:<site>`` — a compile slice overlapping a mining dispatch on
the same wall axis is a recompile stealing device time from the sweep.
"""
from __future__ import annotations

from ..meshwatch.pipeline import to_chrome_trace

#: The critical-path row's pid — far above any real rank.
CRITICAL_PID = 999999
#: The collective-rendezvous row's pid — just under the critical path.
COLLECTIVE_PID = 999998
#: The chainwatch incident-annotation row's pid — under the collectives.
INCIDENT_PID = 999997
#: The dispatchwatch XLA-compile row's pid — under the incidents.
COMPILE_PID = 999996


def _collective_lane(events: list, skew_spans: dict, epoch: float) -> None:
    """Append the collective-rendezvous process row: tid = rank, one
    ``ph: X`` slice per span (name = site; args carry the join key)."""
    events.append({"ph": "M", "name": "process_name",
                   "pid": COLLECTIVE_PID, "tid": 0,
                   "args": {"name": "collective rendezvous"}})
    for rank in sorted(skew_spans, key=int):
        events.append({"ph": "M", "name": "thread_name",
                       "pid": COLLECTIVE_PID, "tid": int(rank),
                       "args": {"name": f"rank {rank}"}})
        for rec in skew_spans[rank]:
            try:
                ts = (float(rec["t_enter"]) - epoch) * 1e6
                dur = (float(rec["t_exit"]) - float(rec["t_enter"])) * 1e6
                site = str(rec["site"])
            except (KeyError, TypeError, ValueError):
                continue
            args = {"site": site, "round": rec.get("round"),
                    "ok": rec.get("ok", True)}
            if rec.get("height") is not None:
                args["height"] = rec["height"]
            events.append({
                "ph": "X", "cat": "collective", "name": site,
                "pid": COLLECTIVE_PID, "tid": int(rank),
                "ts": round(ts, 3), "dur": round(max(dur, 1e-1), 3),
                "args": args,
            })


def _incident_lane(events: list, incidents: list, epoch: float) -> None:
    """Append the chainwatch annotation row: one process-scoped instant
    marker per incident at its ``opened_at``, args carrying the record's
    identity (rule, severity, seq, implicated heights, firing rank)."""
    events.append({"ph": "M", "name": "process_name",
                   "pid": INCIDENT_PID, "tid": 0,
                   "args": {"name": "chainwatch incidents"}})
    for inc in incidents:
        try:
            ts = (float(inc["opened_at"]) - epoch) * 1e6
            rule = str(inc["rule"])
        except (KeyError, TypeError, ValueError):
            continue
        args = {"rule": rule,
                "severity": inc.get("severity", ""),
                "incident_seq": inc.get("incident_seq"),
                "heights": list(inc.get("heights") or ())}
        if inc.get("rank") is not None:
            args["rank"] = inc["rank"]
        events.append({
            "ph": "i", "s": "p", "cat": "incident",
            "name": f"incident:{rule}",
            "pid": INCIDENT_PID, "tid": 0, "ts": round(ts, 3),
            "args": args,
        })


def _compile_lane(events: list, compiles: dict, epoch: float) -> None:
    """Append the XLA-compile process row: tid = rank, one ``ph: X``
    slice per observed backend compile. A compile event's ``t`` stamp
    is its END (the listener reports a completed duration), so the
    slice opens ``ms`` earlier."""
    events.append({"ph": "M", "name": "process_name",
                   "pid": COMPILE_PID, "tid": 0,
                   "args": {"name": "xla compiles"}})
    for rank in sorted(compiles, key=int):
        events.append({"ph": "M", "name": "thread_name",
                       "pid": COMPILE_PID, "tid": int(rank),
                       "args": {"name": f"rank {rank}"}})
        for rec in compiles[rank]:
            try:
                ms = float(rec["ms"])
                ts = (float(rec["t"]) - epoch) * 1e6 - ms * 1e3
                site = str(rec["site"])
            except (KeyError, TypeError, ValueError):
                continue
            events.append({
                "ph": "X", "cat": "compile", "name": f"compile:{site}",
                "pid": COMPILE_PID, "tid": int(rank),
                "ts": round(ts, 3), "dur": round(max(ms * 1e3, 1e-1), 3),
                "args": {"site": site, "ms": ms,
                         "stage": rec.get("stage", "backend_compile")},
            })


def to_critical_path_trace(report: dict, records: list[dict],
                           skew_spans: dict | None = None,
                           incidents: list | None = None,
                           compiles: dict | None = None) -> dict:
    """Chrome trace-event JSON: base pipeline rows + the critical-path
    row (+ the collective lane when per-rank ``skew_spans`` — a mapping
    rank -> span list, as carried by meshwatch shards — are passed,
    + the incident annotation lane when chainwatch ``incidents`` —
    rank-stamped records as served by ``/incidents`` — are passed,
    + the xla-compile lane when per-rank ``compiles`` — a mapping
    rank -> compile-event list, as carried by the shard ``compiles``
    key — are passed). Deterministic for a deterministic
    (report, records) pair."""
    trace = to_chrome_trace(records)
    events = trace["traceEvents"]
    epoch = trace.get("metadata", {}).get("epoch_unix_s")
    if skew_spans:
        enters = [float(r["t_enter"]) for spans in skew_spans.values()
                  for r in spans if r.get("t_enter") is not None]
        if enters:
            # Spans share the pipeline's wall-anchored axis; with no
            # pipeline segments at all, the earliest enter is the epoch.
            lane_epoch = epoch if epoch is not None else min(enters)
            _collective_lane(events, skew_spans, lane_epoch)
            trace.setdefault("metadata", {}).setdefault(
                "epoch_unix_s", lane_epoch)
    if incidents:
        opened = [float(i["opened_at"]) for i in incidents
                  if i.get("opened_at") is not None]
        if opened:
            lane_epoch = trace.get("metadata", {}).get("epoch_unix_s")
            lane_epoch = lane_epoch if lane_epoch is not None \
                else min(opened)
            _incident_lane(events, incidents, lane_epoch)
            trace.setdefault("metadata", {}).setdefault(
                "epoch_unix_s", lane_epoch)
    if compiles:
        ends = [float(r["t"]) for recs in compiles.values()
                for r in recs if r.get("t") is not None]
        if ends:
            lane_epoch = trace.get("metadata", {}).get("epoch_unix_s")
            lane_epoch = lane_epoch if lane_epoch is not None \
                else min(ends)
            _compile_lane(events, compiles, lane_epoch)
            trace.setdefault("metadata", {}).setdefault(
                "epoch_unix_s", lane_epoch)
    if epoch is None:       # no segments at all: nothing to highlight
        return trace
    events.append({"ph": "M", "name": "process_name", "pid": CRITICAL_PID,
                   "tid": 0, "args": {"name": "critical path"}})
    for h in report["heights"]:
        block = report["blocks"][str(h)]
        ranks = block["ranks"]
        straggler = str(block["critical_rank"])
        base_us = (ranks[straggler]["t0"] - epoch) * 1e6
        events.append({"ph": "M", "name": "thread_name",
                       "pid": CRITICAL_PID, "tid": int(h),
                       "args": {"name": f"block {h}"}})
        runs = block["critical_path"]
        for i, run in enumerate(runs):
            ts = round(base_us + run["start_ms"] * 1e3, 3)
            dur = round(max(run["ms"], 1e-4) * 1e3, 3)
            events.append({
                "ph": "X", "cat": "critical_path",
                "name": f"critical:{run['stage']}",
                "pid": CRITICAL_PID, "tid": int(h), "ts": ts, "dur": dur,
                "args": {"height": int(h), "rank": run["rank"],
                         "ms": run["ms"]},
            })
            if len(runs) < 2:    # nothing to chain: no dangling flow
                continue
            flow = {"cat": "critical_path", "name": f"block {h}",
                    "id": int(h), "pid": CRITICAL_PID, "tid": int(h)}
            mid_ts = round(ts + dur / 2, 3)
            if i == 0:
                events.append({**flow, "ph": "s", "ts": mid_ts})
            elif i == len(runs) - 1:
                events.append({**flow, "ph": "f", "bp": "e", "ts": mid_ts})
            else:
                events.append({**flow, "ph": "t", "ts": mid_ts})
    return trace
