"""Per-block critical-path analysis over dispatch pipeline records.

Input: the pipeline profiler's record dicts — in-process, or
concatenated from a ``--mesh-obs`` shard directory (records carry their
rank, so the mesh-wide join needs no extra bookkeeping). Output: one
waterfall per mined height.

**Attribution.** A segment belongs to a height when (most specific
wins):

1. it carries its own ``height`` stamp — recorded inside a
   ``trace_block`` scope (the fused drain loop's per-block
   validate/append segments, the CLI's checkpoint seam);
2. its record's meta carries ``height`` + ``k`` (a fused batch): block
   ``height+j+1`` gets the slice from its estimated start within the
   segment (the fori_loop mines sequentially on device, so start_j =
   ``t0 + j*(t1-t0)/k``) to the segment's END — a batched block cannot
   complete before the whole batch materializes, so the tail of the
   window is genuinely part of ITS wall too. Sibling slices overlap by
   design: conservation is per block, never summed across blocks.
   Slices are flagged ``estimated`` (except ``k == 1``, where the whole
   window belongs to the single block exactly);
3. its record's meta carries ``height`` alone (a per-block sweep
   dispatch): the whole segment joins that height.

Segments with none of the three are counted ``unattributed`` — never
silently dropped into a block.

**Exclusive timeline (the no-double-count rule).** Per (height, rank)
the block's wall is the span from its earliest segment start to its
latest end. Every instant of that wall is attributed to exactly ONE
stage — the highest-priority stage active at that instant
(``device > collective > validate > append > checkpoint > enqueue``:
when host work overlaps an in-flight device window the block is waiting
on the device, so the device owns the instant and the hidden host work
costs nothing — exactly the pipelining credit the overlap report
grants) — or to ``gap`` when no segment is active. By construction
``sum(stages) + gap == wall`` with no double-count, which is the
conservation property tests/test_blocktrace.py pins.

**Critical path.** The maximal runs of that exclusive timeline, in
time order: at every instant the run's stage is what the block was
actually waiting on, so the run list IS the longest dependency chain —
pipelined overlap collapses onto the blocking stage instead of being
counted twice.

**Mesh rollup.** Ranks keep separate waterfalls (clock comparability
across hosts is not assumed, and elastic ranks mine rank-local chains);
the block's headline numbers come from its *straggler* — the rank with
the largest wall — because the slowest rank is where the block's
critical chain lives. ``gap_pct`` headline = the straggler's.

Deterministic: a pure function of the record set (record order
irrelevant — segments are sorted), so byte-identical inputs produce
byte-identical reports.
"""
from __future__ import annotations

import weakref

from ..telemetry.registry import default_registry, telemetry_disabled

#: Exclusive-attribution priority, most critical first. Unknown stages
#: rank after every known one (alphabetically, for determinism).
STAGE_PRIORITY = ("device", "collective", "validate", "append",
                  "checkpoint", "enqueue")

#: A block's critical path is "complete" when its gap share stays under
#: this (the trace-smoke gate asserts < 5).
COMPLETE_GAP_PCT = 5.0


def _priority(stage: str) -> tuple:
    try:
        return (STAGE_PRIORITY.index(stage),)
    except ValueError:
        return (len(STAGE_PRIORITY), stage)


def segments_by_block(records: list[dict]) -> tuple[dict, int]:
    """Groups every attributable segment slice as
    ``{height: {rank: [slice, ...]}}``; returns ``(blocks,
    n_unattributed)``. Slices are ``{"stage", "t0", "t1", "rank",
    "dispatch", "estimated"}``."""
    blocks: dict[int, dict[int, list[dict]]] = {}
    unattributed = 0

    def _add(height: int, rank: int, seg: dict, t0: float, t1: float,
             estimated: bool, dispatch) -> None:
        if t1 <= t0:
            return
        blocks.setdefault(int(height), {}).setdefault(rank, []).append(
            {"stage": str(seg["stage"]), "t0": float(t0), "t1": float(t1),
             "rank": rank, "dispatch": dispatch,
             "estimated": bool(estimated)})

    for r in records:
        rank = int(r.get("rank", 0))
        meta = r.get("meta") or {}
        dispatch = r.get("dispatch")
        try:
            base_h = int(meta["height"])
        except (KeyError, TypeError, ValueError):
            base_h = None
        try:
            k = int(meta.get("k") or 0)
        except (TypeError, ValueError):
            k = 0
        for seg in r.get("segments") or []:
            t0, t1 = float(seg["t0"]), float(seg["t1"])
            if seg.get("height") is not None:
                _add(int(seg["height"]), rank, seg, t0, t1, False, dispatch)
            elif base_h is not None and k > 1:
                step = (t1 - t0) / k
                for j in range(k):
                    _add(base_h + j + 1, rank, seg, t0 + j * step,
                         t1, True, dispatch)
            elif base_h is not None and k == 1:
                # A 1-block batch needs no sequential split: the whole
                # window belongs to the single block, exactly.
                _add(base_h + 1, rank, seg, t0, t1, False, dispatch)
            elif base_h is not None:
                _add(base_h, rank, seg, t0, t1, False, dispatch)
            else:
                unattributed += 1
    return blocks, unattributed


def _waterfall(slices: list[dict]) -> dict:
    """One (height, rank)'s exclusive timeline: per-stage exclusive ms,
    gap, critical-path runs. ``sum(stages_ms) + gap_ms == wall_ms``."""
    slices = sorted(slices, key=lambda s: (s["t0"], s["t1"], s["stage"]))
    t_lo = min(s["t0"] for s in slices)
    t_hi = max(s["t1"] for s in slices)
    stages_ms: dict[str, float] = {}
    gap_ms = 0.0
    runs: list[dict] = []
    estimated = False
    if all(a["t1"] <= b["t0"] for a, b in zip(slices, slices[1:])):
        # Fast path — the live per-block shape: chained segments never
        # overlap, so each slice owns its own interval outright and the
        # exclusive timeline is just slices + gaps. Same output as the
        # sweep below (the conservation tests run both shapes).
        prev_end = t_lo
        for s in slices:
            if s["t0"] > prev_end:
                gap_ms += (s["t0"] - prev_end) * 1e3
                runs.append({"stage": "gap", "rank": None,
                             "t0": prev_end, "t1": s["t0"]})
            stage = s["stage"]
            stages_ms[stage] = (stages_ms.get(stage, 0.0)
                                + (s["t1"] - s["t0"]) * 1e3)
            estimated = estimated or s["estimated"]
            if (runs and runs[-1]["stage"] == stage
                    and runs[-1]["rank"] == s["rank"]):
                runs[-1]["t1"] = s["t1"]
            else:
                runs.append({"stage": stage, "rank": s["rank"],
                             "t0": s["t0"], "t1": s["t1"]})
            prev_end = s["t1"]
    else:
        points = sorted({p for s in slices for p in (s["t0"], s["t1"])})
        for a, b in zip(points, points[1:]):
            active = [s for s in slices if s["t0"] < b and s["t1"] > a]
            if not active:
                owner, rank = "gap", None
                gap_ms += (b - a) * 1e3
            else:
                best = min(active, key=lambda s: _priority(s["stage"]))
                owner = best["stage"]
                owners = [s for s in active if s["stage"] == owner]
                rank = min(s["rank"] for s in owners)
                estimated = estimated or any(s["estimated"]
                                             for s in owners)
                stages_ms[owner] = (stages_ms.get(owner, 0.0)
                                    + (b - a) * 1e3)
            if (runs and runs[-1]["stage"] == owner
                    and runs[-1]["rank"] == rank):
                runs[-1]["t1"] = b
            else:
                runs.append({"stage": owner, "rank": rank,
                             "t0": a, "t1": b})
    wall_ms = (t_hi - t_lo) * 1e3
    critical = [
        {"stage": r["stage"], "rank": r["rank"],
         "start_ms": round((r["t0"] - t_lo) * 1e3, 4),
         "ms": round((r["t1"] - r["t0"]) * 1e3, 4)}
        for r in runs if r["stage"] != "gap"]
    return {
        "t0": t_lo,
        "wall_ms": round(wall_ms, 4),
        "stages_ms": {k: round(v, 4) for k, v in sorted(stages_ms.items())},
        "gap_ms": round(gap_ms, 4),
        "gap_pct": round(100.0 * gap_ms / wall_ms, 2) if wall_ms else 0.0,
        "estimated": estimated,
        "critical_path": critical,
        "split": {
            "device_ms": round(stages_ms.get("device", 0.0), 4),
            "collective_ms": round(stages_ms.get("collective", 0.0), 4),
            "host_ms": round(sum(v for k, v in stages_ms.items()
                                 if k not in ("device", "collective")), 4),
            "gap_ms": round(gap_ms, 4),
        },
    }


def _observe_waterfall(slices: list[dict]) -> dict:
    """Lean exclusive accounting for the live observe path: per-stage
    exclusive ms + gap only. The full ``_waterfall`` also builds the
    critical-path runs, split and rounded report fields nobody reads on
    the mining hot path — this trimmed twin is what the telemetry
    overhead audit prices per block, so every instruction here costs
    budget. Overlapping slices (a fused batch) fall back to the full
    sweep; its output is a superset of this shape."""
    slices = sorted(slices, key=lambda s: (s["t0"], s["t1"], s["stage"]))
    if all(a["t1"] <= b["t0"] for a, b in zip(slices, slices[1:])):
        t_lo = slices[0]["t0"]
        stages_ms: dict[str, float] = {}
        gap = 0.0
        prev = t_lo
        for s in slices:
            if s["t0"] > prev:
                gap += s["t0"] - prev
            stage = s["stage"]
            stages_ms[stage] = (stages_ms.get(stage, 0.0)
                                + (s["t1"] - s["t0"]) * 1e3)
            prev = s["t1"]
        wall = prev - t_lo
        return {
            "wall_ms": round(wall * 1e3, 4),
            "stages_ms": stages_ms,
            "gap_ms": gap * 1e3,
            "gap_pct": (round(100.0 * gap / wall, 2) if wall else 0.0),
        }
    return _waterfall(slices)


def critical_path_report(records: list[dict],
                         height: int | None = None) -> dict:
    """The per-block critical-path report of a record set; ``height``
    restricts to one block. See the module docstring for semantics."""
    blocks, unattributed = segments_by_block(records)
    if height is not None:
        blocks = ({int(height): blocks[int(height)]}
                  if int(height) in blocks else {})
    out_blocks: dict[str, dict] = {}
    for h in sorted(blocks):
        per_rank = {str(rank): _waterfall(slices)
                    for rank, slices in sorted(blocks[h].items())}
        straggler = max(sorted(per_rank),
                        key=lambda r: per_rank[r]["wall_ms"])
        head = per_rank[straggler]
        out_blocks[str(h)] = {
            "height": h,
            "ranks": per_rank,
            "critical_rank": int(straggler),
            "wall_ms": head["wall_ms"],
            "stages_ms": head["stages_ms"],
            "gap_ms": head["gap_ms"],
            "gap_pct": head["gap_pct"],
            "split": head["split"],
            "estimated": head["estimated"],
            "critical_path": head["critical_path"],
            "complete": bool(head["critical_path"]
                             and head["gap_pct"] <= COMPLETE_GAP_PCT),
        }
    return {
        "version": 1,
        "heights": sorted(blocks),
        "blocks": out_blocks,
        "record_count": len(records),
        "unattributed_segments": unattributed,
    }


def render_text(report: dict) -> str:
    """Human waterfall rendering of a critical-path report."""
    lines: list[str] = []
    for h in report["heights"]:
        b = report["blocks"][str(h)]
        lines.append(
            f"block {h}: wall {b['wall_ms']:.3f} ms, gap "
            f"{b['gap_pct']:.2f}%, critical rank {b['critical_rank']}"
            f"{' (estimated fused split)' if b['estimated'] else ''}"
            f"{'' if b['complete'] else '  [INCOMPLETE]'}")
        split = b["split"]
        lines.append(
            f"  split: device {split['device_ms']:.3f} ms | collective "
            f"{split['collective_ms']:.3f} ms | host "
            f"{split['host_ms']:.3f} ms | gap {split['gap_ms']:.3f} ms")
        chain = " -> ".join(f"{s['stage']} {s['ms']:.3f}ms"
                            for s in b["critical_path"])
        lines.append(f"  critical path: {chain or '(empty)'}")
    if not report["heights"]:
        lines.append("no attributable blocks in the record set")
    if report["unattributed_segments"]:
        lines.append(f"({report['unattributed_segments']} segment(s) "
                     f"carried no block identity)")
    return "\n".join(lines)


# ---- live per-block metrics ------------------------------------------------

# Histogram handles for the hot observe path, keyed WEAKLY by registry
# instance: `registry.reset()` documents that nothing may cache a metric
# object across a reset, and a dead registry dropping out of the weak
# dict keeps that contract (an id()-keyed cache could alias a recycled
# id onto a stale metric).
_HIST_CACHE: weakref.WeakKeyDictionary = weakref.WeakKeyDictionary()


def _hist(name: str, help: str, **labels):
    reg = default_registry()
    per_reg = _HIST_CACHE.get(reg)
    if per_reg is None:
        per_reg = _HIST_CACHE[reg] = {}
    key = (name, tuple(sorted(labels.items())))
    h = per_reg.get(key)
    if h is None:
        h = per_reg[key] = reg.histogram(name, help=help, **labels)
    return h


def _may_attribute(record: dict, wanted: set[int]) -> bool:
    """Cheap superset test of ``segments_by_block``'s attribution rules:
    could any segment of ``record`` join a ``wanted`` height? The live
    observe path runs per mined block against the whole ring tail, and
    grouping every unrelated record is what the telemetry overhead
    audit prices — this prefilter keeps the per-block cost bounded by
    the block's own records, not the ring."""
    meta = record.get("meta") or {}
    try:
        h = int(meta["height"])
    except (KeyError, TypeError, ValueError):
        h = None
    if h is not None:
        try:
            k = int(meta.get("k") or 0)
        except (TypeError, ValueError):
            k = 0
        if k > 0:
            if any(h < w <= h + k for w in wanted):
                return True
        elif h in wanted:
            return True
    return any(s.get("height") in wanted
               for s in record.get("segments") or [])


def observe_block_metrics(height: int, records: list[dict] | None = None,
                          tail: int = 64, **labels) -> dict | None:
    """Observes ``block_critical_path_ms{stage}`` and
    ``block_trace_gap_pct`` for one just-mined block. The miner passes
    the block's own live record dicts (zero-copy — it created them, and
    this runs on the same thread right after the append); ``records``
    None falls back to the process profiler's newest ``tail``.
    ``labels`` join the observed series (the overhead audit's
    ``backend="trace-audit"`` isolation). In-memory only
    (HOTPATH-safe); returns the single-rank waterfall or None when no
    segment of ``height`` is attributable."""
    if telemetry_disabled():
        return None
    if records is None:
        from ..meshwatch.pipeline import profiler
        records = profiler().records(tail=tail)
    out = observe_batch_metrics([height], records, **labels)
    # The per-block metrics call is chainwatch's hot-path evaluation
    # cadence (the other is the shard-flush tick). Throttled inside to
    # one full rule sweep per MPIBT_CHAINWATCH_INTERVAL; disarmed/off
    # processes pay a flag check. Priced by the trace_overhead audit
    # (blocktrace/overhead.py), which calls this same seam per round.
    from ..chainwatch import evaluate as chainwatch_evaluate

    chainwatch_evaluate(height=int(height), source="block")
    return out.get(int(height))


def observe_batch_metrics(heights: list[int], records: list[dict],
                          **labels) -> dict:
    """The batch form (one grouping pass for a whole fused batch):
    observes the metrics for every listed height present in ``records``
    and returns ``{height: waterfall}`` for those found. Ranks keep
    separate waterfalls (cross-host clocks are not comparable — the
    same rule as ``critical_path_report``); the observed numbers come
    from the straggler rank, mirroring the report's headline. In the
    live path the records are this process's own, so there is exactly
    one rank."""
    if telemetry_disabled():
        return {}
    wanted = {int(h) for h in heights}
    blocks, _ = segments_by_block(
        [r for r in records if _may_attribute(r, wanted)])
    out: dict[int, dict] = {}
    for height in heights:
        ranks = blocks.get(int(height))
        if not ranks:
            continue
        wf = max((_observe_waterfall(slices)
                  for _, slices in sorted(ranks.items())),
                 key=lambda w: w["wall_ms"])
        for stage, ms in wf["stages_ms"].items():
            _hist("block_critical_path_ms",
                  help="per-block exclusive critical-path time per "
                       "stage",
                  stage=stage, **labels).observe(ms)
        _hist("block_trace_gap_pct",
              help="per-block wall share attributed to no stage",
              **labels).observe(wf["gap_pct"])
        out[int(height)] = wf
    return out
