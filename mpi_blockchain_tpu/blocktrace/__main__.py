"""CLI: python -m mpi_blockchain_tpu.blocktrace {smoke,overhead}

``smoke`` is the CI shape (``make trace-smoke``): a 2-rank ``--mesh-obs``
virtual-cpu world mines with tracing on, then the gate proves

1. every mined height yields a COMPLETE critical path with gap_pct < 5
   (block headline and every per-rank waterfall);
2. the analyzer is deterministic — the same record set (in any order)
   produces a byte-identical report, so byte-identical same-seed runs
   produce identical critical-path reports;
3. the Perfetto export round-trips through JSON with the critical-path
   slices and flow chain present;
4. the telemetry self-overhead audit passes its absolute budget
   (``perfwatch check``'s trace_overhead bound: < 3% sweep throughput);
5. the per-block critical-path observation passes its own absolute
   budget (trace_block_observe bound: < 300 us per observation — see
   overhead.py on why block-cadence work is priced separately).

``overhead`` runs the sweep audit alone and prints the bench payload
(``--block-observe`` for the per-block one) — ``perfwatch record
--section trace_overhead --payload`` appends it to PERF_HISTORY.jsonl
(the measure -> gate -> record merge-gate shape).
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time


def _spawn_rank(rank: int, world: int, obs_dir: str, difficulty: int,
                blocks: int):
    import os
    import subprocess

    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "MPIBT_MESH_RANK": str(rank),
           "MPIBT_MESH_WORLD": str(world),
           "MPIBT_MESH_OBS_INTERVAL": "0.2"}
    argv = [sys.executable, "-m", "mpi_blockchain_tpu", "mine",
            "--backend", "cpu", "--difficulty", str(difficulty),
            "--blocks", str(blocks), "--mesh-obs", obs_dir]
    return subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def cmd_overhead(args) -> int:
    from .overhead import measure_block_observe, measure_trace_overhead

    if args.block_observe:
        payload = measure_block_observe()
        print(json.dumps({"event": "trace_block_observe", **payload},
                         sort_keys=True))
        return 0
    payload = measure_trace_overhead(seconds=args.seconds, reps=args.reps)
    print(json.dumps({"event": "trace_overhead", **payload},
                     sort_keys=True))
    return 0


def cmd_smoke(args) -> int:
    """The make trace-smoke gate."""
    import tempfile

    from ..meshwatch.aggregate import read_shards
    from ..perfwatch.detector import check_candidate
    from ..perfwatch.history import DEFAULT_HISTORY_NAME, HistoryStore
    from .critical_path import COMPLETE_GAP_PCT, critical_path_report
    from .export import CRITICAL_PID, to_critical_path_trace
    from .overhead import measure_block_observe, measure_trace_overhead

    world, blocks, difficulty = 2, 6, 12
    with tempfile.TemporaryDirectory() as tmp:
        obs = str(pathlib.Path(tmp) / "mesh")
        ranks = [_spawn_rank(r, world, obs, difficulty, blocks)
                 for r in range(world)]
        # Every exit path reaps every rank: a failed (or hung) rank
        # must not leave a sibling mining into the tmp dir while
        # TemporaryDirectory cleanup walks it, or burning CPU after
        # the gate already failed.
        try:
            for p in ranks:
                out, err = p.communicate(timeout=180)
                if p.returncode != 0:
                    print(f"trace-smoke: rank failed rc={p.returncode}: "
                          f"{err[-800:]}", file=sys.stderr)
                    return 1
        finally:
            for p in ranks:
                if p.poll() is None:
                    p.kill()
                    p.communicate()
        records = [r for s in read_shards(obs)
                   for r in s.get("pipeline") or []]
        report = critical_path_report(records)

        # 1. every mined height has a complete critical path, gap < 5%
        #    — block headline AND every rank's own waterfall.
        if report["heights"] != list(range(1, blocks + 1)):
            print(f"trace-smoke: heights missing: {report['heights']}",
                  file=sys.stderr)
            return 1
        for h in report["heights"]:
            b = report["blocks"][str(h)]
            if not b["complete"] or b["gap_pct"] >= COMPLETE_GAP_PCT:
                print(f"trace-smoke: block {h} incomplete: gap "
                      f"{b['gap_pct']}%, path {b['critical_path']}",
                      file=sys.stderr)
                return 1
            if set(b["ranks"]) != {"0", "1"}:
                print(f"trace-smoke: block {h} missing ranks: "
                      f"{sorted(b['ranks'])}", file=sys.stderr)
                return 1
            for rank, wf in b["ranks"].items():
                if wf["gap_pct"] >= COMPLETE_GAP_PCT:
                    print(f"trace-smoke: block {h} rank {rank} gap "
                          f"{wf['gap_pct']}%", file=sys.stderr)
                    return 1

        # 2. analyzer determinism: record order must not matter, and the
        #    same inputs must produce byte-identical JSON.
        again = json.dumps(critical_path_report(list(reversed(records))),
                           sort_keys=True)
        if json.dumps(report, sort_keys=True) != again:
            print("trace-smoke: report not deterministic across record "
                  "order", file=sys.stderr)
            return 1

        # 3. the Perfetto export loads and carries the highlighted flow.
        trace = json.loads(json.dumps(to_critical_path_trace(report,
                                                             records)))
        cp = [e for e in trace["traceEvents"]
              if e.get("pid") == CRITICAL_PID]
        slices = [e for e in cp if e["ph"] == "X"]
        flows = [e for e in cp if e["ph"] in ("s", "t", "f")]
        if not slices or ({e["ph"] for e in flows} - {"t"}) != {"s", "f"}:
            print(f"trace-smoke: critical-path trace rows broken "
                  f"({len(slices)} slices, {len(flows)} flow events)",
                  file=sys.stderr)
            return 1

    # 4. the observer-effect budget: measure, then gate through the
    #    perfwatch detector's absolute bound (< 3%). Best-of-up-to-4
    #    measurements, longer after a miss: the paired-median estimator
    #    is robust to scheduler weather but not immune (a loaded CI box
    #    right after the mining phase reads high), and the gate's
    #    semantic is "an under-budget measurement is achievable" — a
    #    real regression (true cost over 3%) cannot produce one, while
    #    a weather flake cannot produce four misses with honest
    #    instrumentation. A miss sleeps before remeasuring: in `make
    #    check` this smoke runs in the wake of the multi-rank smokes,
    #    and the box needs seconds for that disturbance (reaped worlds,
    #    frequency/thermal recovery — which scales the memory-bound
    #    emit cost differently from the ALU-bound sweep) to decay;
    #    measured in that wake, reads open ~1.5 points high and settle
    #    across attempts. The first clean read short-circuits.
    repo_root = pathlib.Path(__file__).resolve().parent.parent.parent
    store = HistoryStore(repo_root / DEFAULT_HISTORY_NAME)
    for attempt, kwargs in enumerate(
            ({}, {"seconds": 1.5, "reps": 5}, {"seconds": 1.5, "reps": 5},
             {"seconds": 2.0, "reps": 5})):
        if attempt:
            time.sleep(5.0)
        payload = measure_trace_overhead(**kwargs)
        finding = check_candidate(store, "trace_overhead", payload)
        if finding.verdict != "regression":
            break
        print(f"trace-smoke: overhead read {attempt + 1} over budget "
              f"({payload['overhead_pct']}%)", file=sys.stderr)
    if finding.verdict == "regression":
        print(f"trace-smoke: telemetry overhead over budget: "
              f"{finding.render()}", file=sys.stderr)
        return 1

    # 5. the per-block observation budget (same best-of-≤3 shape: a
    #    real regression cannot produce a clean read, a weather spike
    #    cannot produce three dirty ones).
    for attempt in range(3):
        obs_payload = measure_block_observe()
        obs_finding = check_candidate(store, "trace_block_observe",
                                      obs_payload)
        if obs_finding.verdict != "regression":
            break
        print(f"trace-smoke: block-observe read {attempt + 1} over "
              f"budget ({obs_payload['block_observe_us']} us)",
              file=sys.stderr)
    if obs_finding.verdict == "regression":
        print(f"trace-smoke: per-block observation over budget: "
              f"{obs_finding.render()}", file=sys.stderr)
        return 1

    print(json.dumps({
        "event": "trace_smoke", "ok": True,
        "heights": report["heights"],
        "max_gap_pct": max(report["blocks"][str(h)]["gap_pct"]
                           for h in report["heights"]),
        "trace_events": len(trace["traceEvents"]),
        "critical_slices": len(slices),
        "overhead_pct": payload["overhead_pct"],
        "overhead_verdict": finding.verdict,
        "block_observe_us": obs_payload["block_observe_us"],
        "block_observe_verdict": obs_finding.verdict,
    }, sort_keys=True))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_blockchain_tpu.blocktrace",
        description="per-block critical-path attribution + telemetry "
                    "self-overhead audit (report CLI: python -m "
                    "mpi_blockchain_tpu.perfwatch critical-path)")
    sub = parser.add_subparsers(dest="command", required=True)

    p_ovh = sub.add_parser("overhead", help="measure the telemetry "
                                            "self-overhead bench payload")
    p_ovh.add_argument("--seconds", type=float, default=1.0,
                       help="seconds of paired rounds per rep "
                            "(default %(default)s)")
    p_ovh.add_argument("--reps", type=int, default=3,
                       help="independent paired-median reps "
                            "(default %(default)s)")
    p_ovh.add_argument("--block-observe", action="store_true",
                       help="measure the per-block critical-path "
                            "observation cost (the trace_block_observe "
                            "section) instead of the per-round sweep "
                            "overhead")
    p_ovh.set_defaults(fn=cmd_overhead)

    p_smk = sub.add_parser("smoke", help="the make trace-smoke gate")
    p_smk.set_defaults(fn=cmd_smoke)

    args = parser.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        sys.stderr.close()
        return 0


if __name__ == "__main__":
    sys.exit(main())
