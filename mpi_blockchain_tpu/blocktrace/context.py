"""The block trace context: a (height, template, rank) identity stamped
through every subsystem a block traverses.

``trace_block(height, template=...)`` pushes one frame on a thread-local
stack (each thread traces its own block, mirroring the span stack's
discipline — the GIL-free bench pool cannot corrupt nesting). The
innermost frame is what the telemetry layer consults:

* ``meshwatch.pipeline.DispatchRecord.add_segment`` stamps the frame's
  ``height``/``template`` onto every segment recorded in scope;
* ``telemetry.events.emit_event`` attaches a ``trace`` dict to every
  event emitted in scope (unless the record already carries one);
* ``PipelineProfiler.dispatch`` defaults its meta's ``height`` from the
  frame when the call site did not pass one.

``template`` is the per-height template rebuild counter — the
extra-nonce rollover index for the per-block miner, the rollover index
of the fused recovery path. ``rank`` defaults to the process's declared
mesh rank so cross-rank joins need no extra bookkeeping.

Pure stdlib, in-memory only: safe on the chainlint HOTPATH.
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading

_tls = threading.local()


@dataclasses.dataclass(frozen=True)
class BlockTrace:
    """One block's trace identity."""
    height: int
    template: int = 0
    rank: int = 0

    def to_dict(self) -> dict:
        return {"height": self.height, "template": self.template,
                "rank": self.rank}


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def current_trace() -> BlockTrace | None:
    """The innermost open block trace on this thread, or None."""
    stack = _stack()
    return stack[-1] if stack else None


def trace_dict() -> dict | None:
    """The innermost trace as a JSON-able dict, or None when no block
    is in scope — the stamp ``emit_event`` attaches."""
    t = current_trace()
    return None if t is None else t.to_dict()


@contextlib.contextmanager
def trace_block(height: int, template: int | None = None,
                rank: int | None = None):
    """Declares everything inside as work on block ``height``.

    ``template`` defaults to the enclosing frame's template when
    re-entering the same height (the miner pushes an outer
    height-scoped frame, then per-extra-nonce frames inside), else 0;
    ``rank`` defaults to the process's declared mesh rank.

    With ``MPIBT_TELEMETRY_OFF`` this is a bare yield (no stack, no
    frame): the context is itself instrumentation, so the overhead
    audit's off leg must not pay for it.
    """
    from ..telemetry import mesh_rank
    from ..telemetry.registry import telemetry_disabled

    if telemetry_disabled():
        yield None
        return
    stack = _stack()
    if template is None:
        parent = stack[-1] if stack else None
        template = (parent.template
                    if parent is not None and parent.height == height
                    else 0)
    frame = BlockTrace(height=int(height), template=int(template),
                       rank=int(rank if rank is not None else mesh_rank()))
    stack.append(frame)
    try:
        yield frame
    finally:
        stack.pop()
