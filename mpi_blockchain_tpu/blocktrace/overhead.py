"""Telemetry self-overhead audit: what does always-on tracing cost?

Every PR widens the instrument set (spans, counters, pipeline records,
trace stamps), and each addition is individually "negligible" — the
classic way an observer effect accretes unbudgeted. This module prices
the whole instrumentation stack as one number: the C++ scalar sweep is
run with the *identical* loop body — once fully instrumented with the
miner's per-round emit pattern (block trace context + spans + counters +
heartbeat + a pipeline dispatch with segments), once under
``MPIBT_TELEMETRY_OFF`` (every emit point a flag-check no-op) — and

    overhead_pct = 100 * (t_on - t_off) / t_off

is the ``trace_overhead`` bench section, recorded to PERF_HISTORY.jsonl
and gated by ``perfwatch check`` under the absolute 3% budget
(``detector.SECTION_BOUNDS``).

The one emit that does NOT fire per sweep round is the per-block
critical-path observation (``observe_block_metrics`` in the miners'
``mine_chain``) — per-BLOCK work priced per round would conflate two
cadences and drown the sweep gate in block-rate assumptions. It gets
its own audit, ``measure_block_observe``: the median microseconds of
one observation, timed in-situ (each sample follows an un-timed sweep
so the observation pays real cache weather, exactly as in the mining
loop, not tight-loop warm-cache fiction) — the ``trace_block_observe``
section, bounded absolutely by ``SECTION_BOUNDS`` too.

**Noise discipline.** Host noise here is *multiplicative and slow*
(frequency scaling, steal time: round times drift 2× over seconds), so
whole-leg averages — and even per-leg minima — swing far more than the
budget. The robust design is **paired rounds**: each sample runs one
instrumented and one off round back-to-back (same scheduler weather),
with the order alternating per pair to cancel position bias, and the
estimate is the **median** of the per-pair deltas — a load burst lands
on both halves of the pairs it covers and cancels; an asymmetric spike
is an outlier the median ignores. Measured on a noisy shared box, the
null experiment (both halves identical) reads well under 1%.

The instrumented half emits into a LOCAL pipeline profiler and
audit-labeled metric series (``backend="trace-audit"``): the audit must
price the emit path, not contaminate the run's real telemetry.
"""
from __future__ import annotations

import statistics
import time

from .. import chainwatch, core
from ..dispatchwatch import compile_scope, note_cache
from ..meshprof.spans import skew_span
from ..telemetry import counter, heartbeat, set_telemetry_disabled
from ..telemetry.spans import span
from .context import trace_block
from .critical_path import observe_block_metrics

_IMPOSSIBLE_DIFFICULTY = 64   # pure sweep: no winner, no early exit
_HEADER = bytes(range(80))


def _instrumented_round(profiler, height: int, base: int, chunk: int):
    """The miner's per-round emit pattern, verbatim in shape: trace
    context, dispatch record, enqueue/device segments, sweep span,
    round + hash counters, heartbeat stamp. The ONE copy both audits
    run (``trace_overhead`` prices it per round, ``trace_block_observe``
    sweeps it before each timed observation) — two hand-maintained
    copies would silently price different instrumentation stacks.
    Returns the round's dispatch record."""
    with trace_block(height):
        prec = profiler.dispatch(kind="sweep", height=height,
                                 backend="trace-audit")
        with prec.segment("enqueue"):
            pass
        with span("miner.sweep", height=height), \
                prec.segment("device"):
            core.cpu_search(_HEADER, base, chunk,
                            _IMPOSSIBLE_DIFFICULTY)
        counter("mining_rounds_total",
                help="backend sweep rounds issued",
                backend="trace-audit").inc()
        counter("hashes_tried_total",
                help="nonces evaluated across all sweeps",
                backend="trace-audit").inc(chunk)
        heartbeat("bench_heartbeat").inc()
        # The meshprof rendezvous span: the newest per-round emit point
        # (ring append + round counter + trace stamp), priced by the
        # same paired audit — the off half pays only its flag check.
        with skew_span(site="trace-audit"):
            pass
        # The dispatchwatch emit points, priced the same way: the scope
        # is the per-dispatch cost every wired seam pays (arm check +
        # tls push/pop; the off half pays one flag check in __init__).
        # The cache note is a per-cache-MISS emit — a steady-state
        # round pays none — so it is priced once, on the first round,
        # matching the wired seams' cadence. No jax here, so
        # ensure_listener stays a sys.modules miss — exactly the
        # cold-backend fast path.
        with compile_scope(site="trace-audit"):
            pass
        if height <= 1:
            note_cache(site="trace-audit", entries=1)
        # The chainwatch watchdog step — the newest per-round emit
        # point: rule evaluation rides the same audit so the ≤3% gate
        # prices the live SLO rules too. The off half pays only the
        # flag check (evaluate returns on telemetry_disabled), and the
        # audits arm chainwatch so the on half pays the real sweep
        # throttle + rules.
        chainwatch.evaluate(height=height, source="audit")
    return prec


def _one_round(profiler, rounds: int, base: int, chunk: int,
               instrumented: bool) -> float:
    """One sweep round; returns its wall seconds. The body is IDENTICAL
    in both halves — only the kill switch differs, so the paired delta
    prices the emit points and nothing else."""
    prev = set_telemetry_disabled(not instrumented)
    try:
        t0 = time.perf_counter()
        _instrumented_round(profiler, rounds + 1, base, chunk)
        return time.perf_counter() - t0
    finally:
        set_telemetry_disabled(prev)


def _paired_rep(seconds: float, chunk: int) -> tuple[list, float, float]:
    """One repetition: paired rounds until the wall budget runs out;
    returns (per-pair delta pcts, fastest on-round rate, fastest
    off-round rate)."""
    from ..meshwatch.pipeline import PipelineProfiler

    profiler = PipelineProfiler()
    deltas: list[float] = []
    best_on = best_off = float("inf")
    base = 0
    rounds = 0
    deadline = time.perf_counter() + seconds
    while time.perf_counter() < deadline or not deltas:
        # Alternate which half goes first (position-bias cancellation).
        first_on = len(deltas) % 2 == 0
        t_a = _one_round(profiler, rounds, base, chunk, first_on)
        base += chunk
        rounds += 1
        t_b = _one_round(profiler, rounds, base, chunk, not first_on)
        base += chunk
        rounds += 1
        t_on, t_off = (t_a, t_b) if first_on else (t_b, t_a)
        deltas.append(100.0 * (t_on - t_off) / t_off)
        best_on = min(best_on, t_on)
        best_off = min(best_off, t_off)
    return (deltas, chunk / best_on, chunk / best_off)


def measure_block_observe(samples: int = 400,
                          chunk_pow2: int = 11) -> dict:
    """The ``trace_block_observe`` bench payload: the median
    microseconds ONE per-block critical-path observation costs, timed
    in-situ — every sample observes a freshly-instrumented sweep's own
    record right after the (un-timed) sweep ran, so the measurement
    pays the same cache/branch weather the mining loop does (a tight
    loop over a warm record reads ~3x cheaper than reality)."""
    from ..meshwatch.pipeline import PipelineProfiler

    profiler = PipelineProfiler()
    chunk = 1 << chunk_pow2
    times: list[float] = []
    base = 0
    prev = set_telemetry_disabled(False)
    # Arm the watchdog so the timed observation pays chainwatch's real
    # per-block cost (the throttle check, occasionally a full sweep) —
    # the same path the mining loop pays once `mine` arms it.
    was_armed = chainwatch.installed()
    if not was_armed:
        chainwatch.install()
    try:
        for i in range(max(8, samples)):
            prec = _instrumented_round(profiler, i + 1, base, chunk)
            base += chunk
            t0 = time.perf_counter()
            observe_block_metrics(i + 1, records=[prec.record],
                                  backend="trace-audit")
            times.append((time.perf_counter() - t0) * 1e6)
    finally:
        set_telemetry_disabled(prev)
        if not was_armed:
            chainwatch.uninstall()
    times.sort()
    return {
        "backend": "cpu",
        "chunk_pow2": chunk_pow2,
        "samples": len(times),
        "block_observe_us": round(statistics.median(times), 1),
        "p90_us": round(times[int(0.9 * (len(times) - 1))], 1),
    }


def measure_trace_overhead(seconds: float = 1.0, reps: int = 3,
                           chunk_pow2: int = 13) -> dict:
    """The ``trace_overhead`` bench payload: ``overhead_pct`` is the
    median over ALL pairs pooled across ``reps`` repetitions — one
    estimate from a few hundred paired samples beats a median of rep
    medians, because a load burst contaminating one rep is outvoted by
    the others' pairs instead of contributing a full vote. May be
    negative on a noisy box (the off halves drew the slower slices);
    the gate only bounds the upside."""
    chunk = 1 << chunk_pow2
    # Armed watchdog: the on half pays chainwatch's live cost (throttle
    # check, periodically a full rule sweep); the off half pays only the
    # kill-switch flag check — so the paired delta prices rule
    # evaluation under the same ≤3% gate as every other emit point.
    was_armed = chainwatch.installed()
    if not was_armed:
        chainwatch.install()
    try:
        rep_runs = [_paired_rep(seconds, chunk)
                    for _ in range(max(1, reps))]
    finally:
        if not was_armed:
            chainwatch.uninstall()
    pooled = [d for deltas, _, _ in rep_runs for d in deltas]
    rep_medians = [statistics.median(deltas) for deltas, _, _ in rep_runs]
    return {
        "backend": "cpu",
        "chunk_pow2": chunk_pow2,
        "seconds_per_rep": seconds,
        "reps": len(rep_runs),
        "pairs": len(pooled),
        "hashes_per_sec_instrumented": round(
            max(on for _, on, _ in rep_runs), 1),
        "hashes_per_sec_off": round(
            max(off for _, _, off in rep_runs), 1),
        "overhead_pct": round(statistics.median(pooled), 3),
        "spread_pct": round(max(rep_medians) - min(rep_medians), 2),
        "all_overhead_pct": [round(m, 3) for m in rep_medians],
    }
