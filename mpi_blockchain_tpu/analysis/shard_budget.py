"""SBD rules — the collective-site budget ratchet for the mesh sweep.

The fourth committed ratchet in the OPBUDGET / TRANSFERBUDGET /
WAITBUDGET lineage, and the one that gates the v5e-8 bring-up
(ROADMAP item 1): accelerator-parallel consensus lives or dies on
exactly two collectives per round — ``winner_select``'s psum + pmin
(parallel/mesh.py) — and nothing stopped a refactor from silently
adding a host gather or an extra rendezvous to the hot path. This pass
is the tripwire: ``SHARDBUDGET.json`` pins a **static collective-site
census** — a deterministic count of collective call sites
(``psum``/``pmin``/``all_gather``/``axis_index``/... plus calls to the
sanctioned ``winner_select`` seam itself) over the SPMD-scope sources —
and the build fails when the census grows.

Like its siblings the static census is a monotone *proxy*; the
physically-meaningful numbers ride along in the baseline's ``traced``
section: the one sanctioned mover —
``python -m mpi_blockchain_tpu.analysis.shard_budget --write``
(imports jax lazily; this gate pass never does) — builds a 1-device
('miners',) mesh, traces ``make_mesh_sweep_fn`` per traceable kernel
flavor and pins exactly which collective primitives appear per sweep
dispatch (today: one psum + one pmin, axes ``('miners',)``, 8
replicated payload bytes), so the committed diff names every
collective the ICI carries per round.

  SBD001  the static collective-site census exceeds the committed
          budget — a RATCHET INCREASE: collective sites on the sweep
          path only ratchet DOWN. A justified increase goes through
          the sanctioned mover and a reviewed SHARDBUDGET.json diff;
          ``--rebaseline-shards`` only accepts a LOWER census.
  SBD002  SHARDBUDGET.json is missing, unparseable, or lacks the
          required keys — the collective ratchet is not armed.
  SBD003  the census scope resolves to no readable source file — the
          gate is counting nothing (update ``SHARD_SCOPE`` here
          alongside a sweep-path refactor).

``--check`` (the ``make shardbudget-check`` target) re-runs the FULL
mover census — static and traced — and fails unless the committed
baseline reproduces byte-identically, calling out any growth as a
RATCHET INCREASE with the delta.

Override keys: ``shardbudget_json`` (baseline path), ``shard_files``
(census file set, shared with the SHD pass) — the drift-fixture seams.
"""
from __future__ import annotations

import ast
import pathlib

from . import Finding, override_files, rel_path, source_cached
from .budget import (int_key_error, read_json_object, refuse_upward,
                     require_amendable, write_json_budget)
from .callgraph import call_name
from .shard_lint import _is_collective

BASELINE_NAME = "SHARDBUDGET.json"
REQUIRED_KEYS = ("static_collective_sites", "traced")
MOVER = "python -m mpi_blockchain_tpu.analysis.shard_budget --write"

#: The SPMD-scope sources whose collective call sites are budgeted —
#: everything between the mine loop and the mesh program.
SHARD_SCOPE = (
    "mpi_blockchain_tpu/parallel/mesh.py",
    "mpi_blockchain_tpu/parallel/distributed.py",
    "mpi_blockchain_tpu/backend/tpu.py",
    "mpi_blockchain_tpu/models/fused.py",
    "mpi_blockchain_tpu/models/miner.py",
)

#: Calls to the winner-select seam count as collective sites: adding a
#: seam call site IS adding a per-round collective pair, and must show
#: up in a reviewed baseline diff.
_SEAM_CALLS = {"winner_select"}

#: Communicating collective primitives in a traced jaxpr (axis queries
#: like axis_index are censused but carry no payload). Version-suffixed
#: spellings normalize to the base name.
_COMM_PRIMS = {"psum", "pmin", "pmax", "pmean", "all_gather",
               "all_to_all", "ppermute"}
_PRIM_ALIASES = {"psum2": "psum", "psum_invariant": "psum"}


def static_collective_census(
        root: pathlib.Path, files: list[pathlib.Path]
) -> tuple[int, dict[str, int], list[dict],
           list[tuple[str, int, str]]]:
    """(total, per-label counts, per-site records, syntax errors) over
    the scoped files — collective/axis-query calls plus winner_select
    seam calls (labels are the rightmost call name)."""
    total = 0
    by_label: dict[str, int] = {}
    sites: list[dict] = []
    errors: list[tuple[str, int, str]] = []
    for path in sorted(pathlib.Path(p) for p in files):
        rel = rel_path(path, root)
        try:
            _, tree, err = source_cached(path)
        except OSError:
            continue
        if tree is None:
            errors.append((rel, err[0], err[1]))
            continue
        found: list[tuple[int, str]] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if _is_collective(node) or name in _SEAM_CALLS:
                found.append((node.lineno, name))
        for lineno, label in sorted(found):
            total += 1
            by_label[label] = by_label.get(label, 0) + 1
            sites.append({"file": rel, "line": lineno, "label": label})
    return total, by_label, sites, errors


def _paths(root: pathlib.Path, overrides: dict
           ) -> tuple[pathlib.Path, list[pathlib.Path]]:
    baseline = pathlib.Path(overrides.get("shardbudget_json",
                                          root / BASELINE_NAME))
    files = override_files(overrides, "shard_files",
                           lambda: [root / p for p in SHARD_SCOPE])
    return baseline, files


def load_baseline(baseline: pathlib.Path) -> tuple[dict | None, str]:
    """(budget dict, error message) — dict None iff invalid."""
    data, err = read_json_object(baseline)
    if data is None:
        return None, err
    err = int_key_error(data, baseline.name, "static_collective_sites",
                        MOVER)
    if err:
        return None, err
    if not isinstance(data.get("traced"), dict):
        return None, (f"{baseline.name} lacks the 'traced' per-flavor "
                      f"collective census — regenerate it with "
                      f"`{MOVER}`")
    return data, ""


def run_shard_budget(root: pathlib.Path, overrides=None,
                     notes=None) -> list[Finding]:
    overrides = overrides or {}
    baseline_path, files = _paths(root, overrides)
    baseline, err = load_baseline(baseline_path)
    if baseline is None:
        return [Finding(rel_path(baseline_path, root), 1, "SBD002",
                        f"collective-site ratchet is not armed: {err}")]
    readable = [p for p in files if pathlib.Path(p).is_file()]
    if not readable:
        return [Finding("mpi_blockchain_tpu", 1, "SBD003",
                        "collective-site census scope resolves to no "
                        "readable source file — the gate is counting "
                        "nothing; update SHARD_SCOPE in "
                        "analysis/shard_budget.py alongside the "
                        "refactor")]
    total, by_label, sites, errors = static_collective_census(
        root, readable)
    findings = [Finding(rel, lineno, "SBD000", f"syntax error: {msg}")
                for rel, lineno, msg in errors]
    budget = baseline["static_collective_sites"]
    if total > budget:
        anchor = (sites[0]["file"], sites[0]["line"]) if sites else (
            rel_path(pathlib.Path(readable[0]), root), 1)
        breakdown = ", ".join(f"{k}×{v}"
                              for k, v in sorted(by_label.items()))
        findings.append(Finding(
            anchor[0], anchor[1], "SBD001",
            f"RATCHET INCREASE: static collective-site census grew: "
            f"{total} > budget {budget} (delta +{total - budget}; "
            f"{breakdown}). The sweep path carries exactly the "
            f"collectives SHARDBUDGET.json pins — an accidental host "
            f"gather or extra rendezvous here is a multi-chip "
            f"regression (ROADMAP item 1's v5e-8 bring-up depends on "
            f"it); if this increase is justified, re-census with "
            f"`{MOVER}` and commit the SHARDBUDGET.json diff"))
    elif total < budget and notes is not None:
        notes.append(f"shard_budget: static census {total} is below "
                     f"the budget {budget} — ratchet it down with "
                     f"--rebaseline-shards (or the --write mover)")
    return findings


def rebaseline_shards(root: pathlib.Path,
                      overrides=None) -> tuple[int, int, pathlib.Path]:
    """Writes the current static collective census into the baseline,
    refusing to RAISE it (the ratchet). Returns (old, new, path).
    Raises ValueError when the census is higher, the scope is empty, or
    there is no valid baseline to amend — bootstrapping (and any
    justified raise) is the sanctioned mover's job (``shard_budget
    --write``, which records the traced per-flavor census too)."""
    overrides = overrides or {}
    baseline_path, files = _paths(root, overrides)
    readable = [p for p in files if pathlib.Path(p).is_file()]
    if not readable:
        raise ValueError("collective census scope resolves to no "
                         "readable source file — nothing to baseline")
    total, by_label, sites, errors = static_collective_census(
        root, readable)
    if errors:
        raise ValueError(f"census scope has syntax errors: {errors[0]}")
    old_data, err = load_baseline(baseline_path)
    old_data = require_amendable(old_data, err, MOVER)
    old = old_data["static_collective_sites"]
    refuse_upward(total, old, census_label="static collective census",
                  policy="Collective sites only ratchet down",
                  mover=MOVER, baseline_name=BASELINE_NAME)
    data = dict(old_data)
    data["static_collective_sites"] = total
    data["static_by_site"] = dict(sorted(by_label.items()))
    data["sites"] = sites
    data["scope"] = [rel_path(pathlib.Path(p), root) for p in readable]
    write_json_budget(baseline_path, data)
    return old, total, baseline_path


# ---- the sanctioned mover (imports jax; never run by the gate) -------------


def _census_jaxpr(jaxpr, counts: dict[str, int], axes: set,
                  payload: list[int]) -> None:
    """Recursive collective-primitive census over a jaxpr: counts per
    normalized primitive name, axis names bound, and the replicated
    payload bytes the communicating collectives move."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        name = _PRIM_ALIASES.get(name, name)
        if name in _COMM_PRIMS or name in ("axis_index", "axis_size"):
            counts[name] = counts.get(name, 0) + 1
            for key in ("axes", "axis_name"):
                v = eqn.params.get(key)
                if isinstance(v, (tuple, list)):
                    axes.update(str(a) for a in v)
                elif isinstance(v, str):
                    axes.add(v)
            if name in _COMM_PRIMS:
                for var in eqn.outvars:
                    aval = getattr(var, "aval", None)
                    if aval is not None and hasattr(aval, "dtype"):
                        size = 1
                        for d in getattr(aval, "shape", ()):
                            size *= int(d)
                        payload.append(size * aval.dtype.itemsize)
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                inner = getattr(sub, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    _census_jaxpr(inner, counts, axes, payload)
                elif hasattr(sub, "eqns"):
                    _census_jaxpr(sub, counts, axes, payload)


def trace_collective_census() -> dict[str, dict]:
    """Traces ``make_mesh_sweep_fn`` per traceable kernel flavor over a
    1-device ('miners',) mesh (always available, deterministic — the
    collective census is device-count independent) and censuses the
    collective primitives per sweep dispatch. Flavors whose kernel
    cannot build on this platform (pallas off-TPU raises ConfigError)
    are recorded under ``skipped`` by exception class, so a CPU mover
    run stays reproducible."""
    import jax  # noqa: F401  (the mover contract: jax only here)
    import numpy as np

    from ..config import ConfigError
    from ..parallel.mesh import make_miner_mesh, make_mesh_sweep_fn

    mesh = make_miner_mesh(1)
    u32 = np.uint32
    flavors: dict[str, dict] = {}
    skipped: dict[str, str] = {}
    for flavor in ("jnp", "pallas"):
        try:
            fn = make_mesh_sweep_fn(mesh, batch_size=1 << 8,
                                    difficulty_bits=12, kernel=flavor)
            closed = jax.make_jaxpr(fn)(
                np.zeros(8, u32), np.zeros(16, u32), u32(0))
        except ConfigError as e:
            skipped[flavor] = type(e).__name__
            continue
        counts: dict[str, int] = {}
        axes: set = set()
        payload: list[int] = []
        _census_jaxpr(closed.jaxpr, counts, axes, payload)
        flavors[flavor] = {
            "primitives": dict(sorted(counts.items())),
            "collective_total": sum(v for k, v in counts.items()
                                    if k in _COMM_PRIMS),
            "axis_names": sorted(axes),
            "replicated_payload_bytes": sum(payload),
        }
    out: dict[str, dict] = dict(sorted(flavors.items()))
    if skipped:
        out["skipped"] = dict(sorted(skipped.items()))
    return out


def _full_census(root: pathlib.Path, overrides=None) -> dict:
    baseline_path, files = _paths(root, overrides or {})
    readable = [p for p in files if pathlib.Path(p).is_file()]
    total, by_label, sites, errors = static_collective_census(
        root, readable)
    if errors:
        raise ValueError(f"census scope has syntax errors: {errors[0]}")
    return {
        "static_collective_sites": total,
        "static_by_site": dict(sorted(by_label.items())),
        "sites": sites,
        "scope": [rel_path(pathlib.Path(p), root) for p in readable],
        "traced": trace_collective_census(),
        "writer": MOVER,
    }


def write_budget(root: pathlib.Path | None = None,
                 overrides=None) -> pathlib.Path:
    """The one sanctioned mover: full rewrite of SHARDBUDGET.json —
    static census plus the traced per-flavor collective census (the
    committed diff is the review surface)."""
    from . import default_root

    root = root if root is not None else default_root()
    baseline_path, _ = _paths(root, overrides or {})
    write_json_budget(baseline_path, _full_census(root, overrides))
    return baseline_path


def check_budget(root: pathlib.Path | None = None,
                 overrides=None) -> int:
    """The ``make shardbudget-check`` gate: recomputes the full mover
    census and requires the committed baseline to reproduce it
    byte-identically. Growth is a RATCHET INCREASE (rc 1 with the
    delta); any other drift is staleness (rc 1); an unarmed baseline
    is rc 2."""
    import sys

    from . import default_root

    root = root if root is not None else default_root()
    baseline_path, _ = _paths(root, overrides or {})
    committed, err = load_baseline(baseline_path)
    if committed is None:
        print(f"shard_budget: not armed: {err}", file=sys.stderr)
        return 2
    current = _full_census(root, overrides)
    cur, old = (current["static_collective_sites"],
                committed["static_collective_sites"])
    if cur > old:
        print(f"shard_budget: RATCHET INCREASE: static collective "
              f"census {cur} > committed {old} (delta +{cur - old}) — "
              f"collective sites on the sweep path only ratchet down; "
              f"a justified increase goes through `{MOVER}` and a "
              f"reviewed {BASELINE_NAME} diff", file=sys.stderr)
        return 1
    for flavor, traced in current["traced"].items():
        if flavor == "skipped":
            continue
        was = committed["traced"].get(flavor, {})
        t_cur = traced.get("collective_total", 0)
        t_old = was.get("collective_total", 0)
        if t_cur > t_old:
            print(f"shard_budget: RATCHET INCREASE: traced collective "
                  f"census for flavor '{flavor}' {t_cur} > committed "
                  f"{t_old} (delta +{t_cur - t_old}) — the sweep "
                  f"dispatch grew a collective; re-census with "
                  f"`{MOVER}` if justified", file=sys.stderr)
            return 1
    if current != committed:
        print(f"shard_budget: {BASELINE_NAME} is stale — the mover "
              f"census no longer reproduces the committed baseline; "
              f"re-run `{MOVER}` and review the diff", file=sys.stderr)
        return 1
    print(f"shard_budget: {BASELINE_NAME} reproduces "
          f"({cur} static sites; traced flavors "
          f"{sorted(k for k in current['traced'] if k != 'skipped')})",
          file=sys.stderr)
    return 0


def main(argv=None) -> int:
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m mpi_blockchain_tpu.analysis.shard_budget",
        description="the sanctioned SHARDBUDGET.json mover: traces the "
                    "mesh sweep per kernel flavor (imports jax) and "
                    "rewrites the committed collective budget; the "
                    "chainlint gate itself stays stdlib-only")
    parser.add_argument("--write", action="store_true",
                        help="re-census and rewrite SHARDBUDGET.json")
    parser.add_argument("--check", action="store_true",
                        help="verify the committed baseline reproduces "
                             "byte-identically (make shardbudget-check)")
    parser.add_argument("--baseline", type=pathlib.Path, default=None,
                        metavar="PATH",
                        help="check/write against an alternate "
                             "SHARDBUDGET.json (the drift-fixture seam)")
    parser.add_argument("--root", type=pathlib.Path, default=None)
    args = parser.parse_args(argv)
    if not (args.write or args.check):
        parser.error("nothing to do: pass --write or --check")
    overrides = ({"shardbudget_json": args.baseline}
                 if args.baseline is not None else None)
    if args.check:
        return check_budget(args.root, overrides)
    try:
        path = write_budget(args.root, overrides)
    except (ValueError, OSError) as e:
        print(f"shard_budget: {e}", file=sys.stderr)
        return 2
    print(f"shard_budget: wrote {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
