"""CLI: python -m mpi_blockchain_tpu.analysis

Runs the chainlint pass families and exits non-zero on any finding —
the PR gate `make check` runs this before the test suite. See
docs/static_analysis.md for the rule catalogue.

Modes beyond the default lint run:

* ``--audit-suppressions`` — append a warning-only report of
  ``chainlint: disable=`` comments whose rule no longer fires, computed
  from the same analysis run (stale suppressions never affect the exit
  code; ``make check`` passes this flag so one run serves both).
* ``--since REV`` — git-diff-driven changed-files mode: only pass
  families whose scope holds a changed file run (``make lint-fast``).
* ``--rebaseline`` — write the current static ALU census into
  OPBUDGET.json; refuses to raise the budget (the ratchet).
* ``--rebaseline-transfers`` — the same ratchet for the device-transfer
  census into TRANSFERBUDGET.json; a justified RAISE of either budget
  goes through its sanctioned mover (``roofline.py --write-budget`` /
  ``python -m mpi_blockchain_tpu.analysis.transfer_budget --write``).
* ``--rebaseline-waits`` — the same ratchet for the blocking-wait
  census into WAITBUDGET.json (mover: ``python -m
  mpi_blockchain_tpu.analysis.thread_lint --write``).
* ``--rebaseline-shards`` — the same ratchet for the collective-site
  census into SHARDBUDGET.json (mover: ``python -m
  mpi_blockchain_tpu.analysis.shard_budget --write``, which also
  re-traces the per-flavor collective census).
* ``--jobs N`` — run pass families on a thread pool; per-pass wall
  times are always collected and emitted under ``pass_timings_ms`` in
  ``--json`` output (which is a JSON object: ``{"findings": [...],
  "pass_timings_ms": {...}}``).
"""
from __future__ import annotations

import argparse
import json
import pathlib
import subprocess
import sys

from . import (apply_suppressions, audit_from_raw, default_root,
               families_for_changed, pass_families, run_all)

OVERRIDE_KEYS = ("capi", "ctypes_binding", "pybind", "chain_hpp",
                 "chain_cpp", "core_init", "sha_jnp", "header_test",
                 "mesh_py", "core_makefile", "core_src", "sim_py",
                 "telemetry_files", "resilience_files",
                 "adversary_files", "rank_scope_files",
                 "blocktrace_scope_files", "jax_files",
                 "conc_files", "spmd_files", "elastic_files",
                 "hotpath_files", "opbudget_json", "kernel_src",
                 "host_src",
                 "sync_files", "donation_files",
                 "transferbudget_json", "transfer_files",
                 "lock_files", "future_files", "thread_files",
                 "wait_files", "waitbudget_json",
                 "shard_files", "shardbudget_json",
                 "skew_scope_files", "incident_scope_files",
                 "compile_scope_files")


def _changed_files(root: pathlib.Path, rev: str) -> list[str] | None:
    """Repo-relative paths changed since ``rev`` — committed + worktree
    edits PLUS untracked files (`git diff` alone would let a brand-new
    file with a violation sail through lint-fast green); None when git
    cannot answer."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", rev, "--"],
            cwd=root, capture_output=True, text=True, timeout=60)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=60)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0 or untracked.returncode != 0:
        return None
    names = {line.strip() for line in diff.stdout.splitlines()
             if line.strip()}
    names |= {line.strip() for line in untracked.stdout.splitlines()
              if line.strip()}
    return sorted(names)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_blockchain_tpu.analysis",
        description="chainlint: cross-language static analysis "
                    "(binding contract, header layout, JAX purity, "
                    "sanitizer matrix, thread races, SPMD collectives, "
                    "hot-path blocking, device-sync provenance, "
                    "buffer donation, deadlint lock-order/future/"
                    "thread lifecycle, shardlint partition-spec/axis-"
                    "context, op-budget + transfer-budget + wait-budget "
                    "+ collective-site ratchets)")
    parser.add_argument("--root", type=pathlib.Path, default=None,
                        help="repo root (default: auto-detected)")
    parser.add_argument("--passes", default=None,
                        help="comma-separated subset of pass families "
                             f"(default: all of {sorted(pass_families())})")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit a JSON object {findings, "
                             "pass_timings_ms}")
    parser.add_argument("--override", action="append", default=[],
                        metavar="KEY=PATH",
                        help="redirect one checked file (drift-fixture "
                             f"test seam); keys: {', '.join(OVERRIDE_KEYS)}")
    parser.add_argument("--jobs", type=int, default=1, metavar="N",
                        help="run pass families on an N-thread pool "
                             "(default 1)")
    parser.add_argument("--since", default=None, metavar="REV",
                        help="changed-files mode: only run families "
                             "whose scope holds a file changed since "
                             "the git rev (make lint-fast)")
    parser.add_argument("--audit-suppressions", action="store_true",
                        help="also report stale 'chainlint: disable=' "
                             "comments from the same run (warning-only: "
                             "never affects the exit code)")
    parser.add_argument("--rebaseline", action="store_true",
                        help="write the current static ALU census into "
                             "OPBUDGET.json (refuses to raise it)")
    parser.add_argument("--rebaseline-transfers", action="store_true",
                        help="write the current static transfer-site "
                             "census into TRANSFERBUDGET.json (refuses "
                             "to raise it)")
    parser.add_argument("--rebaseline-waits", action="store_true",
                        help="write the current static blocking-wait "
                             "census into WAITBUDGET.json (refuses to "
                             "raise it)")
    parser.add_argument("--rebaseline-shards", action="store_true",
                        help="write the current static collective-site "
                             "census into SHARDBUDGET.json (refuses to "
                             "raise it)")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="suppress the summary/notes lines")
    args = parser.parse_args(argv)

    overrides: dict[str, pathlib.Path] = {}
    for item in args.override:
        key, _, value = item.partition("=")
        if key not in OVERRIDE_KEYS or not value:
            parser.error(f"bad --override {item!r}; keys: "
                         f"{', '.join(OVERRIDE_KEYS)}")
        overrides[key] = pathlib.Path(value)

    root = args.root if args.root is not None else default_root()

    if args.rebaseline:
        from .opbudget import rebaseline
        try:
            old, new, path = rebaseline(root, overrides)
        except (ValueError, OSError, SyntaxError) as e:
            print(f"chainlint: rebaseline refused: {e}", file=sys.stderr)
            return 2
        print(f"chainlint: op budget rebaselined {old} -> {new} "
              f"({path})", file=sys.stderr)
        return 0

    if args.rebaseline_transfers:
        from .transfer_budget import rebaseline_transfers
        try:
            old, new, path = rebaseline_transfers(root, overrides)
        except (ValueError, OSError) as e:
            print(f"chainlint: rebaseline-transfers refused: {e}",
                  file=sys.stderr)
            return 2
        print(f"chainlint: transfer budget rebaselined {old} -> {new} "
              f"({path})", file=sys.stderr)
        return 0

    if args.rebaseline_waits:
        from .thread_lint import rebaseline_waits
        try:
            old, new, path = rebaseline_waits(root, overrides)
        except (ValueError, OSError) as e:
            print(f"chainlint: rebaseline-waits refused: {e}",
                  file=sys.stderr)
            return 2
        print(f"chainlint: wait budget rebaselined {old} -> {new} "
              f"({path})", file=sys.stderr)
        return 0

    if args.rebaseline_shards:
        from .shard_budget import rebaseline_shards
        try:
            old, new, path = rebaseline_shards(root, overrides)
        except (ValueError, OSError) as e:
            print(f"chainlint: rebaseline-shards refused: {e}",
                  file=sys.stderr)
            return 2
        print(f"chainlint: collective budget rebaselined {old} -> {new} "
              f"({path})", file=sys.stderr)
        return 0

    passes = ([p.strip() for p in args.passes.split(",") if p.strip()]
              if args.passes else None)
    if passes is not None:
        # Validate BEFORE any --since filtering: a typo'd family must
        # error, never silently shrink to an empty (green) run.
        unknown = [p for p in passes if p not in pass_families()]
        if unknown:
            parser.error(f"unknown pass families {unknown}; "
                         f"have {sorted(pass_families())}")
    if args.since is not None:
        changed = _changed_files(root, args.since)
        if changed is None:
            print(f"chainlint: cannot git-diff against {args.since!r}",
                  file=sys.stderr)
            return 2
        since_families = families_for_changed(changed)
        passes = ([p for p in passes if p in since_families]
                  if passes is not None else since_families)

    notes: list[str] = []
    timings: dict[str, float] = {}
    try:
        # Raw findings once; suppressions applied in-process so the
        # same run can feed both the gate and the staleness audit.
        raw = run_all(root=root, passes=passes, overrides=overrides,
                      notes=notes, jobs=max(args.jobs, 1),
                      timings=timings, apply_suppress=False)
    except ValueError as e:
        parser.error(str(e))
    except OSError as e:
        # A typo'd --override or a checked file missing from this install
        # (e.g. a wheel without the C++ sources) is a clean usage error,
        # not a traceback.
        print(f"chainlint: cannot read a checked file: {e}",
              file=sys.stderr)
        return 2
    findings = apply_suppressions(raw, root)

    warnings: list[str] = []
    if args.audit_suppressions:
        ran = passes if passes is not None else list(pass_families())
        warnings = audit_from_raw(root, raw, ran)

    if args.as_json:
        payload = {
            "findings": [f.to_dict() for f in findings],
            "pass_timings_ms": timings,
        }
        if args.audit_suppressions:
            payload["stale_suppressions"] = warnings
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        for w in warnings:
            print(f"audit: {w}")
    if not args.quiet:
        for note in notes:
            print(f"note: {note}", file=sys.stderr)
        n_passes = len(passes) if passes is not None \
            else len(pass_families())
        print(f"chainlint: {len(findings)} finding(s) across "
              f"{n_passes} pass families",
              file=sys.stderr)
        if args.audit_suppressions:
            print(f"chainlint: {len(warnings)} stale suppression(s)",
                  file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
