"""CLI: python -m mpi_blockchain_tpu.analysis

Runs the chainlint pass families and exits non-zero on any finding —
the PR gate `make check` runs this before the test suite. See
docs/static_analysis.md for the rule catalogue.
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys

from . import default_root, pass_families, run_all

OVERRIDE_KEYS = ("capi", "ctypes_binding", "pybind", "chain_hpp",
                 "chain_cpp", "core_init", "sha_jnp", "header_test",
                 "mesh_py", "core_makefile", "core_src", "sim_py",
                 "telemetry_files", "resilience_files",
                 "adversary_files", "rank_scope_files")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_blockchain_tpu.analysis",
        description="chainlint: cross-language static analysis "
                    "(binding contract, header layout, JAX purity, "
                    "sanitizer matrix)")
    parser.add_argument("--root", type=pathlib.Path, default=None,
                        help="repo root (default: auto-detected)")
    parser.add_argument("--passes", default=None,
                        help="comma-separated subset of pass families "
                             f"(default: all of {sorted(pass_families())})")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings as a JSON array")
    parser.add_argument("--override", action="append", default=[],
                        metavar="KEY=PATH",
                        help="redirect one checked file (drift-fixture "
                             f"test seam); keys: {', '.join(OVERRIDE_KEYS)}")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="suppress the summary/notes lines")
    args = parser.parse_args(argv)

    overrides: dict[str, pathlib.Path] = {}
    for item in args.override:
        key, _, value = item.partition("=")
        if key not in OVERRIDE_KEYS or not value:
            parser.error(f"bad --override {item!r}; keys: "
                         f"{', '.join(OVERRIDE_KEYS)}")
        overrides[key] = pathlib.Path(value)

    passes = ([p.strip() for p in args.passes.split(",") if p.strip()]
              if args.passes else None)
    root = args.root if args.root is not None else default_root()
    notes: list[str] = []
    try:
        findings = run_all(root=root, passes=passes, overrides=overrides,
                           notes=notes)
    except ValueError as e:
        parser.error(str(e))
    except OSError as e:
        # A typo'd --override or a checked file missing from this install
        # (e.g. a wheel without the C++ sources) is a clean usage error,
        # not a traceback.
        print(f"chainlint: cannot read a checked file: {e}",
              file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
    if not args.quiet:
        for note in notes:
            print(f"note: {note}", file=sys.stderr)
        print(f"chainlint: {len(findings)} finding(s) across "
              f"{len(passes or pass_families())} pass families",
              file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
