"""JAX rules — AST purity/dtype lint over ops/, models/, parallel/.

The device kernels are only correct if they stay inside the jit tracing
model: no Python branching on traced values, no host work inside a traced
function, uint32 discipline on every SHA word, and mesh collectives only
over the canonical axis names. All four are silent-wrong-answer bugs on a
TPU, so they are linted statically:

  JAX001  Python if/while branches on a traced parameter inside a traced
          function (trace-time branch: compiles one side only)
  JAX002  host callback / host-sync call inside a traced function
  JAX003  numpy call (other than a dtype constructor) inside a traced
          function — host computation baked in as a constant
  JAX004  bare int literal in bitwise/shift SHA word arithmetic (dtype
          promotion risk; wrap in np.uint32/jnp.uint32)
  JAX005  mesh axis name not in the canonical set from parallel/mesh.py
  JAX006  telemetry call (counter/gauge/histogram/span/emit_event, or any
          telemetry.* function) inside a traced function — metrics and
          spans are host work; in the hot path they become host callbacks

"Traced function" is detected structurally: decorated with jax.jit (bare
or via functools.partial with static_argnames), wrapped by a jax.jit(...)
call, or passed as the function argument of lax.scan / lax.while_loop /
lax.fori_loop / lax.cond / shard_map. Nested helpers called from traced
code without one of those markers are deliberately out of scope — the rule
set prefers silence over false positives on host-side builder code.
"""
from __future__ import annotations

import ast
import pathlib
from dataclasses import dataclass, field

from . import Finding, override_files, rel_path

LINT_DIRS = ("ops", "models", "parallel")
# JAX004 scope: the kernels where every BinOp operand IS a SHA word.
# (models/fused.py does host-side config math like `1 << batch_pow2`, so
# the literal-operand heuristic would false-positive there.)
SHA_WORD_MODULES = ("ops/sha256_jnp.py", "ops/sha256_pallas.py",
                    "ops/sha256_sched.py")

DTYPE_CONSTRUCTORS = {
    "uint8", "uint16", "uint32", "uint64", "int8", "int16", "int32",
    "int64", "float16", "float32", "float64", "bool_", "dtype",
}
HOST_CALLBACK_NAMES = {"pure_callback", "io_callback", "host_callback"}
# The telemetry public API (mpi_blockchain_tpu/telemetry): bare-name calls
# to these, or any call on a module path containing 'telemetry', are host
# metric/span work and must stay outside the jit boundary (JAX006).
TELEMETRY_FUNCS = {"counter", "gauge", "histogram", "heartbeat", "span",
                   "emit_event"}
HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready", "copy_to_host",
                     "__array__"}
# Calls that trace a function argument -> which positional slots hold it.
TRACING_HOFS = {"scan": (0,), "while_loop": (0, 1), "fori_loop": (2,),
                "cond": (1, 2), "shard_map": (0,), "pallas_call": (0,)}
# Collectives/queries whose axis argument must be a canonical mesh axis
# -> the positional slot that argument occupies.
AXIS_CALLS = {"psum": 1, "pmin": 1, "pmax": 1, "pmean": 1, "all_gather": 1,
              "ppermute": 1, "axis_index": 0, "axis_size": 0}


def _call_name(node: ast.Call) -> str:
    """Rightmost name of the called expression: jax.lax.psum -> 'psum'."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _dotted(node: ast.expr) -> str:
    """Best-effort dotted path: jax.lax.psum -> 'jax.lax.psum'."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_jit_expr(node: ast.expr) -> tuple[bool, set[str]]:
    """(is a jax.jit marker, static_argnames it pins)."""
    if isinstance(node, (ast.Attribute, ast.Name)):
        d = _dotted(node)
        return d in ("jax.jit", "jit"), set()
    if isinstance(node, ast.Call):
        d = _dotted(node.func)
        if d in ("jax.jit", "jit"):
            return True, _static_argnames(node)
        if d in ("functools.partial", "partial") and node.args:
            inner, static = _is_jit_expr(node.args[0])
            return inner, static | _static_argnames(node)
    return False, set()


def _static_argnames(call: ast.Call) -> set[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                return {e.value for e in v.elts
                        if isinstance(e, ast.Constant)}
    return set()


@dataclass
class TracedFn:
    node: ast.FunctionDef
    static: set[str] = field(default_factory=set)

    @property
    def traced_params(self) -> set[str]:
        args = self.node.args
        names = [a.arg for a in args.args + args.posonlyargs
                 + args.kwonlyargs]
        return {n for n in names if n not in self.static
                and n != "axis_name"}


def _collect_traced_functions(tree: ast.Module) -> list[TracedFn]:
    by_name: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            by_name.setdefault(node.name, node)

    traced: dict[int, TracedFn] = {}

    def mark(fn: ast.FunctionDef, static: set[str]):
        traced.setdefault(id(fn), TracedFn(fn, static))

    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                is_jit, static = _is_jit_expr(dec)
                if is_jit:
                    mark(node, static)
        elif isinstance(node, ast.Call):
            name = _call_name(node)
            dotted = _dotted(node.func)
            if dotted in ("jax.jit", "jit") and node.args:
                target = node.args[0]
                static = _static_argnames(node)
                if isinstance(target, ast.Name) and target.id in by_name:
                    mark(by_name[target.id], static)
            elif name in TRACING_HOFS:
                for slot in TRACING_HOFS[name]:
                    if slot >= len(node.args):
                        continue
                    target = node.args[slot]
                    if isinstance(target, ast.Name) and target.id in by_name:
                        mark(by_name[target.id], set())
                    elif (isinstance(target, ast.Call)
                          and _dotted(target.func) in ("functools.partial",
                                                       "partial")
                          and target.args
                          and isinstance(target.args[0], ast.Name)
                          and target.args[0].id in by_name):
                        mark(by_name[target.args[0].id], set())
    return list(traced.values())


def _names_in(node: ast.expr) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _lint_traced_fn(findings, rel: str, tf: TracedFn):
    traced_params = tf.traced_params
    for node in ast.walk(tf.node):
        if isinstance(node, (ast.If, ast.While)):
            hot = _names_in(node.test) & traced_params
            if hot:
                kind = "if" if isinstance(node, ast.If) else "while"
                findings.append(Finding(
                    rel, node.lineno, "JAX001",
                    f"Python `{kind}` on traced value(s) "
                    f"{sorted(hot)} inside traced function "
                    f"'{tf.node.name}' — use lax.cond/lax.while_loop or "
                    f"mark the argument static"))
        elif isinstance(node, ast.Call):
            name = _call_name(node)
            dotted = _dotted(node.func)
            if (name in HOST_CALLBACK_NAMES
                    or dotted.startswith("jax.debug.")
                    or dotted in ("debug.print", "debug.callback")):
                findings.append(Finding(
                    rel, node.lineno, "JAX002",
                    f"host callback '{dotted or name}' inside traced "
                    f"function '{tf.node.name}'"))
            elif (name in HOST_SYNC_METHODS
                    and isinstance(node.func, ast.Attribute)):
                findings.append(Finding(
                    rel, node.lineno, "JAX002",
                    f"host-sync call '.{name}()' inside traced function "
                    f"'{tf.node.name}'"))
            elif ("telemetry" in dotted.split(".")[:-1]
                    or (isinstance(node.func, ast.Name)
                        and name in TELEMETRY_FUNCS)):
                findings.append(Finding(
                    rel, node.lineno, "JAX006",
                    f"telemetry call '{dotted or name}' inside traced "
                    f"function '{tf.node.name}' — metrics/spans are host "
                    f"work; record them outside the jit boundary"))
            elif (isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in ("np", "numpy")
                    and node.func.attr not in DTYPE_CONSTRUCTORS):
                findings.append(Finding(
                    rel, node.lineno, "JAX003",
                    f"numpy call 'np.{node.func.attr}' inside traced "
                    f"function '{tf.node.name}' — host computation baked "
                    f"in at trace time; use jnp or hoist it"))


_BITWISE = (ast.BitAnd, ast.BitOr, ast.BitXor, ast.LShift, ast.RShift)


def _lint_sha_words(findings, rel: str, tree: ast.Module):
    # Bare-literal operands are fine inside a dtype-cast call like
    # np.uint32(32 - n): record every BinOp nested under such a call.
    casted: set[int] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in DTYPE_CONSTRUCTORS):
            for sub in ast.walk(node):
                casted.add(id(sub))
    for node in ast.walk(tree):
        if (isinstance(node, ast.BinOp) and isinstance(node.op, _BITWISE)
                and id(node) not in casted):
            for side in (node.left, node.right):
                if (isinstance(side, ast.Constant)
                        and isinstance(side.value, int)):
                    findings.append(Finding(
                        rel, node.lineno, "JAX004",
                        f"bare int literal {side.value} in "
                        f"{type(node.op).__name__} word arithmetic — wrap "
                        f"it in np.uint32(...) to pin the SHA word dtype"))
                    break


def _canonical_axes(mesh_py: pathlib.Path) -> set[str]:
    """Axis names from every make_mesh/Mesh axis tuple in parallel/mesh.py
    — the single source of truth the rest of the tree must draw from."""
    axes: set[str] = set()
    try:
        tree = ast.parse(mesh_py.read_text(), filename=str(mesh_py))
    except (OSError, SyntaxError):
        return axes
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _call_name(node) in (
                "make_mesh", "Mesh"):
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, (ast.Tuple, ast.List)):
                    for e in arg.elts:
                        if (isinstance(e, ast.Constant)
                                and isinstance(e.value, str)):
                            axes.add(e.value)
    return axes


def _axis_strings(node: ast.Call) -> list[tuple[str, int]]:
    """String axis names used by this call, with line numbers."""
    out: list[tuple[str, int]] = []
    name = _call_name(node)
    candidates: list[ast.expr] = []
    if name in AXIS_CALLS:
        slot = AXIS_CALLS[name]
        if len(node.args) > slot:
            candidates.append(node.args[slot])
        candidates += [k.value for k in node.keywords
                       if k.arg in ("axis_name", "axis")]
    elif name in ("make_mesh", "Mesh"):
        candidates += list(node.args) + [k.value for k in node.keywords]
    elif name == "partial":
        candidates += [k.value for k in node.keywords
                       if k.arg == "axis_name"]
    for c in candidates:
        nodes = c.elts if isinstance(c, (ast.Tuple, ast.List)) else [c]
        for e in nodes:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append((e.value, e.lineno))
    return out


def _lint_axis_names(findings, rel: str, tree: ast.Module,
                     canonical: set[str]):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            for axis, lineno in _axis_strings(node):
                if axis not in canonical:
                    findings.append(Finding(
                        rel, lineno, "JAX005",
                        f"mesh axis name '{axis}' is not in the canonical "
                        f"set {sorted(canonical)} from parallel/mesh.py"))
        elif isinstance(node, ast.FunctionDef):
            args = node.args
            for a, d in zip(args.args[len(args.args)
                                      - len(args.defaults):],
                            args.defaults):
                if (a.arg == "axis_name" and isinstance(d, ast.Constant)
                        and isinstance(d.value, str)
                        and d.value not in canonical):
                    findings.append(Finding(
                        rel, d.lineno, "JAX005",
                        f"axis_name default '{d.value}' is not in the "
                        f"canonical set {sorted(canonical)}"))


def run_jax_lint(root: pathlib.Path, overrides=None,
                 notes=None) -> list[Finding]:
    overrides = overrides or {}
    pkg = root / "mpi_blockchain_tpu"
    mesh_py = overrides.get("mesh_py", pkg / "parallel" / "mesh.py")
    canonical = _canonical_axes(mesh_py)

    files = override_files(
        overrides, "jax_files",
        lambda: [p for d in LINT_DIRS
                 for p in sorted((pkg / d).glob("*.py"))])

    if not canonical and notes is not None:
        notes.append("jax: no canonical mesh axes found; JAX005 skipped")

    findings: list[Finding] = []
    for path in files:
        rel = rel_path(path, root)
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            findings.append(Finding(rel, e.lineno or 1, "JAX000",
                                    f"syntax error: {e.msg}"))
            continue
        for tf in _collect_traced_functions(tree):
            _lint_traced_fn(findings, rel, tf)
        if any(rel.replace("\\", "/").endswith(m)
               for m in SHA_WORD_MODULES) or "jax_files" in overrides:
            _lint_sha_words(findings, rel, tree)
        if canonical:
            _lint_axis_names(findings, rel, tree, canonical)
    return findings
