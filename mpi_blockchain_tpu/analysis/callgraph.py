"""Best-effort Python call-graph builder shared by the flow-aware passes.

CONC (thread-escape races) and HOTPATH (blocking calls on the dispatch
critical path) both need the same primitive: "which functions are
reachable from this entry point?". This module builds that graph from
nothing but the AST — stdlib-only, no imports of the analyzed code — so
the rules stay runnable in any environment, at the cost of well-known
static limits:

* **Name calls** resolve to a function of that name in the same module
  first, then to any same-named function in the analyzed file set.
* **``self.x()``** resolves to a method ``x`` of the enclosing class
  (same module first, then any class of the same name in the set).
* **Other attribute calls** (``obj.search()``) resolve to EVERY analyzed
  function named ``search`` — the deliberately conservative
  approximation of dynamic dispatch. There is no type inference and no
  dynamic-dispatch resolution (docs/static_analysis.md §Known limits).
* **Callables passed as values** (``on_block=...`` callbacks,
  ``functools.partial`` objects handed around) are invisible: a code
  path that only exists through a callback is out of the graph.

The approximation errs toward OVER-connecting (a rule sees more paths
than runtime has), which is the right polarity for drift lints: a false
edge can be suppressed inline with a justification, a missing edge would
rot silently.
"""
from __future__ import annotations

import ast
import collections
import dataclasses
import pathlib
from typing import Callable, Iterable

from . import rel_path

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclasses.dataclass
class FuncInfo:
    """One analyzed function/method (nested defs included)."""
    module: str                    # repo-relative posix path
    cls: str | None                # enclosing class name, if any
    name: str
    node: ast.FunctionDef | ast.AsyncFunctionDef
    lineno: int

    @property
    def qual(self) -> str:
        owner = f"{self.cls}." if self.cls else ""
        return f"{self.module}::{owner}{self.name}"

    @property
    def label(self) -> str:
        """Human label for finding messages: ``Miner.mine_block``."""
        return f"{self.cls}.{self.name}" if self.cls else self.name


def call_name(node: ast.Call) -> str:
    """Rightmost name of the called expression (jax.lax.psum -> psum)."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def dotted(node: ast.expr) -> str:
    """Best-effort dotted path; '' when not a plain attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class CallGraph:
    """Function table + name-based call resolution over a file set."""

    def __init__(self) -> None:
        self.functions: dict[str, FuncInfo] = {}
        self._by_name: dict[str, list[FuncInfo]] = {}
        self._by_method: dict[tuple[str, str], list[FuncInfo]] = {}

    # ---- construction ----------------------------------------------------

    def add_module(self, module: str, tree: ast.Module) -> None:
        """Records every function/method (including nested defs)."""
        def visit(node: ast.AST, cls: str | None) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                elif isinstance(child, _FUNC_NODES):
                    info = FuncInfo(module, cls, child.name, child,
                                    child.lineno)
                    self.functions.setdefault(info.qual, info)
                    self._by_name.setdefault(child.name, []).append(info)
                    if cls is not None:
                        self._by_method.setdefault(
                            (cls, child.name), []).append(info)
                    # Nested defs KEEP the enclosing class: a closure
                    # inside a method captures `self`, so its
                    # `self.attr` mutations and `self.method()` calls
                    # belong to that class (the thread-body-as-closure
                    # idiom CONC must see).
                    visit(child, cls)
                else:
                    visit(child, cls)
        visit(tree, None)

    @classmethod
    def from_files(cls, root: pathlib.Path,
                   files: Iterable[pathlib.Path]
                   ) -> tuple["CallGraph", list[tuple[str, int, str]]]:
        """(graph, [(rel, lineno, syntax-error message)]) for a file set."""
        graph = cls()
        errors: list[tuple[str, int, str]] = []
        for path in files:
            path = pathlib.Path(path)
            rel = rel_path(path, root)
            try:
                tree = ast.parse(path.read_text(), filename=str(path))
            except SyntaxError as e:
                errors.append((rel, e.lineno or 1, e.msg or "syntax error"))
                continue
            except OSError:
                continue
            graph.add_module(rel, tree)
        return graph, errors

    # ---- resolution ------------------------------------------------------

    def _prefer_module(self, candidates: list[FuncInfo],
                       module: str) -> list[FuncInfo]:
        local = [c for c in candidates if c.module == module]
        return local if local else candidates

    def resolve_ref(self, expr: ast.expr,
                    caller: FuncInfo | None) -> list[FuncInfo]:
        """Function(s) a callable REFERENCE may denote (thread targets,
        executor-submitted fns): ``fn`` / ``self.method`` forms only."""
        module = caller.module if caller is not None else ""
        if isinstance(expr, ast.Name):
            return self._prefer_module(
                self._by_name.get(expr.id, []), module)
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and caller is not None
                and caller.cls is not None):
            return self._prefer_module(
                self._by_method.get((caller.cls, expr.attr), []), module)
        return []

    def resolve_call(self, node: ast.Call,
                     caller: FuncInfo) -> list[FuncInfo]:
        """Callee candidates of one call site (see module docstring for
        the resolution rules and their limits)."""
        f = node.func
        if isinstance(f, (ast.Name, ast.Attribute)):
            via_ref = self.resolve_ref(f, caller)
            if via_ref:
                return via_ref
        if isinstance(f, ast.Attribute):
            # Dynamic-dispatch approximation: every analyzed function of
            # this name, wherever it lives.
            return self._by_name.get(f.attr, [])
        if isinstance(f, ast.Name):
            return self._by_name.get(f.id, [])
        return []

    # ---- traversal -------------------------------------------------------

    def resolve_roots(self, entry_points: Iterable[tuple[str, str]]
                      ) -> tuple[list["FuncInfo"], list[tuple[str, str]]]:
        """Resolves ``(class, method)`` entry points to their FuncInfos:
        ``(roots, missing)``. The one copy of the root-set lookup every
        hot-path-rooted pass (HOTPATH, SYNC) shares — a missing entry is
        the pass's "the lint is checking nothing" rule (HOT002/SYNC003),
        reported per pass so a ``--passes`` subset still fires it."""
        roots: list[FuncInfo] = []
        missing: list[tuple[str, str]] = []
        for cls, method in entry_points:
            matches = [f for f in self.functions.values()
                       if f.cls == cls and f.name == method]
            if matches:
                roots.extend(matches)
            else:
                missing.append((cls, method))
        return roots, missing

    def owner_map(self, module: str) -> dict[int, "FuncInfo"]:
        """id(ast node) -> FuncInfo of the innermost enclosing function,
        for every node in ``module``'s functions. Traversal stops at
        nested defs — each claims its own body. The one copy of the
        innermost-owner lookup the flow-aware passes (CONC thread
        spawns, LCK lock scopes, FUT future provenance) share."""
        owners: dict[int, FuncInfo] = {}
        for info in self.functions.values():
            if info.module != module:
                continue
            stack = list(ast.iter_child_nodes(info.node))
            while stack:
                sub = stack.pop()
                owners[id(sub)] = info
                if isinstance(sub, _FUNC_NODES):
                    continue
                stack.extend(ast.iter_child_nodes(sub))
        return owners

    def nested_parents(self) -> dict[str, str]:
        """{nested function qual: qual of its NEAREST enclosing analyzed
        function} for every closure/thread-body def. Passes that analyze
        whole function bodies inline (the provenance walk) use this to
        skip a nested def only when an ancestor is itself analyzed —
        a reachable closure whose enclosing function is NOT reachable
        still gets its own standalone walk. The walk switches parent at
        every function boundary, so starting from any ancestor yields
        the same nearest-parent answer."""
        by_node = {id(info.node): info.qual
                   for info in self.functions.values()}
        parents: dict[str, str] = {}

        def visit(node: ast.AST, parent_qual: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FUNC_NODES):
                    qual = by_node.get(id(child))
                    if qual is not None:
                        parents[qual] = parent_qual
                        visit(child, qual)
                        continue
                visit(child, parent_qual)

        for info in self.functions.values():
            visit(info.node, info.qual)
        return parents

    def reachable(self, roots: Iterable[FuncInfo],
                  prune: Callable[[FuncInfo], bool] | None = None
                  ) -> dict[str, list[str]]:
        """BFS closure from ``roots``: {qual: call chain of labels from
        the root, root first}. ``prune(info)`` True stops traversal AT
        that function (it is excluded from the result entirely — the
        sanctioned-seam mechanism)."""
        chains: dict[str, list[str]] = {}
        queue: collections.deque[FuncInfo] = collections.deque()
        for r in roots:
            if prune is not None and prune(r):
                continue
            if r.qual not in chains:
                chains[r.qual] = [r.label]
                queue.append(r)
        while queue:
            info = queue.popleft()
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                for callee in self.resolve_call(node, info):
                    if callee.qual in chains:
                        continue
                    if prune is not None and prune(callee):
                        continue
                    chains[callee.qual] = (chains[info.qual]
                                           + [callee.label])
                    queue.append(callee)
        return chains
