"""TRB rules — the device-transfer budget ratchet for the sweep path.

OPBUDGET ratchets the kernel's per-nonce ALU work; nothing ratcheted the
per-dispatch overhead around it — yet AsicBoost (arxiv 1604.00575) and
the inner-for-loop paper (arxiv 1906.02770) both show dispatch-overhead
discipline, not just ALU counts, deciding mining throughput, and the
round-4 redesign's entire win was deleting host<->device round trips.
This pass is the tripwire that keeps them deleted: a committed baseline
(``TRANSFERBUDGET.json``) pins a **static transfer-site census** — a
deterministic count of host<->device transfer/sync call sites in the
sweep-path sources — and the build fails when the census grows.

The static census counts, per scoped file:

* ``np.asarray``/``np.array`` (D2H materialization; the jnp spellings
  are device-side constructors and are NOT transfers),
* ``jax.device_put``/``device_get``,
* ``.block_until_ready()``/``.copy_to_host_async()``/
  ``.addressable_data()``/``.item()``/``.tolist()``,
* calls to the sanctioned seam itself
  (``replicated_host_value``/``replicated_host_values``) — adding a new
  seam call site IS adding a transfer, and must show up in a reviewed
  baseline diff.

Like OPBUDGET's static ALU census it is a monotone *proxy*: any edit
that adds a transfer/sync site raises it, which is all a ratchet needs.
The physically-meaningful numbers ride along in the baseline's
``traced`` section: the one sanctioned mover —
``python -m mpi_blockchain_tpu.analysis.transfer_budget --write``
(imports jax; this gate pass never does) — traces the sweep callables
per backend flavor (the multi-round searcher, the fused k-block miner)
and censuses actual transfer/sync primitives in the jaxpr:
``device_put`` equations, host callbacks, and ``convert_element_type``
*widenings* (an unexpected widening doubles the bytes every transfer
moves).

  TRB001  the static transfer-site census exceeds the committed budget
          — transfers on the sweep path only ratchet DOWN. A justified
          increase goes through the sanctioned mover and a reviewed
          TRANSFERBUDGET.json diff; ``--rebaseline-transfers`` only
          accepts a LOWER census.
  TRB002  TRANSFERBUDGET.json is missing, unparseable, or lacks the
          required keys — the transfer ratchet is not armed.
  TRB003  the census scope resolves to no readable source files — the
          gate is counting nothing (fires when a refactor moves the
          sweep files without updating SWEEP_SCOPE here).

Override keys: ``transferbudget_json`` (baseline path),
``transfer_files`` (census file set) — the drift-fixture seams.
"""
from __future__ import annotations

import ast
import pathlib

from . import Finding, override_files, rel_path
from .budget import (int_key_error, mover_main, read_json_object,
                     refuse_upward, require_amendable, write_json_budget)
from .callgraph import call_name, dotted

BASELINE_NAME = "TRANSFERBUDGET.json"
REQUIRED_KEYS = ("static_transfer_sites", "traced")
MOVER = "python -m mpi_blockchain_tpu.analysis.transfer_budget --write"

#: The sweep-path sources whose transfer sites are budgeted (the files
#: between the mine-loop entry points and the device program).
SWEEP_SCOPE = (
    "mpi_blockchain_tpu/models/miner.py",
    "mpi_blockchain_tpu/models/fused.py",
    "mpi_blockchain_tpu/backend/tpu.py",
    "mpi_blockchain_tpu/backend/cpu.py",
    "mpi_blockchain_tpu/parallel/mesh.py",
    "mpi_blockchain_tpu/resilience/dispatch.py",
)

_NP_TRANSFER_DOTTED = {"np.asarray", "np.array", "numpy.asarray",
                       "numpy.array"}
_TRANSFER_NAMES = {"device_put", "device_get"}
_TRANSFER_METHODS = {"block_until_ready", "copy_to_host_async",
                     "addressable_data", "item", "tolist"}
_SEAM_CALLS = {"replicated_host_value", "replicated_host_values"}


def _site_label(node: ast.Call) -> str | None:
    """The census label when this call is a transfer/sync site."""
    name = call_name(node)
    d = dotted(node.func)
    if d in _NP_TRANSFER_DOTTED:
        return d
    if name in _TRANSFER_NAMES:
        return d or name
    if isinstance(node.func, ast.Attribute) and name in _TRANSFER_METHODS:
        return f".{name}()"
    if name in _SEAM_CALLS:
        return name
    return None


def static_transfer_census(
        root: pathlib.Path, files: list[pathlib.Path]
) -> tuple[int, dict[str, int], list[tuple[str, int, str]],
           tuple[str, int] | None]:
    """(total, per-label counts, [(rel, line, syntax msg)], first site)
    over the scoped files. ``first site`` anchors TRB001 at a
    suppressible source line."""
    total = 0
    by_label: dict[str, int] = {}
    errors: list[tuple[str, int, str]] = []
    first: tuple[str, int] | None = None
    for path in sorted(pathlib.Path(p) for p in files):
        rel = rel_path(path, root)
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            errors.append((rel, e.lineno or 1, e.msg or "syntax error"))
            continue
        except OSError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            label = _site_label(node)
            if label is None:
                continue
            total += 1
            by_label[label] = by_label.get(label, 0) + 1
            if first is None or (rel, node.lineno) < first:
                first = (rel, node.lineno)
    return total, by_label, errors, first


def _paths(root: pathlib.Path, overrides: dict
           ) -> tuple[pathlib.Path, list[pathlib.Path]]:
    baseline = pathlib.Path(overrides.get("transferbudget_json",
                                          root / BASELINE_NAME))
    files = override_files(overrides, "transfer_files",
                           lambda: [root / p for p in SWEEP_SCOPE])
    return baseline, files


def load_baseline(baseline: pathlib.Path) -> tuple[dict | None, str]:
    """(budget dict, error message) — dict None iff invalid."""
    data, err = read_json_object(baseline)
    if data is None:
        return None, err
    err = int_key_error(data, baseline.name, "static_transfer_sites",
                        MOVER)
    if err:
        return None, err
    if not isinstance(data.get("traced"), dict):
        return None, (f"{baseline.name} lacks the 'traced' per-flavor "
                      f"jaxpr census — regenerate it with `{MOVER}`")
    return data, ""


def run_transfer_budget(root: pathlib.Path, overrides=None,
                        notes=None) -> list[Finding]:
    overrides = overrides or {}
    baseline_path, files = _paths(root, overrides)
    baseline, err = load_baseline(baseline_path)
    if baseline is None:
        return [Finding(rel_path(baseline_path, root), 1, "TRB002",
                        f"transfer-budget ratchet is not armed: {err}")]
    readable = [p for p in files if pathlib.Path(p).is_file()]
    if not readable:
        return [Finding("mpi_blockchain_tpu", 1, "TRB003",
                        "transfer-budget census scope resolves to no "
                        "readable source file — the gate is counting "
                        "nothing; update SWEEP_SCOPE in "
                        "analysis/transfer_budget.py alongside the "
                        "refactor")]
    total, by_label, errors, first = static_transfer_census(root, readable)
    findings = [Finding(rel, lineno, "TRB000", f"syntax error: {msg}")
                for rel, lineno, msg in errors]
    budget = baseline["static_transfer_sites"]
    if total > budget:
        anchor, line = first if first is not None else (
            rel_path(pathlib.Path(readable[0]), root), 1)
        breakdown = ", ".join(f"{k}×{v}" for k, v in sorted(by_label.items()))
        findings.append(Finding(
            anchor, line, "TRB001",
            f"static transfer-site census grew: {total} > budget "
            f"{budget} ({breakdown}). Host<->device transfers on the "
            f"sweep path only ratchet DOWN (ROADMAP item 1 depends on "
            f"it); if this increase is justified, re-census with "
            f"`python -m mpi_blockchain_tpu.analysis.transfer_budget "
            f"--write` and commit the TRANSFERBUDGET.json diff"))
    elif total < budget and notes is not None:
        notes.append(f"transfer_budget: static census {total} is below "
                     f"the budget {budget} — ratchet it down with "
                     f"--rebaseline-transfers (or the --write mover)")
    return findings


def rebaseline_transfers(root: pathlib.Path,
                         overrides=None) -> tuple[int, int, pathlib.Path]:
    """Writes the current static census into the baseline, refusing to
    RAISE it (the ratchet). Returns (old, new, path). Raises ValueError
    when the census is higher, the scope is empty, or there is no valid
    baseline to amend — bootstrapping (and any justified raise) is the
    sanctioned mover's job (``transfer_budget --write``, which records
    the traced per-flavor census too)."""
    overrides = overrides or {}
    baseline_path, files = _paths(root, overrides)
    readable = [p for p in files if pathlib.Path(p).is_file()]
    if not readable:
        raise ValueError("transfer census scope resolves to no readable "
                         "source file — nothing to baseline")
    total, by_label, errors, _ = static_transfer_census(root, readable)
    if errors:
        raise ValueError(f"census scope has syntax errors: {errors[0]}")
    old_data, err = load_baseline(baseline_path)
    old_data = require_amendable(old_data, err, MOVER)
    old = old_data["static_transfer_sites"]
    refuse_upward(total, old, census_label="static transfer census",
                  policy="Transfers only ratchet down",
                  mover=MOVER, baseline_name=BASELINE_NAME)
    data = dict(old_data)
    data["static_transfer_sites"] = total
    data["static_by_site"] = dict(sorted(by_label.items()))
    # The scope list must describe the files the counts came from, or
    # the committed review surface misstates the budget's coverage.
    data["scope"] = [rel_path(pathlib.Path(p), root) for p in
                     sorted(pathlib.Path(f) for f in readable)]
    write_json_budget(baseline_path, data)
    return old, total, baseline_path


# ---- the sanctioned mover (imports jax; never run by the gate) -------------


def _count_jaxpr(jaxpr, counts: dict[str, int]) -> None:
    """Recursive primitive census over a jaxpr and its subjaxprs."""
    import numpy as np

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "device_put":
            counts["device_put"] += 1
        elif "callback" in name:
            counts["callbacks"] += 1
        elif name == "convert_element_type":
            try:
                new = np.dtype(eqn.params["new_dtype"])
                old = np.dtype(eqn.invars[0].aval.dtype)
                if new.itemsize > old.itemsize:
                    counts["convert_widenings"] += 1
            except (KeyError, TypeError, AttributeError):
                pass
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                inner = getattr(sub, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    _count_jaxpr(inner, counts)
                elif hasattr(sub, "eqns"):
                    _count_jaxpr(sub, counts)


def trace_transfer_census() -> dict[str, dict[str, int]]:
    """Traces the sweep callables per backend flavor and censuses
    transfer/sync primitives in their jaxprs. Small shapes + the jnp
    kernel: the transfer-primitive census is shape- and
    platform-independent, and tracing never runs the program."""
    import jax
    import numpy as np

    from ..backend.tpu import make_multiround_search_fn
    from ..models.fused import make_fused_miner

    flavors: dict[str, dict[str, int]] = {}

    def census(fn, *args) -> dict[str, int]:
        counts = {"device_put": 0, "callbacks": 0, "convert_widenings": 0}
        closed = jax.make_jaxpr(fn)(*args)
        _count_jaxpr(closed.jaxpr, counts)
        counts["total_transfer_prims"] = (
            counts["device_put"] + counts["callbacks"]
            + counts["convert_widenings"])
        return counts

    u32 = np.uint32
    multiround, _ = make_multiround_search_fn(
        batch_size=1 << 8, difficulty_bits=12, kernel="jnp")
    from ..ops.sha256_sched import EXT_WORDS
    flavors["tpu_multiround"] = census(
        multiround, np.zeros(EXT_WORDS, u32), u32(0), u32(4))
    fused = make_fused_miner(k_blocks=2, batch_pow2=8, difficulty_bits=8,
                             kernel="jnp")
    flavors["fused"] = census(
        fused, np.zeros(8, u32), np.zeros((2, 8), u32), u32(0))
    return flavors


def write_budget(root: pathlib.Path | None = None,
                 overrides=None) -> pathlib.Path:
    """The one sanctioned mover: full rewrite of TRANSFERBUDGET.json —
    static census (may move either way; the committed diff is the
    review surface) plus the traced per-flavor jaxpr census."""
    from . import default_root

    root = root if root is not None else default_root()
    baseline_path, files = _paths(root, overrides or {})
    readable = [p for p in files if pathlib.Path(p).is_file()]
    total, by_label, errors, _ = static_transfer_census(root, readable)
    if errors:
        raise ValueError(f"census scope has syntax errors: {errors[0]}")
    data = {
        "static_transfer_sites": total,
        "static_by_site": dict(sorted(by_label.items())),
        "scope": [rel_path(pathlib.Path(p), root) for p in readable],
        "traced": trace_transfer_census(),
        "writer": MOVER,
    }
    write_json_budget(baseline_path, data)
    return baseline_path


def main(argv=None) -> int:
    return mover_main(
        argv,
        prog="python -m mpi_blockchain_tpu.analysis.transfer_budget",
        description="the sanctioned TRANSFERBUDGET.json mover: traces "
                    "the sweep callables (imports jax) and rewrites "
                    "the committed budget; the chainlint gate itself "
                    "stays stdlib-only",
        write_help="re-census and rewrite TRANSFERBUDGET.json",
        label="transfer_budget", writer=write_budget)


if __name__ == "__main__":
    import sys
    sys.exit(main())
