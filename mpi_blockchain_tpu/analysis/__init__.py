"""chainlint — cross-language static analysis for the four-backend miner.

The repo's correctness story is that four backends (scalar C++ core,
ctypes/pybind11 bindings, jnp, Pallas) mine byte-identical chains. The
dynamic equivalence suite proves that at run time; this package catches the
classic *drift* bugs at analysis time, before any run launches:

* ``binding_contract`` — every ``extern "C"`` symbol in ``core/src/capi.cpp``
  cross-checked against the ctypes ``argtypes``/``restype`` declarations and
  the pybind11 surface (BIND0xx rules).
* ``header_layout`` — the frozen 80-byte header byte layout, cross-checked
  between the C++ struct/serializer, the Python ``HeaderFields`` veneer, the
  jnp kernel's nonce word index, and the golden-byte tests (HDR0xx rules).
* ``jax_lint`` — AST lint of ``ops/``, ``models/``, ``parallel/`` for traced
  branching, host callbacks, numpy leaks into jitted code, non-uint32 SHA
  word arithmetic, and non-canonical mesh axis names (JAX0xx rules).
* ``sanitizers`` — the tsan/asan/ubsan Makefile matrix plus the
  cppcheck/clang-tidy ``analyze`` target, surfaced as SAN0xx rules (tools
  gracefully skip when not installed).
* ``telemetry_lint`` — causal-stamp discipline on the simulation bus:
  sim-bus events must carry ``lamport``/``node`` (i.e. go through
  ``CausalLog.record``), or the forensics merge cannot place them
  (TEL0xx rules).
* ``resilience_lint`` — swallow-proof fault handling in dispatch/IO
  paths: no bare ``except:`` / ``except Exception: pass`` outside the
  sanctioned resilience policy layer (RES0xx rules).

CLI: ``python -m mpi_blockchain_tpu.analysis`` — exits non-zero on any
finding. Inline suppression: a ``chainlint: disable=RULE`` comment on the
flagged line (see docs/static_analysis.md).

This module imports only the standard library (no jax, no ctypes load, no
C++ build), so the CLI is safe to run in any environment, including ones
where the accelerator stack is absent.
"""
from __future__ import annotations

import dataclasses
import pathlib
import re
from typing import Callable, Iterable

REPO_PACKAGE = "mpi_blockchain_tpu"

_SUPPRESS_RE = re.compile(r"chainlint:\s*disable=([\w,\- ]+)")
_SUPPRESS_FILE_RE = re.compile(r"chainlint:\s*disable-file=([\w,\- ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One structured finding: tests assert on ``rule`` ids."""
    file: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _suppressed_rules(match: re.Match | None) -> set[str]:
    if match is None:
        return set()
    return {r.strip() for r in match.group(1).split(",") if r.strip()}


def apply_suppressions(findings: Iterable[Finding],
                       root: pathlib.Path) -> list[Finding]:
    """Drops findings suppressed inline in their source file.

    Line-level: the flagged line carries ``chainlint: disable=RULE[,RULE]``
    (or ``disable=all``). File-level: any of the first 10 lines carries
    ``chainlint: disable-file=RULE[,RULE]``.
    """
    kept: list[Finding] = []
    cache: dict[str, list[str]] = {}
    for f in findings:
        path = root / f.file
        lines = cache.get(f.file)
        if lines is None:
            try:
                lines = path.read_text(errors="replace").splitlines()
            except OSError:
                lines = []
            cache[f.file] = lines
        file_rules: set[str] = set()
        for head in lines[:10]:
            file_rules |= _suppressed_rules(_SUPPRESS_FILE_RE.search(head))
        line_rules: set[str] = set()
        if 1 <= f.line <= len(lines):
            line_rules = _suppressed_rules(
                _SUPPRESS_RE.search(lines[f.line - 1]))
        active = file_rules | line_rules
        if f.rule in active or "all" in active:
            continue
        kept.append(f)
    return kept


def default_root() -> pathlib.Path:
    """The repo root: parent of the mpi_blockchain_tpu package dir."""
    return pathlib.Path(__file__).resolve().parent.parent.parent


def pass_families() -> dict[str, Callable[..., list[Finding]]]:
    """Registry of the pass families the CLI runs (import deferred so a
    syntax error in one pass does not take down the others' rule docs)."""
    from .binding_contract import run_binding_contract
    from .header_layout import run_header_layout
    from .jax_lint import run_jax_lint
    from .resilience_lint import run_resilience_lint
    from .sanitizers import run_sanitizers
    from .telemetry_lint import run_telemetry_lint
    return {
        "binding": run_binding_contract,
        "header": run_header_layout,
        "jax": run_jax_lint,
        "sanitizers": run_sanitizers,
        "telemetry": run_telemetry_lint,
        "resilience": run_resilience_lint,
    }


def run_all(root: pathlib.Path | None = None,
            passes: Iterable[str] | None = None,
            overrides: dict[str, pathlib.Path] | None = None,
            notes: list[str] | None = None) -> list[Finding]:
    """Runs the selected pass families and returns suppression-filtered
    findings. ``overrides`` maps checker file keys (e.g. ``capi``,
    ``chain_hpp``) to alternate paths — the drift-fixture test seam.
    ``notes`` collects non-finding diagnostics (e.g. skipped tools)."""
    root = root if root is not None else default_root()
    registry = pass_families()
    selected = list(passes) if passes is not None else list(registry)
    unknown = [p for p in selected if p not in registry]
    if unknown:
        raise ValueError(f"unknown pass families {unknown}; "
                         f"have {sorted(registry)}")
    findings: list[Finding] = []
    for name in selected:
        findings.extend(registry[name](root, overrides=overrides or {},
                                       notes=notes))
    return apply_suppressions(findings, root)
