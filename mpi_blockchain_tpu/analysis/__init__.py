"""chainlint — cross-language static analysis for the four-backend miner.

The repo's correctness story is that four backends (scalar C++ core,
ctypes/pybind11 bindings, jnp, Pallas) mine byte-identical chains. The
dynamic equivalence suite proves that at run time; this package catches the
classic *drift* bugs at analysis time, before any run launches:

* ``binding_contract`` — every ``extern "C"`` symbol in ``core/src/capi.cpp``
  cross-checked against the ctypes ``argtypes``/``restype`` declarations and
  the pybind11 surface (BIND0xx rules).
* ``header_layout`` — the frozen 80-byte header byte layout, cross-checked
  between the C++ struct/serializer, the Python ``HeaderFields`` veneer, the
  jnp kernel's nonce word index, and the golden-byte tests (HDR0xx rules).
* ``jax_lint`` — AST lint of ``ops/``, ``models/``, ``parallel/`` for traced
  branching, host callbacks, numpy leaks into jitted code, non-uint32 SHA
  word arithmetic, and non-canonical mesh axis names (JAX0xx rules).
* ``sanitizers`` — the tsan/asan/ubsan Makefile matrix plus the
  cppcheck/clang-tidy ``analyze`` target, surfaced as SAN0xx rules (tools
  gracefully skip when not installed).
* ``telemetry_lint`` — causal-stamp discipline on the simulation bus:
  sim-bus events must carry ``lamport``/``node`` (i.e. go through
  ``CausalLog.record``), or the forensics merge cannot place them
  (TEL0xx rules).
* ``resilience_lint`` — swallow-proof fault handling in dispatch/IO
  paths: no bare ``except:`` / ``except Exception: pass`` outside the
  sanctioned resilience policy layer (RES0xx rules).
* ``conc_lint`` — flow-aware thread-escape race detection: state mutated
  both inside and outside a thread body without a lock (CONC0xx rules).
* ``spmd_lint`` — collective-consistency over the mesh code paths:
  rank-conditional collectives, non-canonical axis names, collectives
  skippable through a swallowing ``try`` (SPMD0xx rules).
* ``hotpath_lint`` — blocking calls reachable on the dispatch hot path
  outside the sanctioned async seams (HOT0xx rules).
* ``sync_lint`` — device-sync discipline on the same hot path: a
  value-provenance pass tags device-origin values (backend ``search``
  results, dispatched device programs, ``jnp.*``) and flags implicit
  host syncs and device values escaping into Python control flow
  outside the sanctioned materialization seam (SYNC0xx rules).
* ``donation_lint`` — buffer-donation correctness: use-after-donate,
  sweep-shaped dispatches threading an undonated buffer, donation of
  live host state (DON0xx rules).
* ``opbudget`` — the jaxpr op-budget ratchet: the kernel's static ALU
  census must not exceed the committed ``OPBUDGET.json`` (OPB0xx rules).
* ``transfer_budget`` — the device-transfer ratchet: the sweep path's
  static transfer/sync-site census must not exceed the committed
  ``TRANSFERBUDGET.json`` (TRB0xx rules).
* ``lock_lint`` — deadlock discipline over the threaded substrate: a
  per-module lock-acquisition graph flags lock-order inversions,
  blocking waits while holding a lock, and callback invocations under
  a lock (LCK0xx rules).
* ``future_lint`` — future-lifecycle provenance: dropped
  ``search_async``/``submit`` futures (lost errors), unbounded
  ``.result()``/``.get()`` outside the watchdogged seams, and
  done-callbacks mutating shared state without the owning lock
  (FUT0xx rules).
* ``thread_lint`` — thread lifecycle + the blocking-wait ratchet:
  non-daemon threads nobody joins, thread-side unlocked writes racing
  host-side reads (THR0xx rules), and the static blocking-wait census
  pinned in the committed ``WAITBUDGET.json`` (TBW0xx rules).
* ``shard_lint`` — partition-spec & axis-context discipline on the
  mesh code: shard_map in/out_specs arity drift, collectives reachable
  without an enclosing axis context, rank-divergent values flowing
  into traced shapes/trip counts, and raw shard_map imports outside
  the sanctioned compat seam (SHD0xx rules).
* ``shard_budget`` — the collective-site ratchet: the SPMD scope's
  static collective call-site census must not exceed the committed
  ``SHARDBUDGET.json``, whose traced section pins exactly which
  collective primitives each mesh sweep dispatch carries (SBD0xx
  rules).

CLI: ``python -m mpi_blockchain_tpu.analysis`` — exits non-zero on any
finding. Findings are emitted in a deterministic (file, line, rule)
order. Inline suppression: a ``chainlint: disable=RULE`` comment on the
flagged line (see docs/static_analysis.md); ``--audit-suppressions``
reports suppressions whose rule no longer fires.

This module imports only the standard library (no jax, no ctypes load, no
C++ build), so the CLI is safe to run in any environment, including ones
where the accelerator stack is absent.
"""
from __future__ import annotations

import ast
import dataclasses
import pathlib
import re
import threading
from typing import Callable, Iterable

REPO_PACKAGE = "mpi_blockchain_tpu"

_SUPPRESS_RE = re.compile(r"chainlint:\s*disable=([\w,\- ]+)")
_SUPPRESS_FILE_RE = re.compile(r"chainlint:\s*disable-file=([\w,\- ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One structured finding: tests assert on ``rule`` ids."""
    file: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _suppressed_rules(match: re.Match | None) -> set[str]:
    if match is None:
        return set()
    return {r.strip() for r in match.group(1).split(",") if r.strip()}


def apply_suppressions(findings: Iterable[Finding],
                       root: pathlib.Path) -> list[Finding]:
    """Drops findings suppressed inline in their source file.

    Line-level: the flagged line carries ``chainlint: disable=RULE[,RULE]``
    (or ``disable=all``). File-level: any of the first 10 lines carries
    ``chainlint: disable-file=RULE[,RULE]``.
    """
    kept: list[Finding] = []
    cache: dict[str, list[str]] = {}
    for f in findings:
        path = root / f.file
        lines = cache.get(f.file)
        if lines is None:
            try:
                lines = path.read_text(errors="replace").splitlines()
            except OSError:
                lines = []
            cache[f.file] = lines
        file_rules: set[str] = set()
        for head in lines[:10]:
            file_rules |= _suppressed_rules(_SUPPRESS_FILE_RE.search(head))
        line_rules: set[str] = set()
        if 1 <= f.line <= len(lines):
            line_rules = _suppressed_rules(
                _SUPPRESS_RE.search(lines[f.line - 1]))
        active = file_rules | line_rules
        if f.rule in active or "all" in active:
            continue
        kept.append(f)
    return kept


#: Shared (text, AST) cache for the file-scoped passes. The conc/lock/
#: future/thread families walk heavily-overlapping file sets on every
#: ``make lint``; parsing each source once instead of once PER family
#: is what keeps the grown pass set inside the wall-time budget on a
#: single-core runner. Keyed by (path, mtime_ns, size) so a rewritten
#: override fixture re-parses; guarded for the ``--jobs`` thread pool.
_SOURCE_CACHE: dict[tuple, tuple[str, ast.Module | None,
                                 tuple[int, str] | None]] = {}
_SOURCE_LOCK = threading.Lock()


def source_cached(path: pathlib.Path) -> tuple[str, ast.Module | None,
                                               tuple[int, str] | None]:
    """(text, tree, syntax_error) for a source file, memoized across
    pass families. ``tree`` is None iff the file failed to parse;
    ``syntax_error`` is then ``(lineno, msg)``. Raises OSError like
    ``read_text`` would (callers already handle unreadable files)."""
    path = pathlib.Path(path)
    st = path.stat()
    key = (str(path), st.st_mtime_ns, st.st_size)
    with _SOURCE_LOCK:
        hit = _SOURCE_CACHE.get(key)
    if hit is not None:
        return hit
    text = path.read_text()
    try:
        tree = ast.parse(text, filename=str(path))
        entry = (text, tree, None)
    except SyntaxError as e:
        entry = (text, None, (e.lineno or 1, e.msg or "syntax error"))
    with _SOURCE_LOCK:
        if len(_SOURCE_CACHE) > 4096:    # fixture churn in long test runs
            _SOURCE_CACHE.clear()
        _SOURCE_CACHE[key] = entry
    return entry


def default_root() -> pathlib.Path:
    """The repo root: parent of the mpi_blockchain_tpu package dir."""
    return pathlib.Path(__file__).resolve().parent.parent.parent


def rel_path(path: pathlib.Path, root: pathlib.Path) -> str:
    """Repo-relative rendering used in findings (falls back to the
    given path for override fixtures outside the repo). One copy: the
    suppression/audit machinery joins findings on this string, so every
    pass must render it identically."""
    path = pathlib.Path(path)
    return (str(path.relative_to(root)) if path.is_relative_to(root)
            else str(path))


def package_scope(root: pathlib.Path, subdirs: Iterable[str] = (),
                  extras: Iterable[str] = (),
                  core_glob: bool = False) -> list[pathlib.Path]:
    """Default scope-file builder shared by the file-scoped passes:
    rglob of package subdirs + optional ``core/*.py`` glob (top level
    only — core/src is C++) + explicit package-relative extras, sorted.
    One copy, so a sweep-path refactor updates every family's scope in
    its pass module's argument list rather than three hand-rolled
    walkers."""
    pkg = root / REPO_PACKAGE
    files: list[pathlib.Path] = []
    for sub in subdirs:
        d = pkg / sub
        if d.is_dir():
            files += [p for p in d.rglob("*.py")
                      if "__pycache__" not in p.parts]
    if core_glob:
        core = pkg / "core"
        if core.is_dir():
            files += list(core.glob("*.py"))
    for extra in extras:
        p = pkg / extra
        if p.is_file():
            files.append(p)
    return sorted(files)


def override_files(overrides: dict | None, key: str,
                   default: Callable[[], Iterable[pathlib.Path]]
                   ) -> list[pathlib.Path]:
    """Normalizes a file-list override: absent -> ``default()``, a bare
    str/Path (the CLI's ``--override KEY=PATH`` form) -> one-element
    list. The one copy of the idiom every file-scoped pass needs."""
    value = (overrides or {}).get(key)
    if value is None:
        value = default()
    elif isinstance(value, (str, pathlib.Path)):
        value = [value]
    return [pathlib.Path(p) for p in value]


def pass_families() -> dict[str, Callable[..., list[Finding]]]:
    """Registry of the pass families the CLI runs (import deferred so a
    syntax error in one pass does not take down the others' rule docs)."""
    from .binding_contract import run_binding_contract
    from .conc_lint import run_conc_lint
    from .donation_lint import run_donation_lint
    from .future_lint import run_future_lint
    from .header_layout import run_header_layout
    from .hotpath_lint import run_hotpath_lint
    from .jax_lint import run_jax_lint
    from .lock_lint import run_lock_lint
    from .opbudget import run_opbudget
    from .resilience_lint import run_resilience_lint
    from .sanitizers import run_sanitizers
    from .shard_budget import run_shard_budget
    from .shard_lint import run_shard_lint
    from .spmd_lint import run_spmd_lint
    from .sync_lint import run_sync_lint
    from .telemetry_lint import run_telemetry_lint
    from .thread_lint import run_thread_lint
    from .transfer_budget import run_transfer_budget
    return {
        "binding": run_binding_contract,
        "header": run_header_layout,
        "jax": run_jax_lint,
        "sanitizers": run_sanitizers,
        "telemetry": run_telemetry_lint,
        "resilience": run_resilience_lint,
        "conc": run_conc_lint,
        "spmd": run_spmd_lint,
        "hotpath": run_hotpath_lint,
        "sync": run_sync_lint,
        "don": run_donation_lint,
        "lock": run_lock_lint,
        "future": run_future_lint,
        "thread": run_thread_lint,
        "opbudget": run_opbudget,
        "trb": run_transfer_budget,
        "shard": run_shard_lint,
        "sbd": run_shard_budget,
    }


#: Repo-relative path prefixes each family draws findings from — the
#: ``--since REV`` changed-files mode skips families whose scope holds
#: no changed file (a family that runs keeps ALL its findings: the
#: cross-file contract passes can flag file A because file B changed).
FAMILY_SCOPES: dict[str, tuple[str, ...]] = {
    "binding": ("mpi_blockchain_tpu/core",),
    "header": ("mpi_blockchain_tpu/core", "mpi_blockchain_tpu/ops",
               "tests/test_header_layout.py"),
    "jax": ("mpi_blockchain_tpu/ops", "mpi_blockchain_tpu/models",
            "mpi_blockchain_tpu/parallel"),
    "sanitizers": ("mpi_blockchain_tpu/core",),
    "telemetry": ("mpi_blockchain_tpu", "experiments"),
    "resilience": ("mpi_blockchain_tpu",),
    "conc": ("mpi_blockchain_tpu", "experiments"),
    "spmd": ("mpi_blockchain_tpu/parallel", "experiments",
             "mpi_blockchain_tpu/resilience/elastic.py"),
    "hotpath": ("mpi_blockchain_tpu",),
    "sync": ("mpi_blockchain_tpu/models", "mpi_blockchain_tpu/backend",
             "mpi_blockchain_tpu/parallel", "mpi_blockchain_tpu/core",
             "mpi_blockchain_tpu/utils", "mpi_blockchain_tpu/config.py",
             "mpi_blockchain_tpu/resilience/dispatch.py",
             "mpi_blockchain_tpu/resilience/elastic.py"),
    "don": ("mpi_blockchain_tpu/models", "mpi_blockchain_tpu/backend",
            "mpi_blockchain_tpu/parallel",
            "mpi_blockchain_tpu/resilience/dispatch.py",
            "mpi_blockchain_tpu/resilience/elastic.py"),
    "lock": ("mpi_blockchain_tpu", "experiments"),
    "future": ("mpi_blockchain_tpu", "experiments"),
    "thread": ("mpi_blockchain_tpu", "experiments", "WAITBUDGET.json"),
    "opbudget": ("mpi_blockchain_tpu/ops", "OPBUDGET.json",
                 "experiments/roofline.py",
                 "mpi_blockchain_tpu/analysis/opbudget.py"),
    "trb": ("mpi_blockchain_tpu/models", "mpi_blockchain_tpu/backend",
            "mpi_blockchain_tpu/parallel",
            "mpi_blockchain_tpu/resilience/dispatch.py",
            "TRANSFERBUDGET.json"),
    "shard": ("mpi_blockchain_tpu/parallel", "mpi_blockchain_tpu/backend",
              "mpi_blockchain_tpu/models", "experiments"),
    "sbd": ("mpi_blockchain_tpu/parallel", "mpi_blockchain_tpu/backend",
            "mpi_blockchain_tpu/models", "SHARDBUDGET.json"),
}

#: Rule-id prefix -> owning family (suppression audit attribution).
RULE_FAMILIES = {"BIND": "binding", "HDR": "header", "JAX": "jax",
                 "SAN": "sanitizers", "TEL": "telemetry",
                 "RES": "resilience", "CONC": "conc", "SPMD": "spmd",
                 "HOT": "hotpath", "SYNC": "sync", "DON": "don",
                 "LCK": "lock", "FUT": "future", "THR": "thread",
                 "TBW": "thread", "OPB": "opbudget", "TRB": "trb",
                 "SHD": "shard", "SBD": "sbd"}


#: A change under the analysis engine itself (a pass module, the
#: suppression machinery, the CLI) can alter ANY family's behavior —
#: --since runs everything rather than guessing which rules moved.
_ENGINE_PREFIX = "mpi_blockchain_tpu/analysis"


def families_for_changed(changed: Iterable[str]) -> list[str]:
    """Families whose scope intersects a changed-file set (repo-relative
    posix paths), in registry order. Any change under the analysis
    engine selects every family."""
    changed = [c.replace("\\", "/") for c in changed]
    if any(c == _ENGINE_PREFIX
           or c.startswith(_ENGINE_PREFIX + "/") for c in changed):
        return list(FAMILY_SCOPES)
    selected: list[str] = []
    for family, prefixes in FAMILY_SCOPES.items():
        if any(c == p or c.startswith(p.rstrip("/") + "/")
               for c in changed for p in prefixes):
            selected.append(family)
    return selected


def run_all(root: pathlib.Path | None = None,
            passes: Iterable[str] | None = None,
            overrides: dict[str, pathlib.Path] | None = None,
            notes: list[str] | None = None,
            *,
            apply_suppress: bool = True,
            jobs: int = 1,
            timings: dict[str, float] | None = None) -> list[Finding]:
    """Runs the selected pass families and returns suppression-filtered
    findings, sorted by (file, line, rule) — registration order never
    leaks into output order. ``overrides`` maps checker file keys (e.g.
    ``capi``, ``chain_hpp``) to alternate paths — the drift-fixture test
    seam. ``notes`` collects non-finding diagnostics (e.g. skipped
    tools). ``jobs`` > 1 runs the families on a thread pool (each pass
    only reads files and builds its own ASTs, so they parallelize
    freely); results are merged in registry order either way.
    ``timings`` (if given) receives per-family wall milliseconds.
    ``apply_suppress=False`` returns the RAW findings — the
    suppression-audit path."""
    import time

    root = root if root is not None else default_root()
    registry = pass_families()
    selected = list(passes) if passes is not None else list(registry)
    unknown = [p for p in selected if p not in registry]
    if unknown:
        raise ValueError(f"unknown pass families {unknown}; "
                         f"have {sorted(registry)}")

    def run_one(name: str) -> list[Finding]:
        t0 = time.perf_counter()
        result = registry[name](root, overrides=overrides or {},
                                notes=notes)
        if timings is not None:
            timings[name] = round((time.perf_counter() - t0) * 1e3, 3)
        return result

    findings: list[Finding] = []
    if jobs > 1 and len(selected) > 1:
        import concurrent.futures
        with concurrent.futures.ThreadPoolExecutor(
                min(jobs, len(selected))) as pool:
            futures = {name: pool.submit(run_one, name)
                       for name in selected}
        for name in selected:           # registry order, not finish order
            # Finite CPU-bound AST walks on a local pool: a hang here is
            # a chainlint bug, and make-check's outer timeout owns it.
            findings.extend(futures[name].result())  # chainlint: disable=FUT002
    else:
        for name in selected:
            findings.extend(run_one(name))
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.message))
    if apply_suppress:
        return apply_suppressions(findings, root)
    return findings


# ---- stale-suppression audit ----------------------------------------------

_AUDIT_SUFFIXES = (".py", ".cpp", ".hpp", ".h", ".cc")


def _audit_files(root: pathlib.Path) -> list[pathlib.Path]:
    # tests/ is deliberately NOT scanned: its fixture literals embed
    # `chainlint: disable=` strings that are test data, not suppressions.
    files: list[pathlib.Path] = []
    for base in (root / "mpi_blockchain_tpu", root / "experiments"):
        if base.is_dir():
            files += [p for p in base.rglob("*")
                      if p.suffix in _AUDIT_SUFFIXES
                      and "__pycache__" not in p.parts]
    return sorted(files)


def audit_suppressions(root: pathlib.Path | None = None,
                       passes: Iterable[str] | None = None,
                       overrides: dict | None = None,
                       notes: list[str] | None = None,
                       jobs: int = 1) -> list[str]:
    """Warnings for every ``chainlint: disable=`` comment whose rule no
    longer fires on that line (and every ``disable-file=`` whose rule
    fires nowhere in the file). Stale suppressions rot silently — the
    rule they silenced could return unnoticed. Only rules whose owning
    family actually RAN are audited, so a ``--passes`` subset never
    reports false staleness. Warning-only: warnings never fail a gate."""
    root = root if root is not None else default_root()
    registry = pass_families()
    selected = list(passes) if passes is not None else list(registry)
    raw = run_all(root=root, passes=selected, overrides=overrides,
                  notes=notes, apply_suppress=False, jobs=jobs)
    return audit_from_raw(root, raw, selected)


def audit_from_raw(root: pathlib.Path, raw: Iterable[Finding],
                   ran_families: Iterable[str]) -> list[str]:
    """The audit computed from an existing RAW (unsuppressed) findings
    set — the seam that lets the CLI's gating run serve the staleness
    report without analyzing everything a second time."""
    fired_line = {(f.file, f.line, f.rule) for f in raw}
    fired_file = {(f.file, f.rule) for f in raw}
    ran = set(ran_families)

    def audited(rule: str) -> bool:
        prefix = rule.rstrip("0123456789")
        return RULE_FAMILIES.get(prefix) in ran

    warnings: list[str] = []
    for path in _audit_files(root):
        rel = rel_path(path, root)
        try:
            lines = path.read_text(errors="replace").splitlines()
        except OSError:
            continue
        for i, line in enumerate(lines, start=1):
            m = _SUPPRESS_FILE_RE.search(line)
            if m and i <= 10:
                for rule in _suppressed_rules(m):
                    if rule != "all" and audited(rule) and \
                            (rel, rule) not in fired_file:
                        warnings.append(
                            f"{rel}:{i}: stale file-level suppression — "
                            f"{rule} fires nowhere in this file")
                continue
            m = _SUPPRESS_RE.search(line)
            if m:
                for rule in _suppressed_rules(m):
                    if rule != "all" and audited(rule) and \
                            (rel, i, rule) not in fired_line:
                        warnings.append(
                            f"{rel}:{i}: stale suppression — {rule} no "
                            f"longer fires on this line")
    return warnings
