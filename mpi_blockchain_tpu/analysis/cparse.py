"""Minimal C/C++ source parsing for the chainlint passes.

Not a compiler: the core sources are house-style (clang-format, no macros in
signatures, no function pointers in the C ABI), so line-preserving comment
stripping + regexes over declarations are reliable here. Everything returns
1-based line numbers against the ORIGINAL file so findings are clickable.
"""
from __future__ import annotations

import dataclasses
import pathlib
import re


def strip_comments(text: str) -> str:
    """Removes // and /* */ comments, preserving line structure (every
    newline survives, so offsets->line numbers stay valid)."""
    def _block(m: re.Match) -> str:
        return "\n" * m.group(0).count("\n")

    text = re.sub(r"/\*.*?\*/", _block, text, flags=re.S)
    text = re.sub(r"//[^\n]*", "", text)
    return text


def line_of(text: str, offset: int) -> int:
    return text.count("\n", 0, offset) + 1


@dataclasses.dataclass(frozen=True)
class CParam:
    name: str
    ctype: str          # canonical: "uint32_t", "uint8_t*", "void*", ...


@dataclasses.dataclass(frozen=True)
class CFunc:
    name: str
    ret: str            # canonical type
    params: tuple[CParam, ...]
    line: int


_BASE_TYPES = ("uint8_t", "uint16_t", "uint32_t", "uint64_t",
               "int64_t", "int32_t", "size_t", "int", "char", "void")


def canon_ctype(decl: str) -> str:
    """'const uint8_t* data' -> 'uint8_t*'; 'uint8_t out[32]' -> 'uint8_t*';
    'uint64_t len' -> 'uint64_t'. Unknown shapes come back as-is (they then
    fail the compatibility table, which is the safe direction)."""
    decl = decl.strip()
    is_ptr = "*" in decl or re.search(r"\[\s*\d*\s*\]", decl) is not None
    for base in _BASE_TYPES:
        if re.search(rf"\b{base}\b", decl):
            return f"{base}*" if is_ptr else base
    return decl


_FUNC_RE = re.compile(
    r"(?m)^(?P<ret>[A-Za-z_][\w ]*?\s*\*?)\s*"
    r"(?P<name>cc_\w+)\s*\((?P<params>[^)]*)\)\s*\{", re.S)


def parse_extern_c_funcs(path: pathlib.Path) -> dict[str, CFunc]:
    """All cc_* function definitions in a capi-style translation unit."""
    raw = path.read_text(errors="replace")
    text = strip_comments(raw)
    funcs: dict[str, CFunc] = {}
    for m in _FUNC_RE.finditer(text):
        params: list[CParam] = []
        plist = m.group("params").strip()
        if plist and plist != "void":
            for p in plist.split(","):
                p = p.strip()
                name_m = re.search(r"([A-Za-z_]\w*)\s*(?:\[\s*\d*\s*\])?$", p)
                params.append(CParam(
                    name=name_m.group(1) if name_m else p,
                    ctype=canon_ctype(p)))
        funcs[m.group("name")] = CFunc(
            name=m.group("name"), ret=canon_ctype(m.group("ret")),
            params=tuple(params), line=line_of(text, m.start()))
    return funcs


@dataclasses.dataclass(frozen=True)
class StructField:
    name: str
    width: int
    line: int


_FIELD_RE = re.compile(
    r"(?m)^\s*(?P<type>uint8_t|uint16_t|uint32_t|uint64_t)\s+"
    r"(?P<name>\w+)\s*(?:\[(?P<n>\d+)\])?\s*(?:=\s*[^;]*)?;")
_WIDTHS = {"uint8_t": 1, "uint16_t": 2, "uint32_t": 4, "uint64_t": 8}


def parse_struct_fields(path: pathlib.Path,
                        struct: str) -> list[StructField]:
    """Data members of ``struct <name> { ... }`` in declaration order.

    Method declarations inside the struct contain '(' and never match the
    field regex; nested braces (none in chain.hpp's headers) are out of
    scope for this parser.
    """
    text = strip_comments(path.read_text(errors="replace"))
    m = re.search(rf"struct\s+{struct}\s*\{{", text)
    if m is None:
        return []
    depth, i = 1, m.end()
    while i < len(text) and depth:
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
        i += 1
    body = text[m.end():i - 1]
    fields = []
    for fm in _FIELD_RE.finditer(body):
        width = _WIDTHS[fm.group("type")]
        if fm.group("n"):
            width *= int(fm.group("n"))
        fields.append(StructField(fm.group("name"), width,
                                  line_of(text, m.end() + fm.start())))
    return fields


def extract_function_body(path: pathlib.Path, signature_re: str) -> str:
    """Brace-matched body text of the first function whose definition
    matches ``signature_re`` (searched in comment-stripped text)."""
    text = strip_comments(path.read_text(errors="replace"))
    m = re.search(signature_re, text)
    if m is None:
        return ""
    start = text.find("{", m.end() - 1)
    if start < 0:
        return ""
    depth, i = 1, start + 1
    while i < len(text) and depth:
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
        i += 1
    return text[start + 1:i - 1]
