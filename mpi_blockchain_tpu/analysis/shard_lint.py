"""SHD rules — partition-spec & axis-context lint for the mesh sweep.

ROADMAP item 1's 8-chip bring-up is gated on exactly the hazard class
deadlint cannot see: bugs that are *silent on one device* and only
crash (or hang) on a real multi-chip mesh. All four rules here fire on
shapes that trace fine on CPU with a 1-device mesh:

  SHD001  shard_map ``in_specs``/``out_specs`` arity mismatch against
          the wrapped function's signature / return tuple — XLA accepts
          a wrong-length spec tuple only until the first multi-device
          run, and a spec that silently replicates a sharded operand
          makes every device sweep the SAME nonce slice (the
          silent-replication bug class: duplicated work, no error).
  SHD002  a collective (``psum``/``pmin``/``all_gather``/
          ``axis_index``/...) reachable from a call site with no
          enclosing shard_map/axis context — axis-name provenance is
          walked through the callgraph the way sync_lint walks device
          provenance: a function whose collectives ride its own
          ``axis_name`` parameter is fine (the caller decides), but a
          *literal* axis name (or a parameter default) with no
          shard_map above it is the "unbound axis name 'miners'" crash
          that only fires on a real mesh.
  SHD003  a rank-divergent value (``jax.process_index()``,
          ``mesh_rank()``, ``ElasticWorld.index()``-style world
          queries) flowing into a shape slot, a traced function's
          static argument, or the trip count of a loop that dispatches
          collectives/traced work — each rank then traces a DIFFERENT
          program and the collectives inside stop lining up: the
          multi-host hang deadlint (which sees locks and futures, not
          traces) cannot see.
  SHD004  a raw ``jax.shard_map``/``jax.experimental.shard_map``
          import or attribute use outside the one sanctioned compat
          seam ``parallel.mesh._resolve_shard_map`` — the check_vma
          workaround must stay the single spelling, or a jax version
          bump forks behavior between call sites.

Provenance limits (documented, deliberate): SHD001 only checks literal
spec tuples against module-local defs (``(P(),) * n`` computed arities
are trusted — ``maybe_shard_over_miners`` derives them from the
signature precisely so nobody hand-miscounts); SHD002's
parameter-threading recognizes the ``axis_name`` parameter name (the
repo-wide spelling) and one level of ``functools.partial``; SHD003's
taint is per-function (no cross-function argument threading). The rule
set prefers silence over false positives on host-side builder code —
the same contract as jax_lint.

Scope: ``parallel/``, ``backend/``, ``models/`` (recursive) plus
``experiments/*.py`` (override key ``shard_files``).
"""
from __future__ import annotations

import ast
import pathlib

from . import Finding, override_files, package_scope, rel_path, \
    source_cached
from .callgraph import call_name, dotted
from .jax_lint import _collect_traced_functions

SANCTIONED_SEAM_FILE = "mpi_blockchain_tpu/parallel/mesh.py"
SANCTIONED_SEAM_FN = "_resolve_shard_map"

#: Collectives + axis queries whose axis argument binds a mesh axis ->
#: the positional slot that argument occupies (jax.lax signatures).
AXIS_SLOTS = {"psum": 1, "pmin": 1, "pmax": 1, "pmean": 1,
              "all_gather": 1, "all_to_all": 1, "ppermute": 1,
              "axis_index": 0, "axis_size": 0}
_LAX_PREFIXES = ("jax.lax", "lax")


def _default_files(root: pathlib.Path) -> list[pathlib.Path]:
    files = package_scope(root, ("parallel", "backend", "models"))
    exp = root / "experiments"
    if exp.is_dir():
        files += sorted(exp.glob("*.py"))
    return files


def _is_collective(node: ast.Call) -> bool:
    name = call_name(node)
    if name not in AXIS_SLOTS:
        return False
    if isinstance(node.func, ast.Name):
        return True
    d = dotted(node.func)
    return any(d == f"{p}.{name}" for p in _LAX_PREFIXES)


def _axis_expr(node: ast.Call) -> ast.expr | None:
    """The axis argument of a collective call, or None when absent."""
    slot = AXIS_SLOTS.get(call_name(node))
    if slot is not None and len(node.args) > slot:
        return node.args[slot]
    for kw in node.keywords:
        if kw.arg in ("axis_name", "axis"):
            return kw.value
    return None


# ---- function records ------------------------------------------------------


class _Fn:
    """One top-level function (nested defs folded in): its axis_name
    parameter (if any), its default, and where its collectives bind."""

    def __init__(self, rel: str, node: ast.FunctionDef):
        self.rel = rel
        self.node = node
        self.name = node.name
        # axis_name parameter: position + default, of the OUTERMOST def
        # that declares one (the nested-closure case reads the outer
        # parameter, which is what run()/body() in mesh.py do).
        self.axis_index: int | None = None
        self.axis_default: ast.expr | None = None
        self.param_axis_names: set[str] = set()
        for fn in self._defs():
            args = fn.args
            names = [a.arg for a in args.posonlyargs + args.args
                     + args.kwonlyargs]
            if "axis_name" in names:
                self.param_axis_names.add("axis_name")
                if self.axis_index is None and fn is node:
                    pos = (args.posonlyargs + args.args)
                    for i, a in enumerate(pos):
                        if a.arg == "axis_name":
                            self.axis_index = i
                            n_def = len(args.defaults)
                            j = i - (len(pos) - n_def)
                            if 0 <= j < n_def:
                                self.axis_default = args.defaults[j]
                    if self.axis_index is None:
                        for a, d in zip(args.kwonlyargs, args.kw_defaults):
                            if a.arg == "axis_name":
                                self.axis_default = d
        # requirement state for the SHD002 fixpoint
        self.param_req = False           # collectives ride axis_name
        self.always_sites: list[tuple[int, str]] = []   # (line, detail)

    def _defs(self):
        for n in ast.walk(self.node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield n

    def calls(self):
        """(call, chain) pairs — ``chain`` is the tuple of NESTED def
        names lexically enclosing the call (used to exempt sites inside
        a nested def that is itself shard_map-provided, the per_device
        shape in make_mesh_sweep_fn)."""

        def walk(node, chain):
            for child in ast.iter_child_nodes(node):
                sub = chain
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    sub = chain + (child.name,)
                if isinstance(child, ast.Call):
                    yield child, chain
                yield from walk(child, sub)

        yield from walk(self.node, ())


def _top_level_functions(rel: str, tree: ast.Module) -> list[_Fn]:
    out = []
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            out.append(_Fn(rel, node))
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef):
                    out.append(_Fn(rel, sub))
    return out


# ---- SHD001: spec arity vs wrapped signature -------------------------------


def _partial_target(expr: ast.expr) -> tuple[str | None, int, set[str]]:
    """(callee name, bound positional count, bound keyword names) for a
    shard_map arg0: a bare Name or one functools.partial() level."""
    if isinstance(expr, ast.Name):
        return expr.id, 0, set()
    if isinstance(expr, ast.Call) and dotted(expr.func) in (
            "functools.partial", "partial") and expr.args and \
            isinstance(expr.args[0], ast.Name):
        bound_kw = {kw.arg for kw in expr.keywords if kw.arg}
        return expr.args[0].id, len(expr.args) - 1, bound_kw
    return None, 0, set()


def _own_returns(fn: ast.FunctionDef) -> list[ast.Return]:
    """Return statements lexically in ``fn`` itself (nested defs cut)."""
    out: list[ast.Return] = []

    def walk(nodes):
        for n in nodes:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(n, ast.Return):
                out.append(n)
            walk(ast.iter_child_nodes(n))

    walk(ast.iter_child_nodes(fn))
    return out


def _fn_return_arity(fn: ast.FunctionDef,
                     local_defs: dict[str, ast.FunctionDef],
                     hop: int = 0) -> int | None:
    """Consistent return-tuple arity of ``fn``'s own returns (nested
    defs cut), following ONE hop of a module-local tail call — the
    per_device -> winner_select -> 2-tuple shape in parallel/mesh.py.
    None when any return's arity is not statically known."""
    arities: set[int] = set()
    for ret in _own_returns(fn):
        if ret.value is None:
            return None
        v = ret.value
        if isinstance(v, ast.Tuple):
            arities.add(len(v.elts))
        elif isinstance(v, ast.Call) and hop < 1 and \
                isinstance(v.func, ast.Name) and v.func.id in local_defs:
            inner = _fn_return_arity(local_defs[v.func.id], local_defs,
                                     hop + 1)
            if inner is None:
                return None
            arities.add(inner)
        else:
            return None
    return arities.pop() if len(arities) == 1 else None


def _shd001(rel: str, tree: ast.Module) -> list[Finding]:
    local_defs: dict[str, ast.FunctionDef] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.FunctionDef):
            local_defs.setdefault(n.name, n)
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and call_name(node) == "shard_map" and node.args):
            continue
        target, bound_pos, bound_kw = _partial_target(node.args[0])
        fn = local_defs.get(target) if target else None
        if fn is None:
            continue
        args = fn.args
        params = [a.arg for a in args.posonlyargs + args.args
                  + args.kwonlyargs]
        unbound = [p for i, p in enumerate(params)
                   if i >= bound_pos and p not in bound_kw]
        specs = {kw.arg: kw.value for kw in node.keywords
                 if kw.arg in ("in_specs", "out_specs")}
        in_specs = specs.get("in_specs")
        if isinstance(in_specs, ast.Tuple) and \
                len(in_specs.elts) != len(unbound):
            findings.append(Finding(
                rel, node.lineno, "SHD001",
                f"shard_map in_specs has {len(in_specs.elts)} spec(s) "
                f"but '{target}' takes {len(unbound)} unbound "
                f"parameter(s) {unbound} — a mis-counted spec tuple "
                f"silently replicates (or drops) an operand and every "
                f"device sweeps the same slice; derive the arity from "
                f"the signature like parallel.mesh."
                f"maybe_shard_over_miners does"))
        out_specs = specs.get("out_specs")
        if isinstance(out_specs, ast.Tuple):
            ret = _fn_return_arity(fn, local_defs)
            if ret is not None and ret != len(out_specs.elts):
                findings.append(Finding(
                    rel, node.lineno, "SHD001",
                    f"shard_map out_specs has {len(out_specs.elts)} "
                    f"spec(s) but '{target}' returns a {ret}-tuple — "
                    f"the mismatched output spec misplaces the "
                    f"collective epilogue's replication on a real mesh"))
    return findings


# ---- SHD002: axis-context provenance ---------------------------------------


def _literal_axis(expr: ast.expr | None) -> str | None:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, (ast.Tuple, ast.List)) and expr.elts and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in expr.elts):
        return str(expr.elts[0].value)
    return None


def _context_provided(trees: dict[str, ast.Module]) -> set[tuple]:
    """(rel, fn name) wrapped by a shard_map in its module — the axis
    context that makes literal-axis collectives legal."""
    provided: set[tuple] = set()
    for rel, tree in trees.items():
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    call_name(node) == "shard_map" and node.args:
                target, _, _ = _partial_target(node.args[0])
                if target:
                    provided.add((rel, target))
    return provided


def _shd002(files: list[tuple[str, ast.Module]]) -> list[Finding]:
    trees = dict(files)
    fns: list[_Fn] = []
    for rel, tree in files:
        fns.extend(_top_level_functions(rel, tree))
    by_name: dict[str, list[_Fn]] = {}
    for f in fns:
        by_name.setdefault(f.name, []).append(f)
    provided = _context_provided(trees)

    def site(f: _Fn, chain: tuple, line: int, detail: str) -> None:
        # A site inside a nested def that is itself shard_map-provided
        # (per_device in make_mesh_sweep_fn) has its context.
        if any((f.rel, name) in provided for name in chain):
            return
        if (line, detail) not in f.always_sites:
            f.always_sites.append((line, detail))

    # Direct collective sites classify each function once.
    for f in fns:
        for call, chain in f.calls():
            if not _is_collective(call):
                continue
            axis = _axis_expr(call)
            lit = _literal_axis(axis)
            if lit is not None:
                site(f, chain, call.lineno,
                     f"'{call_name(call)}' binds axis '{lit}'")
            elif isinstance(axis, ast.Name) and \
                    axis.id in f.param_axis_names:
                f.param_req = True
            # unknown axis expressions stay silent (provenance limit)

    # Fixpoint: thread the axis_name parameter through named calls.
    changed = True
    while changed:
        changed = False
        for f in fns:
            for call, chain in f.calls():
                name = call_name(call)
                callees = by_name.get(name, ())
                for g in callees:
                    if not g.param_req:
                        continue
                    axis = None
                    if g.axis_index is not None and \
                            len(call.args) > g.axis_index:
                        axis = call.args[g.axis_index]
                    else:
                        for kw in call.keywords:
                            if kw.arg == "axis_name":
                                axis = kw.value
                    if axis is None:
                        axis = g.axis_default
                    lit = _literal_axis(axis)
                    if lit is not None:
                        before = len(f.always_sites)
                        site(f, chain, call.lineno,
                             f"'{g.name}' resolves its collectives to "
                             f"axis '{lit}' here")
                        changed |= len(f.always_sites) != before
                    elif isinstance(axis, ast.Name) and \
                            axis.id in f.param_axis_names and \
                            not f.param_req:
                        f.param_req = True
                        changed = True
                    break    # one resolution per call name is enough

    # Close the provided set over exclusively-inside-context callers:
    # a helper whose every resolvable call site sits in a provided
    # function inherits the context.
    callers: dict[str, set[tuple]] = {}
    for f in fns:
        for call, chain in f.calls():
            name = call_name(call)
            if name in by_name:
                owner = chain[-1] if chain else f.name
                callers.setdefault(name, set()).add((f.rel, owner))
    closed = set(provided)
    grew = True
    while grew:
        grew = False
        for f in fns:
            key = (f.rel, f.name)
            if key in closed:
                continue
            sites = callers.get(f.name)
            if sites and all(s in closed for s in sites):
                closed.add(key)
                grew = True

    findings: list[Finding] = []
    for f in fns:
        if (f.rel, f.name) in closed:
            continue
        for line, detail in f.always_sites:
            findings.append(Finding(
                f.rel, line, "SHD002",
                f"collective with no enclosing shard_map/axis context: "
                f"{detail}, but '{f.name}' is never wrapped by (or "
                f"exclusively called from) a shard_map over that axis "
                f"— this traces on one device and dies with an unbound "
                f"axis name on a real mesh; thread axis_name through "
                f"like parallel.mesh.make_round_search, or wrap the "
                f"caller in the mesh context"))
    # Module-level collective calls have no context by construction.
    for rel, tree in files:
        in_fn: set[int] = set()
        for n in ast.walk(tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(n):
                    in_fn.add(id(sub))
        for n in ast.walk(tree):
            if isinstance(n, ast.Call) and id(n) not in in_fn and \
                    _is_collective(n):
                lit = _literal_axis(_axis_expr(n))
                if lit is not None:
                    findings.append(Finding(
                        rel, n.lineno, "SHD002",
                        f"module-level collective "
                        f"'{call_name(n)}' binds axis '{lit}' with no "
                        f"shard_map context — unbound axis name on any "
                        f"real mesh"))
    return findings


# ---- SHD003: rank-divergent values into trace-shaping slots ----------------

_RANK_CALLS = {"process_index", "mesh_rank", "process_id"}
_WORLD_TOKENS = ("world", "elastic")
_SHAPE_CTORS = {"zeros", "ones", "full", "empty", "arange",
                "broadcast_to"}
_ARRAY_NS = ("jnp", "jax.numpy", "np", "numpy")


def _is_rank_producer(call: ast.Call) -> str | None:
    name = call_name(call)
    if name in _RANK_CALLS:
        return dotted(call.func) or name
    if name == "index" and isinstance(call.func, ast.Attribute):
        recv = dotted(call.func.value).lower()
        if any(tok in recv for tok in _WORLD_TOKENS):
            return dotted(call.func)
    return None


def _tainted_names(fn: ast.AST) -> set[str]:
    """Names assigned (transitively) from a rank-divergent producer,
    per-function — a deliberate provenance limit (no cross-function
    argument threading)."""
    tainted: set[str] = set()
    assigns: list[tuple[list[str], ast.expr]] = []
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign):
            names = [t.id for t in n.targets if isinstance(t, ast.Name)]
            for t in n.targets:
                if isinstance(t, ast.Tuple):
                    names += [e.id for e in t.elts
                              if isinstance(e, ast.Name)]
            if names:
                assigns.append((names, n.value))
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)) and \
                isinstance(n.target, ast.Name) and n.value is not None:
            assigns.append(([n.target.id], n.value))

    def dirty(expr: ast.expr) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call) and _is_rank_producer(sub):
                return True
            if isinstance(sub, ast.Name) and sub.id in tainted:
                return True
        return False

    changed = True
    while changed:
        changed = False
        for names, value in assigns:
            if dirty(value) and not set(names) <= tainted:
                tainted |= set(names)
                changed = True
    return tainted


def _shd003(rel: str, tree: ast.Module) -> list[Finding]:
    traced = {tf.node.name: tf for tf in _collect_traced_functions(tree)}
    findings: list[Finding] = []
    in_fn: set[int] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.FunctionDef):
            for sub in ast.iter_child_nodes(n):
                for inner in ast.walk(sub):
                    in_fn.add(id(inner))
    scopes: list[ast.AST] = [tree]
    scopes += [n for n in ast.walk(tree)
               if isinstance(n, ast.FunctionDef)]
    seen: set[tuple[int, str]] = set()

    def flag(line: int, msg: str) -> None:
        if (line, msg) not in seen:
            seen.add((line, msg))
            findings.append(Finding(rel, line, "SHD003", msg))

    for scope in scopes:
        tainted = _tainted_names(scope)

        def dirty(expr: ast.expr | None) -> str | None:
            if expr is None:
                return None
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Call):
                    p = _is_rank_producer(sub)
                    if p:
                        return p
                if isinstance(sub, ast.Name) and sub.id in tainted:
                    return sub.id
            return None

        module_scope = isinstance(scope, ast.Module)
        for node in ast.walk(scope):
            if module_scope and id(node) in in_fn:
                continue    # function bodies get their own scope pass
            if isinstance(node, ast.Call):
                name = call_name(node)
                d = dotted(node.func)
                ns = d.rsplit(".", 1)[0] if "." in d else ""
                if name in _SHAPE_CTORS and ns in _ARRAY_NS:
                    cands = list(node.args[:1]) + [
                        kw.value for kw in node.keywords
                        if kw.arg == "shape"]
                    for c in cands:
                        src = dirty(c)
                        if src:
                            flag(node.lineno,
                                 f"rank-divergent value '{src}' flows "
                                 f"into the shape of '{d or name}' — "
                                 f"each rank traces a different-shaped "
                                 f"program and the mesh collectives "
                                 f"stop lining up (multi-host hang)")
                elif name == "reshape" and \
                        isinstance(node.func, ast.Attribute):
                    for c in node.args:
                        src = dirty(c)
                        if src:
                            flag(node.lineno,
                                 f"rank-divergent value '{src}' flows "
                                 f"into '.reshape()' — divergent "
                                 f"shapes diverge the traced program "
                                 f"across ranks (multi-host hang)")
                elif name in traced:
                    tf = traced[name]
                    args = tf.node.args
                    params = [a.arg for a in args.posonlyargs
                              + args.args]
                    for s in tf.static:
                        expr = None
                        if s in params and \
                                params.index(s) < len(node.args):
                            expr = node.args[params.index(s)]
                        for kw in node.keywords:
                            if kw.arg == s:
                                expr = kw.value
                        src = dirty(expr)
                        if src:
                            flag(node.lineno,
                                 f"rank-divergent value '{src}' is "
                                 f"passed as static argument '{s}' of "
                                 f"traced function '{name}' — every "
                                 f"rank compiles a different program "
                                 f"and the collectives inside desync "
                                 f"(the multi-host hang deadlint "
                                 f"cannot see)")
            elif isinstance(node, ast.For) and \
                    isinstance(node.iter, ast.Call) and \
                    call_name(node.iter) == "range":
                src = None
                for a in node.iter.args:
                    src = src or dirty(a)
                if not src:
                    continue
                dispatches = any(
                    isinstance(sub, ast.Call)
                    and (_is_collective(sub)
                         or call_name(sub) in traced)
                    for sub in ast.walk(node))
                if dispatches:
                    flag(node.lineno,
                         f"rank-divergent value '{src}' sets the trip "
                         f"count of a loop that dispatches "
                         f"collective/traced work — ranks run "
                         f"different numbers of collective phases and "
                         f"the mesh hangs at the first missing "
                         f"rendezvous")
    return findings


# ---- SHD004: the single shard_map spelling ---------------------------------


def _shd004(rel: str, tree: ast.Module) -> list[Finding]:
    posix = rel.replace("\\", "/")
    sanctioned = posix == SANCTIONED_SEAM_FILE
    seam_nodes: set[int] = set()
    if sanctioned:
        for n in ast.walk(tree):
            if isinstance(n, ast.FunctionDef) and \
                    n.name == SANCTIONED_SEAM_FN:
                for sub in ast.walk(n):
                    seam_nodes.add(id(sub))
    findings: list[Finding] = []

    def flag(node: ast.AST, what: str) -> None:
        if id(node) in seam_nodes:
            return
        findings.append(Finding(
            rel, node.lineno, "SHD004",
            f"raw shard_map {what} outside the sanctioned compat seam "
            f"parallel.mesh.{SANCTIONED_SEAM_FN} — the check_vma "
            f"workaround must stay the single spelling; import "
            f"``shard_map`` from mpi_blockchain_tpu.parallel.mesh "
            f"instead"))

    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if "shard_map" in mod or (
                    mod in ("jax", "jax.experimental")
                    and any(a.name == "shard_map" for a in node.names)):
                flag(node, f"import (`from {mod} import ...`)")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if "shard_map" in a.name:
                    flag(node, f"import (`import {a.name}`)")
        elif isinstance(node, ast.Attribute) and \
                node.attr == "shard_map":
            d = dotted(node)
            if d in ("jax.shard_map", "jax.experimental.shard_map") or \
                    d.endswith(".experimental.shard_map"):
                flag(node, f"attribute use (`{d}`)")
    return findings


# ---- the pass --------------------------------------------------------------


def run_shard_lint(root: pathlib.Path, overrides=None,
                   notes=None) -> list[Finding]:
    overrides = overrides or {}
    files = override_files(overrides, "shard_files",
                           lambda: _default_files(root))
    findings: list[Finding] = []
    parsed: list[tuple[str, ast.Module]] = []
    for path in files:
        path = pathlib.Path(path)
        rel = rel_path(path, root)
        try:
            _, tree, err = source_cached(path)
        except OSError:
            continue
        if tree is None:
            findings.append(Finding(rel, err[0], "SHD000",
                                    f"syntax error: {err[1]}"))
            continue
        parsed.append((rel, tree))
        findings.extend(_shd001(rel, tree))
        findings.extend(_shd003(rel, tree))
        findings.extend(_shd004(rel, tree))
    findings.extend(_shd002(parsed))
    return findings
