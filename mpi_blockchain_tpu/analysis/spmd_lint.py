"""SPMD rules — collective-consistency lint over the mesh code paths.

The multi-chip scale-out path (ROADMAP item 1) is SPMD: every rank runs
the same program, and every collective (``psum``/``pmin`` winner-select,
``jax.distributed.initialize``, mesh builds) is a *rendezvous* — a rank
that skips one leaves the other seven blocked in the ICI/DCN fabric
until a watchdog kills the job. That failure mode is invisible to unit
tests (1-process worlds never block) and miserable to debug live, which
is why VaultxGPU-class designs (PAPERS.md, arxiv 2606.14007) structure
consensus so accelerator ranks never diverge on collective sequences.
These rules catch the three lexical ways a future edit makes ranks
diverge:

  SPMD001  a collective/rendezvous call lexically guarded by a
           rank-identity conditional (``if process_index() == 0:``) —
           rank 0 enters the collective, every other rank never does:
           a mesh-wide hang, not an error.
  SPMD002  a literal mesh axis name, in a collective's axis argument or
           a mesh/shard_map axis tuple, that is not in the canonical
           set derived from ``parallel/mesh.py`` (currently
           ``{'miners'}``) — XLA treats unknown axis names as a new
           mesh dimension and the program either fails to trace or
           silently stops reducing across the real mesh.
  SPMD003  a collective/rendezvous reachable inside a ``try`` whose
           handler does not re-raise — a rank that catches-and-continues
           skips the collective that the other ranks are blocked in
           (a one-rank retry is a mesh-wide hang). Handlers that
           re-raise (cleanup idiom) are fine.
  SPMD004  a collective/rendezvous in an ELASTIC file (override key
           ``elastic_files``; default resilience/elastic.py) that does
           not go through the ``guarded_collective`` helper — elastic
           code is exactly the code that recovers from rank loss, so an
           unguarded rendezvous there reintroduces the mesh-wide hang
           the supervisor exists to prevent. A collective is guarded
           when it sits inside a DEFERRED (lambda-wrapped) argument of
           a ``guarded_collective(...)`` call, or inside a function
           whose EVERY module-local call site does (one lexical hop —
           the ``_rendezvous`` idiom); an eagerly-evaluated argument
           (``guarded_collective(self._rendezvous(n))``, no lambda)
           runs BEFORE the guard and is flagged. Elastic files are
           exempt from
           SPMD001-003: ``guarded_collective`` + watchdog recovery is
           their sanctioned alternative to the re-raise discipline.

"Collective" is detected directly (``lax.psum``/``pmin``/... ,
``jax.distributed.initialize``, the repo's ``init_distributed``) and by
module-local propagation: a function whose body (transitively, within
the module) calls a collective is itself a collective site at its call
sites. Cross-module propagation and collectives reached only through
values (a function passed to ``lax.while_loop``) are out of scope —
the call-graph builder's known limits (docs/static_analysis.md).

Scope: ``mpi_blockchain_tpu/parallel/`` and ``experiments/`` (override
key ``spmd_files``); the canonical axis set honors the ``mesh_py``
override shared with the JAX pass. SPMD002 DEFERS to JAX005 on files
the jax pass already covers (its ``jax_files`` scope — ``ops/``,
``models/``, ``parallel/``, honoring the same override): the two rules
check the identical literal-axis-name drift, and double-reporting one
edit as two findings buries real signal and forces paired
suppressions. On files only this pass sees (``experiments/``, override
fixtures) SPMD002 still fires, so every scoped file gets the axis
check exactly once.
"""
from __future__ import annotations

import ast
import pathlib

from . import Finding, override_files, rel_path
from .callgraph import call_name, dotted
from .jax_lint import AXIS_CALLS, _canonical_axes

#: Cross-rank reductions/permutations: skipping one on any rank hangs
#: the mesh.
COLLECTIVES = {"psum", "pmin", "pmax", "pmean", "all_gather",
               "all_to_all", "ppermute", "pshuffle", "all_reduce"}

#: World/mesh rendezvous: every rank must execute these, same order.
#: (``jax.distributed.initialize`` — dotted or bare from-import — is
#: handled separately in ``_is_collective_call``.)
RENDEZVOUS = {"init_distributed", "make_mesh", "Mesh",
              "make_miner_mesh", "make_global_miner_mesh"}

#: Names in a conditional test that mark it rank-divergent.
RANK_TESTS = {"process_index", "process_id", "rank", "node_id",
              "local_rank", "mesh_rank", "is_coordinator"}


def _is_collective_call(node: ast.Call) -> str | None:
    """The op label when this call is directly a collective/rendezvous."""
    name = call_name(node)
    if name in COLLECTIVES:
        return name
    if name == "initialize":
        # Dotted jax.distributed.initialize, or the bare from-import
        # form (`from jax.distributed import initialize`). Other
        # attribute calls named initialize (obj.initialize()) are not
        # world rendezvous.
        d = dotted(node.func)
        if d == "initialize" or "distributed" in d.split("."):
            return d or name
        return None
    if name in RENDEZVOUS:
        return name
    return None


def _collective_funcs(tree: ast.Module) -> set[str]:
    """Names of module-local functions that (transitively, module-local)
    contain a collective — their call sites are collective sites too."""
    local: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local.setdefault(node.name, node)
    marked: set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, fn in local.items():
            if name in marked:
                continue
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                if _is_collective_call(sub) is not None or \
                        call_name(sub) in marked:
                    marked.add(name)
                    changed = True
                    break
    return marked


def _rank_names_in(test: ast.expr) -> set[str]:
    found: set[str] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in RANK_TESTS:
            found.add(node.id)
        elif isinstance(node, ast.Attribute) and node.attr in RANK_TESTS:
            found.add(node.attr)
    return found


def _handler_reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise)
               for stmt in handler.body for n in ast.walk(stmt))


class _ContextWalker(ast.NodeVisitor):
    """Tracks rank-conditional and swallowing-try lexical context."""

    def __init__(self, rel: str, propagated: set[str],
                 findings: list[Finding]):
        self.rel = rel
        self.propagated = propagated
        self.findings = findings
        self._rank_if: list[tuple[int, set[str]]] = []
        self._swallow_try: list[int] = []

    # -- context ----------------------------------------------------------

    def visit_If(self, node: ast.If) -> None:
        # The test expression runs on every rank that reaches the `if`
        # (only the ENCLOSING contexts apply to it) — visit it, or a
        # rendezvous used AS the condition escapes both rules.
        self.visit(node.test)
        ranky = _rank_names_in(node.test)
        if ranky:
            self._rank_if.append((node.lineno, ranky))
        for child in node.body:
            self.visit(child)
        if ranky:
            self._rank_if.pop()
        # The else/elif branch of a rank test is equally divergent.
        if ranky:
            self._rank_if.append((node.lineno, ranky))
        for child in node.orelse:
            self.visit(child)
        if ranky:
            self._rank_if.pop()

    def visit_Try(self, node: ast.Try) -> None:
        swallowing = any(not _handler_reraises(h) for h in node.handlers)
        if swallowing:
            self._swallow_try.append(node.lineno)
        for child in node.body:
            self.visit(child)
        if swallowing:
            self._swallow_try.pop()
        # A collective inside a NON-reraising handler is the literal
        # one-rank-retry pattern: only the rank that saw the exception
        # re-enters the rendezvous, its peers are not there.
        for handler in node.handlers:
            handler_swallows = not _handler_reraises(handler)
            if handler_swallows:
                self._swallow_try.append(node.lineno)
            for child in handler.body:
                self.visit(child)
            if handler_swallows:
                self._swallow_try.pop()
        for part in (node.orelse, node.finalbody):
            for child in part:
                self.visit(child)

    # -- collective sites --------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        op = _is_collective_call(node)
        if op is None and call_name(node) in self.propagated:
            op = f"{call_name(node)} (contains a collective)"
        if op is not None:
            if self._rank_if:
                line, ranky = self._rank_if[-1]
                self.findings.append(Finding(
                    self.rel, node.lineno, "SPMD001",
                    f"collective/rendezvous '{op}' guarded by the "
                    f"rank-identity conditional on line {line} "
                    f"({sorted(ranky)}) — only some ranks enter it, the "
                    f"rest of the mesh blocks forever; run collectives "
                    f"unconditionally on every rank and branch on the "
                    f"RESULT instead"))
            if self._swallow_try:
                self.findings.append(Finding(
                    self.rel, node.lineno, "SPMD003",
                    f"collective/rendezvous '{op}' inside the try on "
                    f"line {self._swallow_try[-1]} whose handler does "
                    f"not re-raise — a rank that swallows the failure "
                    f"skips the collective its peers are blocked in "
                    f"(one-rank retry = mesh-wide hang); re-raise, or "
                    f"move the recovery outside the collective sequence"))
        self.generic_visit(node)


def _axis_findings(rel: str, tree: ast.Module,
                   canonical: set[str]) -> list[Finding]:
    """SPMD002 over every literal axis string used by a collective or a
    mesh/shard_map axis declaration."""
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        candidates: list[ast.expr] = []
        if name in AXIS_CALLS:
            slot = AXIS_CALLS[name]
            if len(node.args) > slot:
                candidates.append(node.args[slot])
            candidates += [k.value for k in node.keywords
                           if k.arg in ("axis_name", "axis")]
        elif name in ("make_mesh", "Mesh"):
            candidates += list(node.args) + \
                [k.value for k in node.keywords]
        elif name == "shard_map":
            candidates += [k.value for k in node.keywords
                           if k.arg == "axis_names"]
        for c in candidates:
            elts = c.elts if isinstance(c, (ast.Tuple, ast.List)) else [c]
            for e in elts:
                if isinstance(e, ast.Constant) and \
                        isinstance(e.value, str) and \
                        e.value not in canonical:
                    findings.append(Finding(
                        rel, e.lineno, "SPMD002",
                        f"mesh axis name '{e.value}' in '{name}' is not "
                        f"in the canonical set {sorted(canonical)} "
                        f"declared by parallel/mesh.py — the collective "
                        f"would not reduce over the real "
                        f"('miners',) mesh"))
    return findings


#: The sanctioned guard helpers SPMD004 recognizes (resilience/elastic).
ELASTIC_GUARDS = {"guarded_collective"}


class _ElasticWalker(ast.NodeVisitor):
    """Collects every call site's guard status in an elastic file:
    whether it sits inside a DEFERRED (lambda-wrapped) argument of a
    ``guarded_collective(...)`` call. Deferral matters: in
    ``guarded_collective(self._rendezvous(n))`` the rendezvous runs
    eagerly in the caller's thread BEFORE the guard is even entered —
    lexically inside the argument, but unguarded at runtime."""

    def __init__(self):
        self._fn_stack: list[str] = []
        self._guard_depth = 0
        self._deferred_depth = 0
        #: (node, op label, innermost enclosing function, guarded)
        self.collectives: list[tuple[ast.Call, str, str | None, bool]] = []
        #: every call site: name -> [guarded?, ...]
        self.call_sites: dict[str, list[bool]] = {}

    def _guarded(self) -> bool:
        # Inside a guard argument AND behind at least one lambda since
        # entering it — only then does the code run on the guard's
        # watchdogged worker rather than eagerly at the call site.
        return self._guard_depth > 0 and self._deferred_depth > 0

    def visit_FunctionDef(self, node):
        self._fn_stack.append(node.name)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        deferred = self._guard_depth > 0
        if deferred:
            self._deferred_depth += 1
        self.generic_visit(node)
        if deferred:
            self._deferred_depth -= 1

    def visit_Call(self, node: ast.Call) -> None:
        name = call_name(node)
        self.call_sites.setdefault(name, []).append(self._guarded())
        op = _is_collective_call(node)
        if op is not None and not (set(self._fn_stack) & ELASTIC_GUARDS):
            self.collectives.append(
                (node, op, self._fn_stack[-1] if self._fn_stack else None,
                 self._guarded()))
        self.visit(node.func)
        if name in ELASTIC_GUARDS:
            self._guard_depth += 1
        for arg in node.args:
            self.visit(arg)
        for kw in node.keywords:
            self.visit(kw.value)
        if name in ELASTIC_GUARDS:
            self._guard_depth -= 1


def _elastic_findings(rel: str, tree: ast.Module) -> list[Finding]:
    """SPMD004: unguarded collectives in an elastic file. One lexical
    hop is recognized: a collective inside function F is guarded when
    EVERY module-local call site of F is itself inside a DEFERRED guard
    argument (the ``guarded_collective(lambda: self._rendezvous(n))``
    idiom — without the lambda the rendezvous runs eagerly before the
    guard and is flagged); deeper indirection is out of scope, like the
    call-graph builder's other known limits (docs/static_analysis.md)."""
    walker = _ElasticWalker()
    walker.visit(tree)
    findings: list[Finding] = []
    for node, op, enclosing, guarded in walker.collectives:
        if guarded:
            continue
        if enclosing is not None:
            sites = walker.call_sites.get(enclosing, [])
            if sites and all(sites):
                continue   # only ever reached through the guard
        findings.append(Finding(
            rel, node.lineno, "SPMD004",
            f"collective/rendezvous '{op}' in an elastic file does not "
            f"go through guarded_collective — elastic code is the "
            f"rank-loss recovery path, and an unguarded rendezvous "
            f"there can hang the survivors the supervisor exists to "
            f"save; wrap the dispatch in guarded_collective(lambda: "
            f"...) (one lexical hop is recognized)"))
    return findings


def _default_elastic_files(root: pathlib.Path) -> list[pathlib.Path]:
    path = root / "mpi_blockchain_tpu" / "resilience" / "elastic.py"
    return [path] if path.is_file() else []


def _scoped_files(root: pathlib.Path) -> list[pathlib.Path]:
    files: list[pathlib.Path] = []
    par = root / "mpi_blockchain_tpu" / "parallel"
    if par.is_dir():
        files += [p for p in par.rglob("*.py")
                  if "__pycache__" not in p.parts]
    exp = root / "experiments"
    if exp.is_dir():
        files += list(exp.glob("*.py"))
    return sorted(files)


def run_spmd_lint(root: pathlib.Path, overrides=None,
                  notes=None) -> list[Finding]:
    overrides = overrides or {}
    files = override_files(overrides, "spmd_files",
                           lambda: _scoped_files(root))
    mesh_py = overrides.get(
        "mesh_py", root / "mpi_blockchain_tpu" / "parallel" / "mesh.py")
    canonical = _canonical_axes(pathlib.Path(mesh_py))
    if not canonical and notes is not None:
        notes.append("spmd: no canonical mesh axes found; SPMD002 skipped")

    # SPMD002 defers to JAX005 on files the jax pass already covers —
    # same rule, one finding per drifted axis name (module docstring).
    from .jax_lint import LINT_DIRS
    pkg = root / "mpi_blockchain_tpu"
    jax_covered = {
        pathlib.Path(p).resolve()
        for p in override_files(overrides, "jax_files",
                                lambda: [p for d in LINT_DIRS
                                         for p in sorted(
                                             (pkg / d).glob("*.py"))])}

    findings: list[Finding] = []
    for path in files:
        path = pathlib.Path(path)
        rel = rel_path(path, root)
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            findings.append(Finding(rel, e.lineno or 1, "SPMD000",
                                    f"syntax error: {e.msg}"))
            continue
        except OSError:
            continue
        walker = _ContextWalker(rel, _collective_funcs(tree), findings)
        walker.visit(tree)
        if canonical and path.resolve() not in jax_covered:
            findings.extend(_axis_findings(rel, tree, canonical))
    # SPMD004 scope: the elastic files, which are deliberately EXEMPT
    # from SPMD001-003 (guarded_collective + watchdog recovery is their
    # sanctioned alternative to the re-raise discipline).
    for path in override_files(overrides, "elastic_files",
                               lambda: _default_elastic_files(root)):
        path = pathlib.Path(path)
        rel = rel_path(path, root)
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            findings.append(Finding(rel, e.lineno or 1, "SPMD000",
                                    f"syntax error: {e.msg}"))
            continue
        except OSError:
            continue
        findings.extend(_elastic_findings(rel, tree))
    return findings
