"""LCK rules — lock-order and hold-while-waiting discipline (deadlint).

The async double-buffered dispatch (ROADMAP item 1, landed PR 12) made
the host genuinely concurrent: a single-flight dispatch worker under
``ResilientBackend``'s RLock, ``guarded_collective`` worker pools behind
``_idle_lock``, the shard flusher and MetricsServer threads beside the
pipeline-profiler ring lock. CONC catches *unlocked* cross-thread
mutation; nothing caught the opposite failure class — code that locks
CORRECTLY in isolation and deadlocks in composition. The 8-chip
scale-out (ROADMAP item 2) multiplies every such hazard by the mesh: a
lock-order inversion between two ranks' helper threads is a silent
mesh hang, which is exactly the class ``guarded_collective`` exists to
kill dynamically — this pass kills it statically.

The pass builds a **lock-acquisition graph** per module: every
``with <lock>:`` scope (lock spelled per the shared CONC token rule —
``self._lock``, ``_idle_lock``, ``rlock``, ``mutex``, ``cond``) is an
acquisition of an identified lock (``self.X`` keys to the enclosing
class, a module-level name keys to the module, anything else is
function-local), and the module-local call-graph closure propagates
which locks / blocking waits / callback invocations are reachable
while each lock is held:

  LCK001  lock-order inversion: two locks acquired in BOTH orders on
          some pair of reachable paths (A held while taking B, and B
          held while taking A) — two threads interleaving those paths
          deadlock. One finding per lock pair, anchored at the first
          witness, naming both acquisition sites.
  LCK002  blocking wait while holding a lock: an unbounded
          ``.result()``/``.get()``/``.join()``/``.wait()``/
          ``.acquire()`` (no ``timeout=``), or any HOTPATH blocking
          primitive (file I/O, ``time.sleep``, sockets, subprocess),
          lexically inside a ``with lock:`` extent or reachable from
          one through module-local calls — every other taker of that
          lock stalls behind the wait, and if the waited-on work needs
          the same lock the process deadlocks.
  LCK003  callback invocation while holding a lock: calling a stored /
          registered callable (an ``on_*``/``*_callback``/``*_cb``/
          ``*_hook`` name, ``add_done_callback`` — which runs the
          callback INLINE when the future is already done, on this
          thread, under this lock) — the classic re-entrancy deadlock
          when the callback takes the same lock, and a lock-hold-time
          landmine even when it does not.

Timeout-bounded waits (``.get(timeout=...)``, ``.result(timeout=...)``)
are exempt from LCK002: a bounded wait under a lock is a latency bug,
not a deadlock, and the WAITBUDGET census (thread_lint) prices it.

Known limits (docs/static_analysis.md §LCK): analysis is module-local
(an inversion whose two orders live in different modules crosses the
horizon) with the usual name-based lock identity (two locks spelled
``self._lock`` on DIFFERENT classes are distinct keys; two different
locks bound to one name are one key); same-key re-acquisition is
skipped (RLock reentrancy — a non-reentrant self-acquire is invisible);
callables passed as values are invisible past the LCK003 name tokens.

Scope: every ``.py`` in the package plus ``experiments/`` (override key
``lock_files``).
"""
from __future__ import annotations

import ast
import dataclasses
import pathlib

from . import Finding, override_files, rel_path, source_cached
from .callgraph import CallGraph, FuncInfo, call_name, dotted
from .conc_lint import (_is_lockish, _module_level_names,
                        _scoped_files)
from .hotpath_lint import _banned_label

#: Wait methods that block their caller until another thread acts; a
#: ``timeout=`` keyword (or positional timeout for .wait/.join) bounds
#: them and exempts the site from LCK002.
_WAIT_METHODS = {"result", "get", "join", "wait", "acquire"}

#: Callback-ish callee name shapes (rightmost name): the stored-callable
#: idiom LCK003 exists for.
_CALLBACK_SUFFIXES = ("_callback", "_cb", "_hook")
_CALLBACK_NAMES = {"callback", "cb", "hook", "add_done_callback"}

#: Cheap text prefilter: a module with none of these tokens holds no
#: lock scope, so the graph/closure work is skipped.
_LOCK_TOKENS = ("Lock(", "RLock(", "Condition(", "Semaphore(",
                "_lock", "mutex")


def _wait_label(node: ast.Call) -> str | None:
    """Label when this call is an UNBOUNDED blocking wait (or any
    HOTPATH blocking primitive)."""
    name = call_name(node)
    if isinstance(node.func, ast.Attribute) and name in _WAIT_METHODS:
        # Positional args: str.join(seq)/dict.get(key)/wait(5.0) — a
        # bounded or non-wait spelling either way; kw timeout bounds.
        kws = {kw.arg for kw in node.keywords}
        if not node.args and "timeout" not in kws:
            return f".{name}()"
        return None
    return _banned_label(node)


def _callback_label(node: ast.Call, cls_methods: set[str]) -> str | None:
    """Label when this call invokes a stored/registered callable."""
    name = call_name(node)
    if name in _CALLBACK_NAMES or name.startswith("on_") or \
            name.endswith(_CALLBACK_SUFFIXES):
        return name
    # self.X(...) where X is not a method of the enclosing class in
    # this module: a stored callable attribute.
    if isinstance(node.func, ast.Attribute) and \
            isinstance(node.func.value, ast.Name) and \
            node.func.value.id == "self" and cls_methods and \
            name not in cls_methods:
        return f"self.{name}"
    return None


def _lock_key(expr: ast.expr, info: FuncInfo,
              module_names: set[str]) -> tuple:
    """Identity key of a lockish ``with`` context expression."""
    d = dotted(expr)
    if not d and isinstance(expr, ast.Call):
        d = dotted(expr.func)
    parts = d.split(".") if d else []
    if parts and parts[0] == "self" and info.cls is not None:
        return ("attr", info.cls, ".".join(parts[1:]) or d)
    if parts and parts[0] in module_names:
        return ("global", d)
    return ("local", info.qual, d or f"<line {expr.lineno}>")


def _render_lock(key: tuple) -> str:
    if key[0] == "attr":
        return f"self.{key[2]} ({key[1]})"
    if key[0] == "global":
        return key[1]
    return key[2]


@dataclasses.dataclass
class _FnSummary:
    """One function's direct lock behavior, before closure."""
    info: FuncInfo
    #: (held-keys tuple, acquired key, line)
    acquires: list = dataclasses.field(default_factory=list)
    #: (held-keys tuple, label, line) — only sites under >=1 lock
    waits: list = dataclasses.field(default_factory=list)
    #: (held-keys tuple, label, line)
    callbacks: list = dataclasses.field(default_factory=list)
    #: (held-keys tuple — possibly empty, callee FuncInfo, line):
    #: EVERY resolved module-local call, so the closure can derive the
    #: call-edge list without a second AST walk.
    calls: list = dataclasses.field(default_factory=list)
    #: any blocking wait anywhere in the fn: (label, line)
    any_waits: list = dataclasses.field(default_factory=list)
    #: any callback call anywhere in the fn: (label, line)
    any_callbacks: list = dataclasses.field(default_factory=list)
    #: every lock key this fn acquires directly
    direct_locks: set = dataclasses.field(default_factory=set)


def _summarize(info: FuncInfo, graph: CallGraph, module: str,
               module_names: set[str]) -> _FnSummary:
    s = _FnSummary(info)
    cls_methods = ({m for (c, m) in graph._by_method
                    if c == info.cls} if info.cls is not None else set())

    def walk(nodes, held: tuple) -> None:
        for child in nodes:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue    # a nested def runs later, not under the lock
            if isinstance(child, ast.With):
                inner = held
                for item in child.items:
                    if _is_lockish(item.context_expr):
                        key = _lock_key(item.context_expr, info,
                                        module_names)
                        s.acquires.append((inner, key, child.lineno))
                        s.direct_locks.add(key)
                        inner = inner + (key,)
                walk(child.body, inner)
                continue
            if isinstance(child, ast.Call):
                wl = _wait_label(child)
                if wl is not None:
                    s.any_waits.append((wl, child.lineno))
                    if held:
                        s.waits.append((held, wl, child.lineno))
                cl = _callback_label(child, cls_methods)
                if cl is not None:
                    s.any_callbacks.append((cl, child.lineno))
                    if held:
                        s.callbacks.append((held, cl, child.lineno))
                for callee in graph.resolve_call(child, info):
                    if callee.module == module:
                        s.calls.append((held, callee, child.lineno))
            walk(ast.iter_child_nodes(child), held)

    walk(ast.iter_child_nodes(info.node), ())
    return s


def _closure(summaries: dict[str, _FnSummary], graph: CallGraph,
             module: str) -> tuple[dict, dict, dict]:
    """Transitive (lock / wait / callback) reach per function qual:
    ``all_locks[q]`` = {key: chain}, ``all_waits[q]`` /
    ``all_callbacks[q]`` = (label, line, chain) of one witness."""
    all_locks: dict[str, dict] = {}
    all_waits: dict[str, tuple | None] = {}
    all_callbacks: dict[str, tuple | None] = {}
    # Module-local call edges, straight from the summaries (which record
    # every resolved call, lock-held or not) — no second AST walk.
    edges: dict[str, list[str]] = {}
    for qual, s in summaries.items():
        edges[qual] = [callee.qual for _, callee, _ in s.calls
                       if callee.qual in summaries]
        all_locks[qual] = {k: s.info.label for k in s.direct_locks}
        all_waits[qual] = ((s.any_waits[0][0], s.any_waits[0][1],
                            s.info.label) if s.any_waits else None)
        all_callbacks[qual] = ((s.any_callbacks[0][0],
                               s.any_callbacks[0][1], s.info.label)
                              if s.any_callbacks else None)
    changed = True
    while changed:
        changed = False
        for qual, callees in edges.items():
            for c in callees:
                for key, chain in all_locks[c].items():
                    if key not in all_locks[qual]:
                        all_locks[qual][key] = \
                            f"{summaries[qual].info.label} -> {chain}"
                        changed = True
                for table in (all_waits, all_callbacks):
                    if table[qual] is None and table[c] is not None:
                        label, line, chain = table[c]
                        table[qual] = (
                            label, line,
                            f"{summaries[qual].info.label} -> {chain}")
                        changed = True
    return all_locks, all_waits, all_callbacks


_MSG_LCK002 = ("blocking wait '{label}' while holding {lock}{via} — every "
               "other taker of the lock stalls behind it, and if the "
               "waited-on work needs the same lock the process deadlocks; "
               "release the lock before waiting, or bound the wait with "
               "timeout= (docs/static_analysis.md §LCK)")
_MSG_LCK003 = ("callback '{label}' invoked while holding {lock}{via} — a "
               "callback that takes the same lock re-enters and "
               "deadlocks (add_done_callback runs the callback INLINE "
               "when the future is already done); invoke callbacks "
               "after releasing the lock (docs/static_analysis.md §LCK)")


def _scan_module(root: pathlib.Path, path: pathlib.Path) -> list[Finding]:
    rel = rel_path(path, root)
    try:
        text, tree, err = source_cached(path)
    except OSError:
        return []
    if not any(tok in text for tok in _LOCK_TOKENS):
        return []
    if tree is None:
        return [Finding(rel, err[0], "LCK000",
                        f"syntax error: {err[1]}")]

    graph = CallGraph()
    graph.add_module(rel, tree)
    module_names = _module_level_names(tree)
    summaries = {info.qual: _summarize(info, graph, rel, module_names)
                 for info in graph.functions.values()
                 if info.module == rel}
    if not any(s.direct_locks for s in summaries.values()):
        return []
    all_locks, all_waits, all_callbacks = _closure(summaries, graph, rel)

    findings: list[Finding] = []
    #: (outer, inner) -> (line, description) first witness
    order_edges: dict[tuple, tuple[int, str]] = {}

    def add_edge(outer: tuple, inner: tuple, line: int,
                 desc: str) -> None:
        if outer == inner:
            return    # RLock reentrancy / name-identity limit
        if (outer, inner) not in order_edges:
            order_edges[(outer, inner)] = (line, desc)

    for qual in sorted(summaries):
        s = summaries[qual]
        for held, key, line in s.acquires:
            for outer in held:
                add_edge(outer, key, line,
                         f"{s.info.label} takes {_render_lock(key)} "
                         f"while holding {_render_lock(outer)}")
        for held, label, line in s.waits:
            findings.append(Finding(
                rel, line, "LCK002", _MSG_LCK002.format(
                    label=label, lock=_render_lock(held[-1]), via="")))
        for held, label, line in s.callbacks:
            findings.append(Finding(
                rel, line, "LCK003", _MSG_LCK003.format(
                    label=label, lock=_render_lock(held[-1]), via="")))
        for held, callee, line in s.calls:
            if not held or callee.qual not in all_locks:
                continue
            for key, chain in all_locks[callee.qual].items():
                for outer in held:
                    add_edge(outer, key, line,
                             f"{s.info.label} holds "
                             f"{_render_lock(outer)} and reaches "
                             f"{_render_lock(key)} via {chain}")
            w = all_waits[callee.qual]
            if w is not None:
                label, _, chain = w
                findings.append(Finding(
                    rel, line, "LCK002", _MSG_LCK002.format(
                        label=label, lock=_render_lock(held[-1]),
                        via=f" (reached via {chain})")))
            c = all_callbacks[callee.qual]
            if c is not None:
                label, _, chain = c
                findings.append(Finding(
                    rel, line, "LCK003", _MSG_LCK003.format(
                        label=label, lock=_render_lock(held[-1]),
                        via=f" (reached via {chain})")))

    seen_pairs: set = set()
    for (a, b), (line_ab, desc_ab) in sorted(
            order_edges.items(), key=lambda kv: kv[1][0]):
        if (b, a) not in order_edges:
            continue
        pair = tuple(sorted((a, b)))
        if pair in seen_pairs:
            continue
        seen_pairs.add(pair)
        line_ba, desc_ba = order_edges[(b, a)]
        first, second = ((line_ab, desc_ab), (line_ba, desc_ba))
        if line_ba < line_ab:
            first, second = second, first
        findings.append(Finding(
            rel, first[0], "LCK001",
            f"lock-order inversion between {_render_lock(a)} and "
            f"{_render_lock(b)}: {desc_ab} (line {line_ab}), but "
            f"{desc_ba} (line {line_ba}) — two threads interleaving "
            f"these paths deadlock; pick ONE acquisition order and "
            f"hold it everywhere (docs/static_analysis.md §LCK)"))
    return findings


def run_lock_lint(root: pathlib.Path, overrides=None,
                  notes=None) -> list[Finding]:
    files = override_files(overrides, "lock_files",
                           lambda: _scoped_files(root))
    findings: list[Finding] = []
    for path in files:
        findings.extend(_scan_module(root, path))
    return findings
