"""FUT rules — future-lifecycle provenance (deadlint).

PR 12's pipelined driver made futures first-class on the hot path:
``search_async`` dispatches return ``concurrent.futures.Future``s that
are consumed out of order, cancelled, drained through done-callbacks,
and (in the failure paths) must NEVER be silently dropped — a dropped
future swallows its exception, and an unbounded ``.result()`` on a
wedged dispatch is exactly the hang class ``guarded_collective`` exists
to kill. This pass reuses the provenance idea SYNC proved (track what a
value IS, not what the call looks like), specialized to the future
lifecycle:

  FUT001  dropped future: a ``search_async``/``executor.submit`` result
          discarded outright (a bare expression statement) or bound to
          a name that is never used again in the function — no
          ``.result()``/``.exception()``, no ``add_done_callback``, not
          stored, passed, or returned. Its exception is silently lost
          (the lost-error class; a miner sweep that failed this way
          reads as "no winner" forever).
  FUT002  unbounded blocking consume: ``.result()`` with no ``timeout=``
          or a zero-argument ``.get()`` outside the sanctioned seams
          (``guarded_collective`` and the ``_GuardWorker._loop``
          dispatch-worker inbox — the watchdogged waits that exist so
          nothing else has to wait unbounded). A wedged device dispatch
          behind an unbounded wait is a silent mesh hang at 8-chip
          scale (ROADMAP item 2).
  FUT003  done-callback mutating shared state without the owning lock:
          a callable registered via ``add_done_callback`` whose body
          mutates ``self.attr`` / module-global state with no lock held
          — done-callbacks run on whatever thread completes (or
          cancels) the future, so this is a cross-thread write CONC
          cannot see (the callback edge is invisible to its
          thread-closure walk).

Consumption polarity (FUT001 is deliberately under-approximate): ANY
later use of the bound name — storing it on ``self``, appending it to
a container, passing it to a helper — counts as consumed; only a
future that provably goes nowhere fires. A false negative here is the
price of zero false positives on the deque-threading pipeline driver.

Known limits (docs/static_analysis.md §FUT): producers are recognized
by name (``search_async``, ``.submit(``); FUT001 is per-function (a
future returned to a caller who drops it is the caller's finding only
if the caller is in scope); FUT002 is syntactic (any ``.result()`` —
future or not — with positional args exempt, which excuses
``dict.get(key)`` and ``str.join(seq)``); FUT003 resolves callbacks
one step (a name, ``self.method``, ``functools.partial(fn, ...)``, or
an inline lambda), not through further indirection.

Scope: every ``.py`` in the package plus ``experiments/`` (override key
``future_files``).
"""
from __future__ import annotations

import ast
import pathlib

from . import Finding, override_files, rel_path, source_cached
from .callgraph import CallGraph, FuncInfo, call_name, dotted
from .conc_lint import (_MutationCollector, _module_level_names,
                        _scoped_files)

#: Calls whose result is a future (by rightmost name / method shape).
_FUTURE_CALLS = {"search_async"}
_FUTURE_METHODS = {"submit"}

#: (class or None = any, function) seams sanctioned to wait unbounded:
#: guarded_collective IS the watchdog (its waits are bounded by
#: construction or feed the watchdog queue), and the _GuardWorker loop
#: parks on its inbox BETWEEN dispatches by design (a daemon worker
#: with nothing to do must block; the watchdog guards the dispatch, not
#: the idle park).
SANCTIONED_WAITERS = {(None, "guarded_collective"),
                      ("_GuardWorker", "_loop")}

#: Consuming attribute accesses that settle a future's lifecycle (for
#: the message text only — ANY later use consumes, see module doc).
_CONSUMERS = "result/exception/add_done_callback/cancel"

_SPAWN_TOKENS = ("search_async", ".submit(", ".result(", ".get()",
                 "add_done_callback")


def _is_future_producer(node: ast.Call) -> bool:
    name = call_name(node)
    if name in _FUTURE_CALLS:
        return True
    return (name in _FUTURE_METHODS
            and isinstance(node.func, ast.Attribute))


def _is_sanctioned(info: FuncInfo) -> bool:
    return ((info.cls, info.name) in SANCTIONED_WAITERS
            or (None, info.name) in SANCTIONED_WAITERS)


def _unbounded_wait_label(node: ast.Call) -> str | None:
    name = call_name(node)
    if not isinstance(node.func, ast.Attribute):
        return None
    kws = {kw.arg for kw in node.keywords}
    if name == "result" and not node.args and "timeout" not in kws:
        return ".result()"
    if name == "get" and not node.args and not node.keywords:
        return ".get()"
    return None


def _name_loads(tree: ast.AST, skip: ast.AST | None = None) -> set:
    """Every Name id loaded anywhere under ``tree`` (excluding the
    ``skip`` subtree — the producing assignment's own target)."""
    loads: set[str] = set()
    skipped = {id(n) for n in ast.walk(skip)} if skip is not None else set()
    for n in ast.walk(tree):
        if id(n) in skipped:
            continue
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            loads.add(n.id)
    return loads


def _callback_mutations(cb: ast.expr, graph: CallGraph, owner: FuncInfo,
                        module_names: set) -> list[tuple]:
    """Unlocked shared-state mutation sites inside a registered
    callback: [(key, line)]. ``cb`` is the add_done_callback argument."""
    # functools.partial(fn, ...) -> the wrapped fn.
    if isinstance(cb, ast.Call) and call_name(cb) == "partial" and cb.args:
        cb = cb.args[0]
    sites: list[tuple] = []
    if isinstance(cb, ast.Lambda):
        # Lambdas cannot assign; only mutating method calls on shared
        # receivers count (the conc mutator set).
        from .conc_lint import _MUTATORS
        for n in ast.walk(cb.body):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in _MUTATORS:
                recv = n.func.value
                if isinstance(recv, ast.Name) and recv.id in module_names:
                    sites.append((("global", recv.id), n.lineno))
                elif isinstance(recv, ast.Attribute) and \
                        isinstance(recv.value, ast.Name) and \
                        recv.value.id == "self" and owner.cls is not None:
                    sites.append((("attr", owner.cls, recv.attr),
                                  n.lineno))
        return sites
    for target in graph.resolve_ref(cb, owner):
        collector = _MutationCollector(target, module_names)
        collector.visit(target.node)
        sites.extend((key, line) for key, line, locked in collector.sites
                     if not locked)
    return sites


def _render_key(key: tuple) -> str:
    if key[0] == "global":
        return f"module global '{key[1]}'"
    return f"instance state '{key[1]}.{key[2]}'"


def _scan_module(root: pathlib.Path, path: pathlib.Path) -> list[Finding]:
    rel = rel_path(path, root)
    try:
        text, tree, err = source_cached(path)
    except OSError:
        return []
    if not any(tok in text for tok in _SPAWN_TOKENS):
        return []
    if tree is None:
        return [Finding(rel, err[0], "FUT000",
                        f"syntax error: {err[1]}")]

    graph = CallGraph()
    graph.add_module(rel, tree)
    module_names = _module_level_names(tree)
    owners = graph.owner_map(rel)
    findings: list[Finding] = []

    # ---- FUT002 + FUT003: per call site -------------------------------
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        owner = owners.get(id(node))
        label = _unbounded_wait_label(node)
        if label is not None and \
                not (owner is not None and _is_sanctioned(owner)):
            where = (f" in {owner.label}" if owner is not None else "")
            findings.append(Finding(
                rel, node.lineno, "FUT002",
                f"unbounded blocking consume '{label}'{where} — a wedged "
                f"dispatch behind it is a silent hang (the class "
                f"guarded_collective exists to kill); pass timeout= and "
                f"surface the stall, or route the wait through a "
                f"sanctioned watchdogged seam "
                f"(docs/static_analysis.md §FUT)"))
        if call_name(node) == "add_done_callback" and node.args and \
                owner is not None:
            for key, line in _callback_mutations(
                    node.args[0], graph, owner, module_names):
                findings.append(Finding(
                    rel, line, "FUT003",
                    f"done-callback registered in {owner.label} mutates "
                    f"{_render_key(key)} with no lock — done-callbacks "
                    f"run on whatever thread completes the future, so "
                    f"this races every other toucher of that state "
                    f"(invisible to CONC's thread-closure walk); take "
                    f"the owning lock inside the callback "
                    f"(docs/static_analysis.md §FUT)"))

    # ---- FUT001: dropped futures, per owning function -----------------
    for qual, info in sorted(graph.functions.items()):
        if info.module != rel:
            continue
        for stmt in ast.walk(info.node):
            if isinstance(stmt, ast.Expr) and \
                    isinstance(stmt.value, ast.Call) and \
                    _is_future_producer(stmt.value) and \
                    owners.get(id(stmt.value)) is info:
                findings.append(Finding(
                    rel, stmt.lineno, "FUT001",
                    f"future from "
                    f"'{dotted(stmt.value.func) or call_name(stmt.value)}'"
                    f" in {info.label} is discarded — its exception is "
                    f"silently lost; keep it and {_CONSUMERS} it (or "
                    f"hand it to a consumer) "
                    f"(docs/static_analysis.md §FUT)"))
                continue
            if not isinstance(stmt, ast.Assign) or \
                    not isinstance(stmt.value, ast.Call) or \
                    not _is_future_producer(stmt.value) or \
                    owners.get(id(stmt.value)) is not info:
                continue
            targets = [t for t in stmt.targets if isinstance(t, ast.Name)]
            if len(targets) != len(stmt.targets):
                continue    # attr/subscript target = stored = consumed
            used = _name_loads(info.node, skip=stmt)
            for t in targets:
                if t.id not in used:
                    findings.append(Finding(
                        rel, stmt.lineno, "FUT001",
                        f"future bound to '{t.id}' in {info.label} is "
                        f"never consumed on any path — no "
                        f"{_CONSUMERS}, not stored or passed on; its "
                        f"exception is silently lost "
                        f"(docs/static_analysis.md §FUT)"))
    return findings


def run_future_lint(root: pathlib.Path, overrides=None,
                    notes=None) -> list[Finding]:
    files = override_files(overrides, "future_files",
                           lambda: _scoped_files(root))
    findings: list[Finding] = []
    for path in files:
        findings.extend(_scan_module(root, path))
    return findings
