"""TEL rules — telemetry discipline: causal stamps + metric naming.

The forensics subsystem can only merge per-node logs into one causal
order if every sim-bus event carries a Lamport stamp and a node id.
``CausalLog.record`` stamps both automatically; the classic drift bug is
a future edit that emits a bus event through the raw JSON-lines stream
(``emit_event``) instead, producing records the merge cannot place.

  TEL001  ``emit_event(...)`` in a simulation-bus module whose payload
          cannot be proven to carry both ``lamport`` and ``node`` fields
          — either the dict literal omits them, or the payload is not a
          literal at all. Route sim-bus events through
          ``CausalLog.record`` (telemetry/causal.py), which stamps both.

  TEL002  a registry metric registered under a name that violates the
          documented naming/unit-suffix convention (docs/perfwatch.md):
          counters must end ``_total``; histograms must carry a unit
          suffix (``_ms``/``_seconds``/... or a documented count unit);
          no gauge/histogram may end ``_total``, ``_count`` or ``_sum``
          (``_count``/``_sum`` collide with the summary sample names the
          Prometheus exporter appends, ``_total`` masquerades as a
          counter to any dashboard). Only statically-known (literal
          string) names are checked — f-string families like
          ``sim_group_{field}`` are the call site's responsibility.

  TEL003  a hand-rolled ``rank`` label in a multi-rank code path: a
          ``counter``/``gauge``/``histogram`` call passing ``rank=...``
          directly. The meshwatch aggregator merges per-rank samples on
          the ``rank`` label, so the label must be ONE convention —
          stamped by ``telemetry.rank_counter``/``rank_gauge``/
          ``rank_histogram`` (which default it to the process's declared
          mesh rank) — or an 8-rank merge silently splits one series
          into differently-spelled ones.

  TEL004  a per-dispatch emit point (``profiler().dispatch(...)``) in
          the mining hot loop that does not thread the block trace
          context: the call must carry a ``height=`` keyword (or a
          ``**meta`` spread whose contents the lint cannot see). The
          blocktrace critical-path join attributes segments to blocks
          through the record's meta height (or per-segment trace
          stamps); a dispatch born without one produces segments the
          per-block waterfall can only count as ``unattributed`` — the
          drift bug that silently hollows out ``perfwatch
          critical-path`` (docs/observability.md §blocktrace).

  TEL005  a rendezvous skew-span emit point (``skew_span(...)``) that
          does not carry a ``site=`` keyword. The mesh-skew analyzer
          joins spans ACROSS RANKS on (site, round) — a span born
          without its site label lands in the shard as unjoinable
          noise, silently hollowing out ``perfwatch mesh-skew`` the
          same way a height-less dispatch hollows the critical path
          (docs/observability.md §meshprof). The runtime spells the
          parameter keyword-only for exactly this reason; the lint
          catches the drift where a future refactor loosens it.

  TEL006  a chainwatch incident emit point (``emit_incident(...)``)
          that does not carry explicit ``rule=`` and ``severity=``
          keywords. The incident surfaces all key on them — the
          ``incidents_total{rule,severity}`` counter, the open-episode
          table the shards//healthz//incidents views merge on, the
          bundle filename, the Perfetto annotation lane — so an emit
          born without them produces an incident the whole triage
          pipeline cannot classify (the runtime spells both parameters
          keyword-only; the lint catches the refactor that loosens it,
          same stance as TEL005's site=).

  TEL007  a dispatchwatch compile emit point (``compile_scope(...)`` /
          ``note_cache(...)``) that does not carry a ``site=`` keyword.
          The compile census joins observed XLA compiles to the seam
          cache that should have absorbed them on the site label — a
          compile attributed without one lands as ``unscoped`` noise
          the recompile accounting must price pessimistically, and a
          cache note without one prices nothing at all (the runtime
          spells both parameters keyword-only; the lint catches the
          refactor that loosens it — the same stance as TEL005's
          skew-span site, and the runtime twin of shardlint SHD003's
          divergent-trace gate: SHD003 proves per-rank traces agree
          statically, TEL007 keeps the runtime evidence attributable
          when they don't).

Scope: TEL001 over ``mpi_blockchain_tpu/simulation.py`` (the bus
surface; override key ``sim_py``); TEL002 over every ``.py`` in the
package (override key ``telemetry_files`` — the drift-fixture seam);
TEL003 over the multi-rank surfaces — ``parallel/``, ``meshwatch/``,
``bench_lib.py``, and the multiprocess experiments
(``experiments/multiprocess_world.py``, ``experiments/v5e8_launch.py``;
override key ``rank_scope_files``); TEL004 over the miner/fused/elastic
mining loop plus the CLI seam — ``models/miner.py``, ``models/fused.py``,
``resilience/elastic.py``, ``cli.py`` (override key
``blocktrace_scope_files``); TEL005 over the skew-span emit surface —
``meshprof/``, ``resilience/elastic.py``, ``parallel/mesh.py``,
``blocktrace/overhead.py`` (override key ``skew_scope_files``); TEL006
over the incident emit surface — ``chainwatch/`` plus the wired seams
``resilience/elastic.py``, ``blocktrace/critical_path.py``,
``meshwatch/shard.py`` (override key ``incident_scope_files``); TEL007
over the compile emit surface — ``dispatchwatch/`` plus the wired
dispatch seams ``backend/tpu.py``, ``models/fused.py``,
``parallel/mesh.py``, ``blocktrace/overhead.py`` (override key
``compile_scope_files``).
"""
from __future__ import annotations

import ast
import pathlib

from . import Finding, override_files, rel_path
from .jax_lint import _call_name

REQUIRED_FIELDS = ("lamport", "node")

# TEL002: unit suffixes a histogram name may carry. Time/size units plus
# the repo's documented count units (reorg depth in blocks).
HISTOGRAM_UNIT_SUFFIXES = ("_ms", "_us", "_ns", "_s", "_seconds", "_bytes",
                           "_depth", "_blocks", "_pct")
# Reserved endings: _count/_sum are appended by the Prometheus summary
# renderer; _total is the counter convention.
RESERVED_SUFFIXES = ("_total", "_count", "_sum")


def _literal_str_keys(node: ast.expr) -> set[str] | None:
    """Keys of a dict literal (or dict(...) call with kwargs); None when
    the payload is not statically analyzable."""
    if isinstance(node, ast.Dict):
        keys = set()
        for k in node.keys:
            if k is None:  # **spread: keys unknowable
                return None
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.add(k.value)
        return keys
    if (isinstance(node, ast.Call) and _call_name(node) == "dict"
            and not node.args):
        if any(kw.arg is None for kw in node.keywords):
            return None
        return {kw.arg for kw in node.keywords}
    return None


def _metric_name_arg(node: ast.Call) -> str | None:
    """The literal metric name of a counter/gauge/histogram call, or None
    when it is not statically known (variable, f-string family)."""
    arg = node.args[0] if node.args else None
    if arg is None:
        for kw in node.keywords:
            if kw.arg == "name":
                arg = kw.value
                break
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return None


def _check_metric_name(kind: str, name: str) -> str | None:
    """TEL002 violation message for one (metric kind, literal name)."""
    if kind == "counter":
        if not name.endswith("_total"):
            return (f"counter {name!r} must end '_total' "
                    f"(the monotonic-counter convention)")
        return None
    bad = next((s for s in RESERVED_SUFFIXES if name.endswith(s)), None)
    if bad:
        return (f"{kind} {name!r} must not end {bad!r} — reserved for "
                f"{'counters' if bad == '_total' else 'summary samples'}")
    if kind == "histogram" and not name.endswith(HISTOGRAM_UNIT_SUFFIXES):
        return (f"histogram {name!r} lacks a unit suffix "
                f"{HISTOGRAM_UNIT_SUFFIXES}")
    return None


def _package_py_files(root: pathlib.Path) -> list[pathlib.Path]:
    pkg = root / "mpi_blockchain_tpu"
    return sorted(p for p in pkg.rglob("*.py") if "__pycache__" not in p.parts)


def _rank_scope_files(root: pathlib.Path) -> list[pathlib.Path]:
    """TEL003's multi-rank surface: everywhere a per-rank metric can be
    born (missing files are skipped — experiments are optional in a
    wheel install)."""
    pkg = root / "mpi_blockchain_tpu"
    files: list[pathlib.Path] = []
    for sub in ("parallel", "meshwatch"):
        d = pkg / sub
        if d.is_dir():
            files.extend(p for p in d.rglob("*.py")
                         if "__pycache__" not in p.parts)
    for extra in (pkg / "bench_lib.py",
                  root / "experiments" / "multiprocess_world.py",
                  root / "experiments" / "v5e8_launch.py"):
        if extra.is_file():
            files.append(extra)
    return sorted(files)


def _run_naming_lint(root: pathlib.Path, files) -> list[Finding]:
    """TEL002 over every metric registration with a literal name."""
    findings: list[Finding] = []
    for path in files:
        rel = rel_path(path, root)
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            findings.append(Finding(rel, e.lineno or 1, "TEL000",
                                    f"syntax error: {e.msg}"))
            continue
        except OSError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _call_name(node)
            if kind not in ("counter", "gauge", "histogram"):
                continue
            name = _metric_name_arg(node)
            if name is None:
                continue
            msg = _check_metric_name(kind, name)
            if msg:
                findings.append(Finding(
                    rel, node.lineno, "TEL002",
                    f"{msg}; see the naming convention in "
                    f"docs/perfwatch.md"))
    return findings


def _blocktrace_scope_files(root: pathlib.Path) -> list[pathlib.Path]:
    """TEL004's surface: everywhere a mining dispatch record is born
    (missing files are skipped, matching the other scope builders)."""
    pkg = root / "mpi_blockchain_tpu"
    return sorted(p for p in (pkg / "models" / "miner.py",
                              pkg / "models" / "fused.py",
                              pkg / "resilience" / "elastic.py",
                              pkg / "cli.py") if p.is_file())


def _is_profiler_dispatch(node: ast.Call) -> bool:
    """``profiler().dispatch(...)`` / ``profiler(...).dispatch(...)`` —
    the emit-point idiom, including aliased imports (``from ... import
    profiler as _profiler`` in cli.py), hence the suffix match; the
    profiler's own internal ``self.dispatch`` fallback
    (``segment_on_last``) deliberately does not match."""
    func = node.func
    if not (isinstance(func, ast.Attribute) and func.attr == "dispatch"
            and isinstance(func.value, ast.Call)):
        return False
    name = _call_name(func.value)
    return bool(name) and name.endswith("profiler")


def _run_blocktrace_lint(root: pathlib.Path, files) -> list[Finding]:
    """TEL004: every mining-loop dispatch emit point threads the block
    trace context via an explicit ``height=`` (a ``**`` spread is
    opaque and passes — the call site owns it)."""
    findings: list[Finding] = []
    for path in files:
        rel = rel_path(path, root)
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            findings.append(Finding(rel, e.lineno or 1, "TEL000",
                                    f"syntax error: {e.msg}"))
            continue
        except OSError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or \
                    not _is_profiler_dispatch(node):
                continue
            has_height = any(kw.arg in ("height", None)
                             for kw in node.keywords)
            if not has_height:
                findings.append(Finding(
                    rel, node.lineno, "TEL004",
                    "profiler().dispatch() without height= — the "
                    "dispatch record carries no block identity, so its "
                    "segments fall out of the per-block critical-path "
                    "join as `unattributed`; thread the block trace "
                    "context (pass height=..., or run inside "
                    "blocktrace.trace_block which defaults it) — "
                    "docs/observability.md §blocktrace"))
    return findings


def _skew_scope_files(root: pathlib.Path) -> list[pathlib.Path]:
    """TEL005's surface: everywhere a rendezvous skew span is born
    (missing files are skipped, matching the other scope builders)."""
    pkg = root / "mpi_blockchain_tpu"
    files = [p for p in (pkg / "resilience" / "elastic.py",
                         pkg / "parallel" / "mesh.py",
                         pkg / "blocktrace" / "overhead.py")
             if p.is_file()]
    d = pkg / "meshprof"
    if d.is_dir():
        files.extend(p for p in d.rglob("*.py")
                     if "__pycache__" not in p.parts)
    return sorted(files)


def _run_skew_span_lint(root: pathlib.Path, files) -> list[Finding]:
    """TEL005: every ``skew_span(...)`` emit point carries a literal
    ``site=`` keyword (a ``**`` spread is opaque and passes — the call
    site owns it, same stance as TEL004's height)."""
    findings: list[Finding] = []
    for path in files:
        rel = rel_path(path, root)
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            findings.append(Finding(rel, e.lineno or 1, "TEL000",
                                    f"syntax error: {e.msg}"))
            continue
        except OSError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            # Suffix match for aliased imports (`from ... import
            # skew_span as _skew_span`), same stance as the profiler
            # dispatch idiom.
            if not (name and name.endswith("skew_span")):
                continue
            if not any(kw.arg in ("site", None) for kw in node.keywords):
                findings.append(Finding(
                    rel, node.lineno, "TEL005",
                    "skew_span() without site= — the span carries no "
                    "collective-site label, so the mesh-skew analyzer "
                    "cannot join it across ranks on (site, round) and "
                    "it lands in the shard as unjoinable noise; pass "
                    "site=... at the emit point — "
                    "docs/observability.md §meshprof"))
    return findings


def _incident_scope_files(root: pathlib.Path) -> list[pathlib.Path]:
    """TEL006's surface: everywhere a chainwatch incident is born —
    the subsystem itself plus the wired seams (missing files are
    skipped, matching the other scope builders)."""
    pkg = root / "mpi_blockchain_tpu"
    files = [p for p in (pkg / "resilience" / "elastic.py",
                         pkg / "blocktrace" / "critical_path.py",
                         pkg / "meshwatch" / "shard.py")
             if p.is_file()]
    d = pkg / "chainwatch"
    if d.is_dir():
        files.extend(p for p in d.rglob("*.py")
                     if "__pycache__" not in p.parts)
    return sorted(files)


def _run_incident_lint(root: pathlib.Path, files) -> list[Finding]:
    """TEL006: every ``emit_incident(...)`` emit point carries explicit
    ``rule=`` and ``severity=`` keywords (a ``**`` spread is opaque and
    passes — the call site owns it, same stance as TEL005's site)."""
    findings: list[Finding] = []
    for path in files:
        rel = rel_path(path, root)
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            findings.append(Finding(rel, e.lineno or 1, "TEL000",
                                    f"syntax error: {e.msg}"))
            continue
        except OSError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            # Suffix match for aliased imports (`from ... import
            # emit_incident as _emit_incident`), same stance as TEL005.
            if not (name and name.endswith("emit_incident")):
                continue
            for req in ("rule", "severity"):
                if not any(kw.arg in (req, None)
                           for kw in node.keywords):
                    findings.append(Finding(
                        rel, node.lineno, "TEL006",
                        f"emit_incident() without {req}= — every "
                        f"incident surface (incidents_total labels, "
                        f"the open-episode table the shard//healthz/"
                        f"/incidents views merge on, the bundle "
                        f"filename, the Perfetto annotation lane) keys "
                        f"on it, so the triage pipeline cannot "
                        f"classify the incident; pass {req}=... at the "
                        f"emit point — docs/observability.md "
                        f"§chainwatch"))
    return findings


def _compile_scope_files(root: pathlib.Path) -> list[pathlib.Path]:
    """TEL007's surface: everywhere a compile emit is born — the
    subsystem itself plus the wired dispatch seams (missing files are
    skipped, matching the other scope builders)."""
    pkg = root / "mpi_blockchain_tpu"
    files = [p for p in (pkg / "backend" / "tpu.py",
                         pkg / "models" / "fused.py",
                         pkg / "parallel" / "mesh.py",
                         pkg / "blocktrace" / "overhead.py")
             if p.is_file()]
    d = pkg / "dispatchwatch"
    if d.is_dir():
        files.extend(p for p in d.rglob("*.py")
                     if "__pycache__" not in p.parts)
    return sorted(files)


def _run_compile_emit_lint(root: pathlib.Path, files) -> list[Finding]:
    """TEL007: every ``compile_scope(...)`` / ``note_cache(...)`` emit
    point carries a ``site=`` keyword (a ``**`` spread is opaque and
    passes — the call site owns it, same stance as TEL005's site)."""
    findings: list[Finding] = []
    for path in files:
        rel = rel_path(path, root)
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            findings.append(Finding(rel, e.lineno or 1, "TEL000",
                                    f"syntax error: {e.msg}"))
            continue
        except OSError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            # Suffix match for aliased imports (`from ... import
            # compile_scope as _compile_scope`), same stance as TEL005.
            if not (name and (name.endswith("compile_scope")
                              or name.endswith("note_cache"))):
                continue
            if not any(kw.arg in ("site", None) for kw in node.keywords):
                emit = ("compile_scope" if name.endswith("compile_scope")
                        else "note_cache")
                findings.append(Finding(
                    rel, node.lineno, "TEL007",
                    f"{emit}() without site= — the compile census joins "
                    f"observed XLA compiles to the seam cache that "
                    f"should have absorbed them on the site label, so "
                    f"this emit lands as unscoped/unpriceable noise; "
                    f"pass site=... at the emit point — "
                    f"docs/observability.md §dispatchwatch"))
    return findings


def _run_rank_label_lint(root: pathlib.Path, files) -> list[Finding]:
    """TEL003: no hand-rolled ``rank=`` label on a raw registry call in
    multi-rank code."""
    findings: list[Finding] = []
    for path in files:
        rel = rel_path(path, root)
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            findings.append(Finding(rel, e.lineno or 1, "TEL000",
                                    f"syntax error: {e.msg}"))
            continue
        except OSError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _call_name(node)
            if kind not in ("counter", "gauge", "histogram"):
                continue
            if any(kw.arg == "rank" for kw in node.keywords):
                findings.append(Finding(
                    rel, node.lineno, "TEL003",
                    f"hand-rolled rank label on {kind}() in a "
                    f"multi-rank code path — use telemetry.rank_{kind} "
                    f"so the `rank` label the mesh aggregator merges on "
                    f"stays one convention (docs/observability.md "
                    f"§Mesh shards)"))
    return findings


def run_telemetry_lint(root: pathlib.Path, overrides=None,
                       notes=None) -> list[Finding]:
    overrides = overrides or {}
    tel_files = override_files(overrides, "telemetry_files",
                               lambda: _package_py_files(root))
    findings: list[Finding] = list(_run_naming_lint(root, tel_files))
    rank_files = override_files(overrides, "rank_scope_files",
                                lambda: _rank_scope_files(root))
    findings.extend(_run_rank_label_lint(root, rank_files))
    bt_files = override_files(overrides, "blocktrace_scope_files",
                              lambda: _blocktrace_scope_files(root))
    findings.extend(_run_blocktrace_lint(root, bt_files))
    skew_files = override_files(overrides, "skew_scope_files",
                                lambda: _skew_scope_files(root))
    findings.extend(_run_skew_span_lint(root, skew_files))
    incident_files = override_files(overrides, "incident_scope_files",
                                    lambda: _incident_scope_files(root))
    findings.extend(_run_incident_lint(root, incident_files))
    compile_files = override_files(overrides, "compile_scope_files",
                                   lambda: _compile_scope_files(root))
    findings.extend(_run_compile_emit_lint(root, compile_files))
    sim_py = overrides.get(
        "sim_py", root / "mpi_blockchain_tpu" / "simulation.py")
    rel = rel_path(sim_py, root)
    try:
        tree = ast.parse(sim_py.read_text(), filename=str(sim_py))
    except SyntaxError as e:
        return findings + [Finding(rel, e.lineno or 1, "TEL000",
                                   f"syntax error: {e.msg}")]
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or \
                _call_name(node) != "emit_event":
            continue
        payload = node.args[0] if node.args else None
        keys = _literal_str_keys(payload) if payload is not None else set()
        if keys is None:
            findings.append(Finding(
                rel, node.lineno, "TEL001",
                "emit_event on the simulation bus with a non-literal "
                "payload — the causal stamp cannot be verified; route "
                "the event through CausalLog.record, which stamps "
                "lamport/node automatically"))
        else:
            missing = [f for f in REQUIRED_FIELDS if f not in keys]
            if missing:
                findings.append(Finding(
                    rel, node.lineno, "TEL001",
                    f"sim-bus event omits causal field(s) "
                    f"{missing} — the forensics merge cannot place it; "
                    f"use CausalLog.record (stamps lamport/node) instead "
                    f"of raw emit_event"))
    return findings
