"""TEL rules — causal-stamp discipline on the simulation bus.

The forensics subsystem can only merge per-node logs into one causal
order if every sim-bus event carries a Lamport stamp and a node id.
``CausalLog.record`` stamps both automatically; the classic drift bug is
a future edit that emits a bus event through the raw JSON-lines stream
(``emit_event``) instead, producing records the merge cannot place.

  TEL001  ``emit_event(...)`` in a simulation-bus module whose payload
          cannot be proven to carry both ``lamport`` and ``node`` fields
          — either the dict literal omits them, or the payload is not a
          literal at all. Route sim-bus events through
          ``CausalLog.record`` (telemetry/causal.py), which stamps both.

Scope: ``mpi_blockchain_tpu/simulation.py`` (the bus surface). Override
key ``sim_py`` redirects it — the drift-fixture test seam.
"""
from __future__ import annotations

import ast
import pathlib

from . import Finding
from .jax_lint import _call_name

REQUIRED_FIELDS = ("lamport", "node")


def _literal_str_keys(node: ast.expr) -> set[str] | None:
    """Keys of a dict literal (or dict(...) call with kwargs); None when
    the payload is not statically analyzable."""
    if isinstance(node, ast.Dict):
        keys = set()
        for k in node.keys:
            if k is None:  # **spread: keys unknowable
                return None
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.add(k.value)
        return keys
    if (isinstance(node, ast.Call) and _call_name(node) == "dict"
            and not node.args):
        if any(kw.arg is None for kw in node.keywords):
            return None
        return {kw.arg for kw in node.keywords}
    return None


def run_telemetry_lint(root: pathlib.Path, overrides=None,
                       notes=None) -> list[Finding]:
    overrides = overrides or {}
    sim_py = overrides.get(
        "sim_py", root / "mpi_blockchain_tpu" / "simulation.py")
    findings: list[Finding] = []
    rel = (str(sim_py.relative_to(root)) if sim_py.is_relative_to(root)
           else str(sim_py))
    try:
        tree = ast.parse(sim_py.read_text(), filename=str(sim_py))
    except SyntaxError as e:
        return [Finding(rel, e.lineno or 1, "TEL000",
                        f"syntax error: {e.msg}")]
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or \
                _call_name(node) != "emit_event":
            continue
        payload = node.args[0] if node.args else None
        keys = _literal_str_keys(payload) if payload is not None else set()
        if keys is None:
            findings.append(Finding(
                rel, node.lineno, "TEL001",
                "emit_event on the simulation bus with a non-literal "
                "payload — the causal stamp cannot be verified; route "
                "the event through CausalLog.record, which stamps "
                "lamport/node automatically"))
        else:
            missing = [f for f in REQUIRED_FIELDS if f not in keys]
            if missing:
                findings.append(Finding(
                    rel, node.lineno, "TEL001",
                    f"sim-bus event omits causal field(s) "
                    f"{missing} — the forensics merge cannot place it; "
                    f"use CausalLog.record (stamps lamport/node) instead "
                    f"of raw emit_event"))
    return findings
