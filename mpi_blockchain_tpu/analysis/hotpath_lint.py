"""HOT rules — blocking-call detection on the dispatch hot path.

The roofline work made per-chip speed an algorithmic problem precisely
because the host loop between device sweeps is tight: one dispatch per
sweep, heartbeats and counters through the in-memory telemetry ring, and
nothing else. The async-pipelined-dispatch refactor (ROADMAP item 4)
lives or dies on that staying true — a single ``time.sleep``, checkpoint
write, or socket call creeping into ``Miner.mine_block`` serializes the
pipeline and silently re-opens the bubble the perfwatch pipeline report
measures. This pass walks the call graph (analysis/callgraph.py) from
the mine-loop entry points and flags blocking work reachable on the
sweep critical path:

  HOT001  a blocking call — file I/O (``open``/``os.fdopen``/pathlib
           read/write/mkdir, ``tempfile``), ``time.sleep``, socket ops,
           ``subprocess``/``os.system``, ``os.replace``/``rename``/
           ``fsync`` (the checkpoint-write primitives) — reachable from
           a hot-path entry point outside the sanctioned seams. The
           finding message carries the call chain that reaches it.
  HOT002  a configured hot-path entry point does not exist in the
           analyzed file set — the lint is silently checking nothing
           (fires when a refactor renames ``Miner.mine_chain`` without
           updating the entry list here).

Entry points: ``Miner.mine_chain``/``mine_block`` (models/miner.py) and
``FusedMiner.mine_chain``/``_mine_span`` (models/fused.py).

Sanctioned seams (pruned from traversal — blocking work INSIDE them is
their own contract, reviewed there):

* ``telemetry/`` — in-memory registry/ring/span work (JAX006 already
  keeps it out of jit; here it is the sanctioned hot-loop sink);
* ``meshwatch/`` — the shard flusher does its file I/O on a daemon
  thread, off the mine loop;
* ``perfwatch/`` — the HTTP endpoint serves on its own thread;
* ``resilience/policy.py`` + ``resilience/injection.py`` — retry
  backoff sleeps and injected fault sleeps are deliberate, fault-path-
  only blocking, owned by the resilience layer;
* ``utils/logging.py`` — delegates to the telemetry event ring.

The checkpoint seam stays honest by construction: ``mine
--checkpoint-every`` runs through the ``on_block`` callback, which a
static call graph cannot follow — checkpoint writes only trip HOT001
when someone wires them DIRECTLY into the mine loop, which is exactly
the drift this rule exists to stop. Known limits in
docs/static_analysis.md §Known limits.

Scope (override key ``hotpath_files``): ``models/``, ``backend/``,
``ops/``, ``parallel/``, ``core/*.py``, ``utils/``, ``config.py``,
``resilience/dispatch.py``.
"""
from __future__ import annotations

import ast
import pathlib

from . import Finding, override_files, package_scope, rel_path
from .callgraph import CallGraph, FuncInfo, call_name, dotted

#: (class, method) hot-path entry points; every one must exist (HOT002).
#: Shared root set: sync_lint walks the same roots (SYNC003 mirrors
#: HOT002), so a rename is caught by whichever family runs.
ENTRY_POINTS = (
    ("Miner", "mine_chain"),
    ("Miner", "mine_block"),
    ("FusedMiner", "mine_chain"),
    ("FusedMiner", "_mine_span"),
)

#: Module path prefixes (repo-relative, posix) pruned from traversal.
SANCTIONED_SEAMS = (
    "mpi_blockchain_tpu/telemetry",
    "mpi_blockchain_tpu/meshwatch",
    "mpi_blockchain_tpu/perfwatch",
    # blocktrace: in-memory trace context + per-block waterfall math —
    # the same sanctioned hot-loop sink as telemetry (no file I/O on
    # any path reachable from the miner).
    "mpi_blockchain_tpu/blocktrace",
    "mpi_blockchain_tpu/resilience/policy.py",
    "mpi_blockchain_tpu/resilience/injection.py",
    "mpi_blockchain_tpu/utils/logging.py",
    # blockserve: the miner only ever touches the service through
    # TemplateFeed.payload_for (lock-guarded in-memory template read;
    # rebuilds happen on handler threads) — sanctioned like telemetry.
    "mpi_blockchain_tpu/service",
)

#: Dotted (module, func) pairs that block the calling thread.
_BANNED_DOTTED = {
    ("time", "sleep"),
    ("os", "replace"), ("os", "rename"), ("os", "fsync"),
    ("os", "fdopen"), ("os", "system"), ("os", "popen"),
    ("socket", "socket"), ("socket", "create_connection"),
    ("tempfile", "mkstemp"), ("tempfile", "mkdtemp"),
    ("tempfile", "NamedTemporaryFile"), ("tempfile", "TemporaryFile"),
    ("shutil", "copy"), ("shutil", "copyfile"), ("shutil", "move"),
}

#: Dotted prefixes that are blocking wholesale.
_BANNED_PREFIXES = ("subprocess.", "urllib.request.")

#: Bare builtin/from-imported names that block.
_BANNED_BARE = {"open", "sleep", "mkstemp", "urlopen"}

#: pathlib-style I/O method names (attribute calls on any receiver;
#: "open" covers both ``path.open()`` and e.g. ``gzip.open``).
_BANNED_IO_METHODS = {"open", "read_text", "write_text", "read_bytes",
                      "write_bytes", "mkdir", "rmdir", "touch",
                      "unlink", "hardlink_to", "symlink_to"}


def _banned_label(node: ast.Call) -> str | None:
    """The human label when this call is a blocking primitive."""
    d = dotted(node.func)
    name = call_name(node)
    if d:
        parts = tuple(d.split("."))
        if len(parts) >= 2 and parts[-2:] in _BANNED_DOTTED:
            return d
        if any(d.startswith(p) for p in _BANNED_PREFIXES):
            return d
    if isinstance(node.func, ast.Name) and name in _BANNED_BARE:
        return name
    if isinstance(node.func, ast.Attribute) and \
            name in _BANNED_IO_METHODS:
        return f".{name}()"
    return None


def _scoped_files(root: pathlib.Path) -> list[pathlib.Path]:
    return package_scope(
        root, subdirs=("models", "backend", "ops", "parallel", "utils"),
        extras=("config.py", "resilience/dispatch.py"),
        core_glob=True)


def _is_sanctioned(info: FuncInfo) -> bool:
    mod = info.module.replace("\\", "/")
    return any(mod.startswith(seam) for seam in SANCTIONED_SEAMS)


def run_hotpath_lint(root: pathlib.Path, overrides=None,
                     notes=None) -> list[Finding]:
    files = override_files(overrides, "hotpath_files",
                           lambda: _scoped_files(root))

    graph, errors = CallGraph.from_files(root, files)
    findings: list[Finding] = [
        Finding(rel, lineno, "HOT000", f"syntax error: {msg}")
        for rel, lineno, msg in errors]

    anchor = (rel_path(files[0], root) if files
              else "mpi_blockchain_tpu")
    roots, missing = graph.resolve_roots(ENTRY_POINTS)
    for cls, method in missing:
        findings.append(Finding(
            anchor, 1, "HOT002",
            f"hot-path entry point {cls}.{method} not found in the "
            f"analyzed file set — the blocking-call lint is "
            f"checking nothing for it; update ENTRY_POINTS in "
            f"analysis/hotpath_lint.py alongside the rename"))

    chains = graph.reachable(roots, prune=_is_sanctioned)
    seen: set[tuple[str, int]] = set()
    for qual in sorted(chains):
        info = graph.functions[qual]
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            label = _banned_label(node)
            if label is None:
                continue
            key = (info.module, node.lineno)
            if key in seen:
                continue
            seen.add(key)
            chain = " -> ".join(chains[qual])
            findings.append(Finding(
                info.module, node.lineno, "HOT001",
                f"blocking call '{label}' reachable on the dispatch hot "
                f"path via {chain} — it serializes the sweep pipeline; "
                f"move it behind a sanctioned async seam (telemetry "
                f"ring, meshwatch flusher thread, the on_block "
                f"checkpoint callback) or off the critical path "
                f"(docs/static_analysis.md §HOTPATH)"))
    return findings
