"""RES rules — swallow-proof fault handling in dispatch/IO paths.

The resilience layer's whole premise is that dispatch and I/O failures
reach ONE sanctioned decision point (``resilience/policy.py``'s
``call_with_retry`` — retry, degrade, or raise ``RetryExhausted``)
instead of dying silently where they happened. The classic drift bug is
a future edit dropping an ``except Exception: pass`` around a device
call or a checkpoint write "to be safe" — which converts a detectable
fault into silent corruption or a silent stall, the exact failure class
ISSUE 5 exists to kill.

  RES001  in a dispatch/IO-path module, an exception handler that
          swallows broadly: a bare ``except:`` (catches SystemExit /
          KeyboardInterrupt) that does not re-raise, or an
          ``except Exception:`` / ``except BaseException:`` (alone or
          in a tuple) whose body is only ``pass`` / ``continue`` /
          ``...``. Handle the specific exception, let it propagate to
          the policy layer, or at minimum record it (a counter, an
          event, a warning) before moving on.

Scope: the dispatch/IO surface — ``backend/``, ``core/build.py``,
``core/_ctypes_binding.py``, ``utils/checkpoint.py``,
``simulation.py``, ``models/``, ``parallel/distributed.py`` (override
key ``resilience_files`` — the drift-fixture seam). The sanctioned
swallow point ``resilience/policy.py`` is deliberately outside the
scope.
"""
from __future__ import annotations

import ast
import pathlib

from . import Finding

#: Repo-relative dispatch/IO paths RES001 covers (files or directories).
DISPATCH_IO_PATHS = (
    "mpi_blockchain_tpu/backend",
    "mpi_blockchain_tpu/core/build.py",
    "mpi_blockchain_tpu/core/_ctypes_binding.py",
    "mpi_blockchain_tpu/utils/checkpoint.py",
    "mpi_blockchain_tpu/simulation.py",
    "mpi_blockchain_tpu/models",
    "mpi_blockchain_tpu/parallel/distributed.py",
)

_BROAD = ("Exception", "BaseException")


def _expr_name(node: ast.expr) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):   # builtins.Exception etc.
        return node.attr
    return None


def _catches_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True   # bare except:
    if isinstance(t, ast.Tuple):
        return any(_expr_name(e) in _BROAD for e in t.elts)
    return _expr_name(t) in _BROAD


def _body_swallows(body: list[ast.stmt]) -> bool:
    """True when the handler body does nothing observable: only pass /
    continue / bare `...` — no raise, no logging, no assignment."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant) and \
                stmt.value.value is Ellipsis:
            continue
        return False
    return True


def _reraises(body: list[ast.stmt]) -> bool:
    return any(isinstance(n, ast.Raise)
               for stmt in body for n in ast.walk(stmt))


def _scan_file(root: pathlib.Path, path: pathlib.Path) -> list[Finding]:
    rel = (str(path.relative_to(root)) if path.is_relative_to(root)
           else str(path))
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError as e:
        return [Finding(rel, e.lineno or 1, "RES000",
                        f"syntax error: {e.msg}")]
    except OSError:
        return []
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            # A bare except that re-raises is a (crude) cleanup idiom;
            # one that does not is a black hole for SIGINT and bugs.
            if not _reraises(node.body):
                findings.append(Finding(
                    rel, node.lineno, "RES001",
                    "bare 'except:' in a dispatch/IO path swallows "
                    "everything incl. KeyboardInterrupt — catch the "
                    "specific exception or route it through the "
                    "resilience policy layer (call_with_retry)"))
        elif _catches_broad(node) and _body_swallows(node.body):
            findings.append(Finding(
                rel, node.lineno, "RES001",
                "'except Exception: pass' in a dispatch/IO path turns a "
                "detectable fault into silent corruption/stall — handle "
                "it, record it (counter/event), or let it reach the "
                "resilience policy layer"))
    return findings


def _scoped_files(root: pathlib.Path) -> list[pathlib.Path]:
    files: list[pathlib.Path] = []
    for entry in DISPATCH_IO_PATHS:
        p = root / entry
        if p.is_dir():
            files.extend(sorted(q for q in p.rglob("*.py")
                                if "__pycache__" not in q.parts))
        elif p.exists():
            files.append(p)
    return files


def run_resilience_lint(root: pathlib.Path, overrides=None,
                        notes=None) -> list[Finding]:
    overrides = overrides or {}
    files = overrides.get("resilience_files")
    if files is None:
        files = _scoped_files(root)
    elif isinstance(files, (str, pathlib.Path)):
        files = [pathlib.Path(files)]
    findings: list[Finding] = []
    for path in files:
        findings.extend(_scan_file(root, pathlib.Path(path)))
    return findings
