"""RES rules — swallow-proof fault handling + byte-reproducible attacks.

The resilience layer's whole premise is that dispatch and I/O failures
reach ONE sanctioned decision point (``resilience/policy.py``'s
``call_with_retry`` — retry, degrade, or raise ``RetryExhausted``)
instead of dying silently where they happened. The classic drift bug is
a future edit dropping an ``except Exception: pass`` around a device
call or a checkpoint write "to be safe" — which converts a detectable
fault into silent corruption or a silent stall, the exact failure class
ISSUE 5 exists to kill.

  RES001  in a dispatch/IO-path module, an exception handler that
          swallows broadly: a bare ``except:`` (catches SystemExit /
          KeyboardInterrupt) that does not re-raise, or an
          ``except Exception:`` / ``except BaseException:`` (alone or
          in a tuple) whose body is only ``pass`` / ``continue`` /
          ``...``. Handle the specific exception, let it propagate to
          the policy layer, or at minimum record it (a counter, an
          event, a warning) before moving on.

  RES002  in the adversarial-simulation package (``sim/`` — scenario,
          engine, strategies, live-bus attackers), any randomness or
          time source OUTSIDE the seeded scenario RNG: importing
          ``random``/``secrets``/``uuid``, calling ``os.urandom``,
          reading the wall clock (``time.time``/``monotonic``/
          ``perf_counter``/``*_ns``, ``datetime.now``/``utcnow``/
          ``today``), or numpy's STATEFUL global RNG surface
          (``np.random.seed``/``random``/``rand``/``randint``/...).
          Every attack decision must come from the scenario seed
          through ``ScenarioRng`` (crc32 / keyed Philox) — that is
          what keeps a 1000-node adversarial run byte-reproducible,
          the property the chaos/adversary smoke gates assert.

Scope: RES001 covers the dispatch/IO surface — ``backend/``,
``core/build.py``, ``core/_ctypes_binding.py``, ``utils/checkpoint.py``,
``simulation.py``, ``models/``, ``parallel/distributed.py`` (override
key ``resilience_files``). RES002 covers ``mpi_blockchain_tpu/sim/``
(override key ``adversary_files``). The sanctioned swallow point
``resilience/policy.py`` is deliberately outside both scopes.
"""
from __future__ import annotations

import ast
import pathlib

from . import Finding, override_files, rel_path

#: Repo-relative dispatch/IO paths RES001 covers (files or directories).
DISPATCH_IO_PATHS = (
    "mpi_blockchain_tpu/backend",
    "mpi_blockchain_tpu/core/build.py",
    "mpi_blockchain_tpu/core/_ctypes_binding.py",
    "mpi_blockchain_tpu/utils/checkpoint.py",
    "mpi_blockchain_tpu/simulation.py",
    "mpi_blockchain_tpu/models",
    "mpi_blockchain_tpu/parallel/distributed.py",
    # blockserve: the front door's admission/rebuild paths are dispatch
    # IO — a swallowed failure there is a silently dropped transaction,
    # the exact class the shed/typed-response contract forbids.
    "mpi_blockchain_tpu/service",
)

_BROAD = ("Exception", "BaseException")


def _expr_name(node: ast.expr) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):   # builtins.Exception etc.
        return node.attr
    return None


def _catches_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True   # bare except:
    if isinstance(t, ast.Tuple):
        return any(_expr_name(e) in _BROAD for e in t.elts)
    return _expr_name(t) in _BROAD


def _body_swallows(body: list[ast.stmt]) -> bool:
    """True when the handler body does nothing observable: only pass /
    continue / bare `...` — no raise, no logging, no assignment."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant) and \
                stmt.value.value is Ellipsis:
            continue
        return False
    return True


def _reraises(body: list[ast.stmt]) -> bool:
    return any(isinstance(n, ast.Raise)
               for stmt in body for n in ast.walk(stmt))


def _scan_file(root: pathlib.Path, path: pathlib.Path) -> list[Finding]:
    rel = rel_path(path, root)
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError as e:
        return [Finding(rel, e.lineno or 1, "RES000",
                        f"syntax error: {e.msg}")]
    except OSError:
        return []
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            # A bare except that re-raises is a (crude) cleanup idiom;
            # one that does not is a black hole for SIGINT and bugs.
            if not _reraises(node.body):
                findings.append(Finding(
                    rel, node.lineno, "RES001",
                    "bare 'except:' in a dispatch/IO path swallows "
                    "everything incl. KeyboardInterrupt — catch the "
                    "specific exception or route it through the "
                    "resilience policy layer (call_with_retry)"))
        elif _catches_broad(node) and _body_swallows(node.body):
            findings.append(Finding(
                rel, node.lineno, "RES001",
                "'except Exception: pass' in a dispatch/IO path turns a "
                "detectable fault into silent corruption/stall — handle "
                "it, record it (counter/event), or let it reach the "
                "resilience policy layer"))
    return findings


def _scoped_files(root: pathlib.Path) -> list[pathlib.Path]:
    files: list[pathlib.Path] = []
    for entry in DISPATCH_IO_PATHS:
        p = root / entry
        if p.is_dir():
            files.extend(sorted(q for q in p.rglob("*.py")
                                if "__pycache__" not in q.parts))
        elif p.exists():
            files.append(p)
    return files


# ---- RES002: seeded-RNG-only adversary paths ------------------------------

#: The adversarial-simulation package RES002 covers.
ADVERSARY_PATHS = ("mpi_blockchain_tpu/sim",)

#: Modules whose mere import is nondeterminism on an attack path.
_BANNED_MODULES = {"random", "secrets", "uuid"}

#: attribute-call chains that read the wall clock or OS entropy.
_BANNED_CALLS = {
    ("time", "time"), ("time", "monotonic"), ("time", "perf_counter"),
    ("time", "time_ns"), ("time", "monotonic_ns"),
    ("time", "perf_counter_ns"),
    ("datetime", "now"), ("datetime", "utcnow"), ("datetime", "today"),
    ("os", "urandom"), ("os", "getrandom"),
}

#: numpy's STATEFUL global-RNG surface (np.random.<name>(...)). The
#: counter-based constructors (Philox/Generator/SeedSequence/PCG64 and
#: a SEEDED default_rng) stay legal — they are how ScenarioRng works.
_BANNED_NP_RANDOM = {
    "seed", "random", "rand", "randn", "randint", "random_sample",
    "uniform", "normal", "choice", "shuffle", "permutation", "bytes",
}


def _dotted(node: ast.expr) -> list[str]:
    """['np', 'random', 'seed'] for np.random.seed — [] when not a
    plain attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


def _scan_adversary_file(root: pathlib.Path,
                         path: pathlib.Path) -> list[Finding]:
    rel = rel_path(path, root)
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError as e:
        return [Finding(rel, e.lineno or 1, "RES000",
                        f"syntax error: {e.msg}")]
    except OSError:
        return []
    findings: list[Finding] = []

    def flag(line: int, what: str) -> None:
        findings.append(Finding(
            rel, line, "RES002",
            f"{what} in an adversary/scenario path breaks "
            f"byte-reproducibility — draw from the seeded ScenarioRng "
            f"(crc32 / keyed Philox) instead"))

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.split(".")[0] in _BANNED_MODULES:
                    flag(node.lineno, f"import of {alias.name!r}")
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[0] \
                    in _BANNED_MODULES:
                flag(node.lineno, f"import from {node.module!r}")
            elif node.module:
                # Bare from-imports of banned members (`from time
                # import time`) would otherwise dodge the dotted-call
                # check below — flag them at the import site.
                mod = node.module.split(".")[0]
                for alias in node.names:
                    if (mod, alias.name) in _BANNED_CALLS:
                        flag(node.lineno,
                             f"from-import of wall-clock/entropy "
                             f"{mod}.{alias.name}")
        elif isinstance(node, ast.Call):
            parts = _dotted(node.func)
            if not parts:
                continue
            tail = tuple(parts[-2:])
            if len(parts) >= 2 and tail in _BANNED_CALLS:
                flag(node.lineno, f"wall-clock/entropy call "
                                  f"{'.'.join(parts)}()")
            elif len(parts) >= 3 and parts[-2] == "random" and \
                    parts[-1] in _BANNED_NP_RANDOM:
                flag(node.lineno, f"stateful global-RNG call "
                                  f"{'.'.join(parts)}()")
            elif parts[-1] == "default_rng" and not node.args \
                    and not node.keywords:
                # Bare (from-imported) calls too: len(parts) may be 1.
                flag(node.lineno, "unseeded default_rng() (OS "
                                  "entropy)")
    return findings


def _adversary_files(root: pathlib.Path) -> list[pathlib.Path]:
    files: list[pathlib.Path] = []
    for entry in ADVERSARY_PATHS:
        p = root / entry
        if p.is_dir():
            files.extend(sorted(q for q in p.rglob("*.py")
                                if "__pycache__" not in q.parts))
        elif p.exists():
            files.append(p)
    return files


def run_resilience_lint(root: pathlib.Path, overrides=None,
                        notes=None) -> list[Finding]:
    overrides = overrides or {}
    files = override_files(overrides, "resilience_files",
                           lambda: _scoped_files(root))
    findings: list[Finding] = []
    for path in files:
        findings.extend(_scan_file(root, path))
    adversary = override_files(overrides, "adversary_files",
                               lambda: _adversary_files(root))
    for path in adversary:
        findings.extend(_scan_adversary_file(root, pathlib.Path(path)))
    return findings
