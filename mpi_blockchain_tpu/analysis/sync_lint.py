"""SYNC rules — implicit host-device syncs on the dispatch hot path.

The async-pipelined-dispatch refactor (ROADMAP item 1: double-buffered
dispatch, ``bubble_fraction`` -> ~0) lives or dies on one discipline:
between sweeps, the host may TOUCH a device value only at the sanctioned
materialization seam. Every other touch — ``np.asarray`` on a device
array, ``int()``/``float()`` on a traced scalar, ``.item()``, an ``if``
branching on a device value — is an *implicit* ``block_until_ready``:
the host stalls until the device drains, the pipeline serializes, and
the bubble the meshwatch pipeline report prices silently re-opens.
HOT001 cannot see this class (the calls look pure); this pass can,
because it tracks *value provenance*.

Walking the call graph from the shared hot-path roots
(``hotpath_lint.ENTRY_POINTS``), a lightweight flow-sensitive
provenance pass tags device-origin values — results of backend
``search`` calls, of dispatching a built device program
(``self._fn(k)(...)``/``self._searcher(d)(...)``, the
factory-call-then-call shape), and of ``jnp.*`` constructors — through
assignments, tuple unpacking, subscripts, and closure ``nonlocal``
writebacks (the thread-body idiom), then flags:

  SYNC001  a blocking host sync/transfer applied to a device-origin
           value outside the sanctioned seams: ``np.asarray``/
           ``np.array``, ``jax.device_get``, ``int()``/``float()``/
           ``bool()``, ``.item()``/``.tolist()``/``.copy_to_host()``,
           formatting into an f-string — plus any explicit
           ``.block_until_ready()`` on the hot path (definitionally a
           sync, device-origin or not).
  SYNC002  a device-origin value escaping into Python control flow (an
           ``if``/``while``/``assert``/ternary test, a ``for`` iterating
           a device array) — forces the same sync AND, when the value
           shape/dtype varies, is the retrace-churn trigger.
  SYNC003  a configured hot-path entry point does not exist in the
           analyzed file set — the sync lint is silently checking
           nothing (mirrors HOT002; the root set is shared).

Sanctioned seams:

* the module seams HOTPATH prunes (telemetry/, meshwatch/, perfwatch/,
  blocktrace/, resilience policy/injection, utils/logging) — host work
  inside them is their own reviewed contract;
* ``replicated_host_value``/``replicated_host_values``
  (parallel/mesh.py) — THE materialization point. A call to either is
  the sanctioned sync (the winner re-validation path's ``np.asarray``
  lives inside them, batched to one tunnel round trip), and its result
  is host-origin: provenance is laundered through the seam.

Known limits (documented in docs/static_analysis.md §SYNC): provenance
is per-function (module-local call *names* mark producers; returns
propagate only through tuple unpacking at the call site); attribute
access launders (``res.nonce`` on a ``SearchResult`` is a materialized
host field by the backend contract); values routed through containers
(``batches.append(...)`` then ``pop()``) lose their tag — the polarity
is deliberate: a device value that takes one of those shapes must pass
the seam before the container anyway, and the seam call count is what
the TRB census ratchets.

Scope (override key ``sync_files``): ``models/``, ``backend/``,
``parallel/``, ``core/*.py``, ``utils/``, ``config.py``,
``resilience/dispatch.py``, ``resilience/elastic.py`` — the host-side
sweep loop. ``ops/`` is deliberately out: device-side (traced) purity
is JAX001/JAX002's jurisdiction.
"""
from __future__ import annotations

import ast
import pathlib

from . import Finding, override_files, package_scope, rel_path
from .callgraph import CallGraph, FuncInfo, call_name, dotted
from .hotpath_lint import ENTRY_POINTS, SANCTIONED_SEAMS

#: Calls whose RESULT is device-origin (by rightmost name).
#: ``search_async`` is the double-buffered pipeline's future-returning
#: dispatch seam (backend.search_async): the future wraps a device
#: value, so touching it with a sync primitive is the same stall —
#: consuming it through ``.result()`` (attribute access) launders, per
#: the SearchResult materialized-field contract.
DEVICE_PRODUCING_CALLS = {"search", "search_async"}

#: ``search`` sites that are NOT device dispatches (dotted prefixes).
_SEARCH_EXEMPT_PREFIXES = ("re.", "regex.")

#: Receiver name tokens marking a regex object's ``.search`` (the
#: compiled-pattern spelling: ``pat.search(line)``); token-matched on
#: the receiver's rightmost name split on ``_``.
_SEARCH_EXEMPT_RECEIVER_TOKENS = {"re", "regex", "pattern", "pat", "rx",
                                  "matcher"}


def _regex_receiver(d: str) -> bool:
    """True when the dotted receiver of a ``.search`` call reads as a
    compiled regex (``pat.search`` / ``self._tip_pattern.search``)."""
    parts = d.split(".")
    if len(parts) < 2:
        return False
    tokens = set(parts[-2].lower().split("_"))
    return bool(tokens & _SEARCH_EXEMPT_RECEIVER_TOKENS)

#: Inner-callee names whose factory-call-then-call shape
#: (``self._fn(k)(...)``) dispatches a built device program.
DEVICE_FACTORIES = {"_fn", "_searcher", "jit", "pjit", "compile"}
_FACTORY_PREFIXES = ("make_",)

#: Dotted prefixes that construct device arrays.
_DEVICE_MODULE_PREFIXES = ("jnp.", "jax.numpy.")

#: The sanctioned materialization seam: the call is allowed AND its
#: result is host-origin (provenance laundered).
SANCTIONED_SYNC_FUNCS = {"replicated_host_value", "replicated_host_values"}

#: np-namespace converters that force a D2H copy of a device argument.
_NP_SYNC_DOTTED = {"np.asarray", "np.array", "numpy.asarray",
                   "numpy.array"}
_NP_SYNC_BARE = {"asarray"}          # from-import form; bare array() is
#                                      too generic a name to claim

#: Builtin conversions that force a device scalar to host.
_BUILTIN_SYNCS = {"int", "float", "bool"}

#: Method calls that sync/transfer their receiver.
_SYNC_METHODS = {"item", "tolist", "copy_to_host", "__array__"}


def _is_device_producer(node: ast.Call) -> bool:
    name = call_name(node)
    d = dotted(node.func)
    if name in DEVICE_PRODUCING_CALLS:
        if not any(d.startswith(p) for p in _SEARCH_EXEMPT_PREFIXES) \
                and not _regex_receiver(d):
            return True
    if any(d.startswith(p) for p in _DEVICE_MODULE_PREFIXES):
        return True
    if isinstance(node.func, ast.Call):
        inner = call_name(node.func)
        if inner in DEVICE_FACTORIES or \
                any(inner.startswith(p) for p in _FACTORY_PREFIXES):
            return True
    return False


class _Provenance:
    """One function's flow-sensitive taint walk (statement order, loop
    bodies twice for loop-carried taint, nested defs inline with
    ``nonlocal`` writeback)."""

    def __init__(self, rel: str, chain: str, sink: set):
        self.rel = rel
        self.chain = chain
        self.sink = sink          # {(line, rule, detail)} — dedup across
        #                           the two passes and shared scopes

    # -- findings ----------------------------------------------------------

    def _flag(self, node: ast.AST, rule: str, detail: str) -> None:
        self.sink.add((self.rel, node.lineno, rule, detail, self.chain))

    # -- expression taint (side effect: sync-site detection) ---------------

    def taint(self, e: ast.expr | None, env: set[str]) -> bool:
        if e is None or isinstance(e, ast.Constant):
            return False
        if isinstance(e, ast.Name):
            return e.id in env
        if isinstance(e, ast.Attribute):
            # Attribute access LAUNDERS: the backend contract's
            # SearchResult fields are materialized host values (known
            # limit — see module docstring). Still visit the receiver
            # so sync sites inside it are seen.
            self.taint(e.value, env)
            return False
        if isinstance(e, ast.Subscript):
            t = self.taint(e.value, env)
            self.taint(e.slice, env)
            return t
        if isinstance(e, ast.BinOp):
            lt = self.taint(e.left, env)
            rt = self.taint(e.right, env)
            return lt or rt
        if isinstance(e, ast.UnaryOp):
            return self.taint(e.operand, env)
        if isinstance(e, ast.BoolOp):
            return any([self.taint(v, env) for v in e.values])
        if isinstance(e, ast.Compare):
            parts = [self.taint(e.left, env)] + \
                [self.taint(c, env) for c in e.comparators]
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                # Identity checks (`res is None`) compare object
                # identity on the host — they never materialize a
                # device value, so they are not a sync and branching
                # on them is safe.
                return False
            return any(parts)
        if isinstance(e, ast.IfExp):
            if self.taint(e.test, env):
                self._flag(e.test, "SYNC002", "ternary test")
            bt = self.taint(e.body, env)
            ot = self.taint(e.orelse, env)
            return bt or ot
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any([self.taint(x, env) for x in e.elts])
        if isinstance(e, ast.Dict):
            return any([self.taint(v, env)
                        for v in list(e.keys) + list(e.values)
                        if v is not None])
        if isinstance(e, ast.Starred):
            return self.taint(e.value, env)
        if isinstance(e, ast.JoinedStr):
            for v in e.values:
                if isinstance(v, ast.FormattedValue) and \
                        self.taint(v.value, env):
                    self._flag(v, "SYNC001",
                               "device value formatted into a string "
                               "(forces materialization)")
            return False
        if isinstance(e, ast.Lambda):
            # Evaluate the body for sync sites with the current env;
            # the lambda's own params are unknown (untainted).
            inner = env - {a.arg for a in e.args.args}
            self.taint(e.body, inner)
            return False
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                          ast.DictComp)):
            inner = set(env)
            for gen in e.generators:
                it_t = self.taint(gen.iter, inner)
                if it_t:
                    self._flag(gen.iter, "SYNC002",
                               "device array driving Python iteration")
                for n in ast.walk(gen.target):
                    if isinstance(n, ast.Name):
                        inner.add(n.id) if it_t else inner.discard(n.id)
                for cond in gen.ifs:
                    if self.taint(cond, inner):
                        self._flag(cond, "SYNC002", "comprehension filter")
            if isinstance(e, ast.DictComp):
                kt = self.taint(e.key, inner)
                vt = self.taint(e.value, inner)
                return kt or vt
            return self.taint(e.elt, inner)
        if isinstance(e, ast.Call):
            return self._call(e, env)
        # Structural fallback: any tainted child expression taints.
        return any([self.taint(c, env) for c in ast.iter_child_nodes(e)
                    if isinstance(c, ast.expr)])

    def _call(self, node: ast.Call, env: set[str]) -> bool:
        name = call_name(node)
        d = dotted(node.func)
        arg_taints = [self.taint(a, env) for a in node.args] + \
            [self.taint(k.value, env) for k in node.keywords]
        any_tainted = any(arg_taints)
        # The sanctioned seam: allowed, and the result is host-origin.
        if name in SANCTIONED_SYNC_FUNCS:
            return False
        # Explicit sync method: always a pipeline stall on the hot path.
        if isinstance(node.func, ast.Attribute) and \
                name == "block_until_ready":
            self.taint(node.func.value, env)
            self._flag(node, "SYNC001", ".block_until_ready()")
            return False
        recv_tainted = (isinstance(node.func, ast.Attribute)
                        and self.taint(node.func.value, env))
        if isinstance(node.func, ast.Attribute) and name in _SYNC_METHODS \
                and recv_tainted:
            self._flag(node, "SYNC001", f".{name}()")
            return False
        if isinstance(node.func, ast.Name) and name in _BUILTIN_SYNCS \
                and any_tainted:
            self._flag(node, "SYNC001", f"{name}()")
            return False
        if (d in _NP_SYNC_DOTTED
                or (isinstance(node.func, ast.Name)
                    and name in _NP_SYNC_BARE)) and any_tainted:
            self._flag(node, "SYNC001", d or name)
            return False
        if name == "device_get" and any_tainted:
            self._flag(node, "SYNC001", d or name)
            return False
        if _is_device_producer(node):
            if isinstance(node.func, ast.Call):
                self._call(node.func, env)
            return True
        # Unknown call: conservative propagation — a device value
        # threaded through a helper stays device until the seam.
        return any_tainted or recv_tainted


    # -- statements --------------------------------------------------------

    def _bind(self, target: ast.expr, tainted: bool, env: set[str]) -> None:
        if isinstance(target, ast.Name):
            env.add(target.id) if tainted else env.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, tainted, env)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tainted, env)
        else:
            # self.attr / x[i] targets: visit for sync sites only.
            self.taint(target, env)

    def exec_block(self, stmts: list[ast.stmt], env: set[str]) -> None:
        for s in stmts:
            self._stmt(s, env)

    def _stmt(self, s: ast.stmt, env: set[str]) -> None:
        if isinstance(s, ast.Assign):
            t = self.taint(s.value, env)
            for target in s.targets:
                self._bind(target, t, env)
        elif isinstance(s, ast.AnnAssign):
            t = self.taint(s.value, env) if s.value is not None else False
            self._bind(s.target, t, env)
        elif isinstance(s, ast.AugAssign):
            t = self.taint(s.value, env) or \
                (isinstance(s.target, ast.Name) and s.target.id in env)
            self._bind(s.target, t, env)
        elif isinstance(s, ast.If):
            if self.taint(s.test, env):
                self._flag(s.test, "SYNC002", "if test")
            then_env, else_env = set(env), set(env)
            self.exec_block(s.body, then_env)
            self.exec_block(s.orelse, else_env)
            env.clear()
            env.update(then_env | else_env)
        elif isinstance(s, ast.While):
            if self.taint(s.test, env):
                self._flag(s.test, "SYNC002", "while test")
            for _ in range(2):          # loop-carried taint
                self.exec_block(s.body, env)
                if self.taint(s.test, env):
                    self._flag(s.test, "SYNC002", "while test")
            self.exec_block(s.orelse, env)
        elif isinstance(s, ast.For):
            it = self.taint(s.iter, env)
            if it:
                self._flag(s.iter, "SYNC002",
                           "device array driving Python iteration")
            self._bind(s.target, it, env)
            for _ in range(2):          # loop-carried taint
                self.exec_block(s.body, env)
            self.exec_block(s.orelse, env)
        elif isinstance(s, ast.With):
            for item in s.items:
                self.taint(item.context_expr, env)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, False, env)
            self.exec_block(s.body, env)
        elif isinstance(s, ast.Try):
            self.exec_block(s.body, env)
            for h in s.handlers:
                self.exec_block(h.body, env)
            self.exec_block(s.orelse, env)
            self.exec_block(s.finalbody, env)
        elif isinstance(s, ast.Assert):
            if self.taint(s.test, env):
                self._flag(s.test, "SYNC002", "assert test")
        elif isinstance(s, (ast.Return, ast.Expr)):
            self.taint(getattr(s, "value", None), env)
        elif isinstance(s, ast.Raise):
            self.taint(s.exc, env)
            self.taint(s.cause, env)
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    env.discard(t.id)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Closure/thread-body idiom: the nested body runs with a
            # copy of the enclosing taint; names it declares nonlocal
            # and taints flow BACK (the `nonlocal res; res =
            # backend.search(...)` shape the fused dispatcher uses).
            nonlocals: set[str] = set()
            for n in ast.walk(s):
                if isinstance(n, ast.Nonlocal):
                    nonlocals.update(n.names)
            params = {a.arg for a in s.args.args + s.args.posonlyargs
                      + s.args.kwonlyargs}
            inner = set(env) - params
            self.exec_block(s.body, inner)
            for name in nonlocals:
                if name in inner:
                    env.add(name)
        else:
            for e in ast.iter_child_nodes(s):
                if isinstance(e, ast.expr):
                    self.taint(e, env)


def _scoped_files(root: pathlib.Path) -> list[pathlib.Path]:
    return package_scope(
        root, subdirs=("models", "backend", "parallel", "utils"),
        extras=("config.py", "resilience/dispatch.py",
                "resilience/elastic.py"),
        core_glob=True)


def _pruned(info: FuncInfo) -> bool:
    mod = info.module.replace("\\", "/")
    if any(mod.startswith(seam) for seam in SANCTIONED_SEAMS):
        return True
    # The materialization seam's own body IS the sanctioned sync.
    return info.name in SANCTIONED_SYNC_FUNCS


_MESSAGES = {
    "SYNC001": ("implicit host sync '{detail}' on a device-origin value, "
                "reachable on the dispatch hot path via {chain} — the "
                "host stalls until the device drains, serializing the "
                "sweep pipeline (ROADMAP item 1); materialize through "
                "replicated_host_value(s) at the sanctioned seam, or "
                "move the touch off the critical path "
                "(docs/static_analysis.md §SYNC)"),
    "SYNC002": ("device-origin value escapes into Python control flow "
                "({detail}) via {chain} — branching forces a blocking "
                "sync and, when shapes/dtypes vary, is the "
                "retrace-churn trigger; keep the decision on-device "
                "(lax.cond/while_loop) or branch on a value "
                "materialized at the sanctioned seam "
                "(docs/static_analysis.md §SYNC)"),
}


def run_sync_lint(root: pathlib.Path, overrides=None,
                  notes=None) -> list[Finding]:
    files = override_files(overrides, "sync_files",
                           lambda: _scoped_files(root))
    graph, errors = CallGraph.from_files(root, files)
    findings: list[Finding] = [
        Finding(rel, lineno, "SYNC000", f"syntax error: {msg}")
        for rel, lineno, msg in errors]

    anchor = (rel_path(files[0], root) if files
              else "mpi_blockchain_tpu")
    roots, missing = graph.resolve_roots(ENTRY_POINTS)
    for cls, method in missing:
        findings.append(Finding(
            anchor, 1, "SYNC003",
            f"hot-path entry point {cls}.{method} not found in the "
            f"analyzed file set — the device-sync lint is checking "
            f"nothing for it; update ENTRY_POINTS in "
            f"analysis/hotpath_lint.py (the shared root set) alongside "
            f"the rename"))

    chains = graph.reachable(roots, prune=_pruned)
    parents = graph.nested_parents()

    def covered_inline(qual: str) -> bool:
        # A nested def is analyzed inline by its enclosing function —
        # but only when SOME ancestor is itself reachable; a reachable
        # closure in unreachable setup code still needs its own walk.
        p = parents.get(qual)
        while p is not None:
            if p in chains:
                return True
            p = parents.get(p)
        return False

    sink: set = set()
    for qual in sorted(chains):
        if covered_inline(qual):
            continue
        info = graph.functions[qual]
        walker = _Provenance(info.module, " -> ".join(chains[qual]), sink)
        env: set[str] = set()
        # Two passes over the body: taint discovered late in pass 1
        # (a loop-carried or closure-written name) is live from the
        # top in pass 2; the sink set dedups the findings.
        for _ in range(2):
            walker.exec_block(info.node.body, env)
    for rel, lineno, rule, detail, chain in sorted(sink):
        findings.append(Finding(
            rel, lineno, rule,
            _MESSAGES[rule].format(detail=detail, chain=chain)))
    return findings
