"""SAN rules — the C++ sanitizer/analyzer matrix, surfaced through the CLI.

The dynamic half of the matrix (building and running the tsan/asan/ubsan
sanity driver) lives in tests/test_sanitizers.py; this pass checks that the
matrix EXISTS and wires the pure-static C++ analyzers in:

  SAN001  core Makefile is missing a sanitizer flavor target
  SAN002  core Makefile is missing the `analyze` target
  SAN003  cppcheck reported an issue in core/src (one finding per report)
  SAN004  clang-tidy reported a warning/error in core/src

cppcheck/clang-tidy run only when installed — a missing tool is a note,
never a finding, so the CLI stays green on minimal images.
"""
from __future__ import annotations

import pathlib
import re
import shutil
import subprocess

from . import Finding, rel_path

FLAVORS = ("tsan", "asan", "ubsan")


def _rel(path: pathlib.Path, root: pathlib.Path) -> str:
    return rel_path(path, root)


def _check_makefile(findings, makefile: pathlib.Path, rel: str):
    if not makefile.exists():
        findings.append(Finding(rel, 1, "SAN001",
                                "core Makefile not found"))
        return
    text = makefile.read_text(errors="replace")
    for flavor in FLAVORS:
        if not re.search(rf"(?m)^sanity_{flavor}\s*:", text):
            findings.append(Finding(
                rel, 1, "SAN001",
                f"Makefile has no sanity_{flavor} target — the sanitizer "
                f"matrix must cover {'/'.join(FLAVORS)}"))
    if not re.search(r"(?m)^analyze\s*:", text):
        findings.append(Finding(
            rel, 1, "SAN002",
            "Makefile has no `analyze` target (cppcheck/clang-tidy entry "
            "point)"))


def _run_cppcheck(findings, src: pathlib.Path, root: pathlib.Path,
                  notes):
    if shutil.which("cppcheck") is None:
        if notes is not None:
            notes.append("sanitizers: cppcheck not installed; SAN003 "
                         "skipped")
        return
    try:
        proc = subprocess.run(
            ["cppcheck", "--std=c++17", "--enable=warning,portability",
             "--inline-suppr", "--quiet",
             "--template={file}:{line}:{id}:{message}", str(src)],
            capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        findings.append(Finding(
            _rel(src, root), 1, "SAN003",
            "cppcheck timed out after 600s — treat the hang as a finding"))
        return
    parsed = False
    for line in proc.stderr.splitlines():
        m = re.match(r"(.+?):(\d+):([\w-]+):(.*)", line.strip())
        if m:
            parsed = True
            findings.append(Finding(
                _rel(pathlib.Path(m.group(1)), root), int(m.group(2)),
                "SAN003", f"cppcheck[{m.group(3)}] {m.group(4).strip()}"))
    if proc.returncode != 0 and not parsed:
        # Tool crash / usage error must not read as a clean pass.
        findings.append(Finding(
            _rel(src, root), 1, "SAN003",
            f"cppcheck failed (rc={proc.returncode}) with no parsable "
            f"report: {proc.stderr.strip()[-300:]}"))


def _run_clang_tidy(findings, src: pathlib.Path, root: pathlib.Path,
                    notes):
    if shutil.which("clang-tidy") is None:
        if notes is not None:
            notes.append("sanitizers: clang-tidy not installed; SAN004 "
                         "skipped")
        return
    # pybind_module.cpp needs the Python + vendored pybind11 include dirs
    # that core/build.py probes at build time; without them clang-tidy
    # reports a spurious file-not-found error on a pristine tree, so that
    # TU is analyzed by the real build + cppcheck only.
    sources = sorted(p for p in src.glob("*.cpp")
                     if p.name != "pybind_module.cpp")
    try:
        proc = subprocess.run(
            ["clang-tidy", *map(str, sources), "--quiet", "--",
             "-std=c++17", f"-I{src}"],
            capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        findings.append(Finding(
            _rel(src, root), 1, "SAN004",
            "clang-tidy timed out after 600s — treat the hang as a "
            "finding"))
        return
    parsed = False
    for line in (proc.stdout + "\n" + proc.stderr).splitlines():
        m = re.match(r"(.+?):(\d+):\d+:\s+(warning|error):\s+(.*)",
                     line.strip())
        if m:
            parsed = True
            findings.append(Finding(
                _rel(pathlib.Path(m.group(1)), root), int(m.group(2)),
                "SAN004", f"clang-tidy {m.group(3)}: {m.group(4)}"))
    if proc.returncode != 0 and not parsed:
        findings.append(Finding(
            _rel(src, root), 1, "SAN004",
            f"clang-tidy failed (rc={proc.returncode}) with no parsable "
            f"report: {(proc.stderr or proc.stdout).strip()[-300:]}"))


def run_sanitizers(root: pathlib.Path, overrides=None,
                   notes=None) -> list[Finding]:
    overrides = overrides or {}
    core = root / "mpi_blockchain_tpu" / "core"
    makefile = overrides.get("core_makefile", core / "Makefile")
    src = overrides.get("core_src", core / "src")

    findings: list[Finding] = []
    _check_makefile(findings, makefile, _rel(makefile, root))
    if src.is_dir():
        _run_cppcheck(findings, src, root, notes)
        _run_clang_tidy(findings, src, root, notes)
    return findings
