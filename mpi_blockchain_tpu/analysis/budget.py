"""Shared committed-ratchet plumbing for the budget families.

Four ratchets ride the same contract — OPBUDGET (OPB, per-nonce ALU
ops), TRANSFERBUDGET (TRB, host<->device transfer sites), WAITBUDGET
(TBW, blocking-wait sites) and SHARDBUDGET (SBD, collective call
sites): a JSON object committed at the repo root, a stdlib-only gate
pass that recomputes a deterministic static census and fails on growth,
a ``--rebaseline-*`` CLI that refuses to move the budget UP, and one
sanctioned mover (which may import jax) that fully rewrites the file.
This module holds the load/validate/refusal/serialize mechanics so the
contract cannot drift between families; everything with a per-family
voice — the rule codes (OPB002 vs TBW002 vs ...), the census itself,
and any extra required keys — stays in the family module.

Byte-level invariants the helpers pin:

* baselines serialize as ``json.dumps(data, indent=1, sort_keys=True)``
  plus a trailing newline, so a mover re-run on an unchanged tree is
  byte-identical (the ``*budget-check`` make targets assert this);
* a rebaseline refusal is a ``ValueError`` starting with
  ``refusing to rebaseline upward:`` and an amend of a missing/corrupt
  baseline is a ``ValueError`` starting with
  ``no valid baseline to amend`` — the CLI (and the tests) match on
  those prefixes.
"""
from __future__ import annotations

import json
import pathlib


def read_json_object(path: pathlib.Path) -> tuple[dict | None, str]:
    """(object, error message) — object None iff the file is missing,
    unparseable, or not a JSON object. The error text names only the
    basename: baselines are committed at the repo root and findings
    must not leak absolute paths."""
    try:
        data = json.loads(path.read_text())
    except OSError as e:
        return None, f"cannot read {path.name}: {e}"
    except ValueError as e:
        return None, f"{path.name} is not valid JSON: {e}"
    if not isinstance(data, dict):
        return None, f"{path.name} must hold a JSON object"
    return data, ""


def int_key_error(data: dict, baseline_name: str, key: str,
                  mover: str, *, positive: bool = False) -> str:
    """The validation error for a missing/non-integer budget key, or
    "" when the key holds a well-formed count. ``bool`` is rejected
    explicitly (it subclasses int and ``true`` in a hand-edited
    baseline must not arm the gate)."""
    v = data.get(key)
    ok = isinstance(v, int) and not isinstance(v, bool) and (
        v > 0 if positive else v >= 0)
    if ok:
        return ""
    kind = "positive" if positive else "non-negative"
    return (f"{baseline_name} lacks a {kind} integer {key!r} — "
            f"regenerate it with `{mover}`")


def require_amendable(old_data: dict | None, err: str,
                      mover: str) -> dict:
    """The rebaseline precondition: a valid committed baseline.
    Bootstrapping (and any justified raise) is the sanctioned mover's
    job — writing a fresh baseline here would just disarm the gate's
    traced/required sections on the next run."""
    if old_data is None:
        raise ValueError(
            f"no valid baseline to amend ({err}); bootstrap the budget "
            f"with `{mover}`")
    return old_data


def refuse_upward(current: int, old: int, *, census_label: str,
                  policy: str, mover: str, baseline_name: str) -> None:
    """The ratchet itself: raises ValueError when the fresh census
    exceeds the committed budget. ``policy`` is the family's one-line
    rationale ("Transfers only ratchet down", ...)."""
    if current > old:
        raise ValueError(
            f"refusing to rebaseline upward: {census_label} {current} "
            f"> committed budget {old}. {policy}; a justified increase "
            f"must go through `{mover}` and a reviewed "
            f"{baseline_name} diff")


def write_json_budget(path: pathlib.Path, data: dict) -> None:
    """The one sanctioned serialization (see module docstring)."""
    path.write_text(json.dumps(data, indent=1, sort_keys=True) + "\n")


def mover_main(argv, *, prog: str, description: str, write_help: str,
               label: str, writer) -> int:
    """The shared ``--write`` mover CLI: parses ``--write``/``--root``,
    calls ``writer(root)`` and reports ``{label}: wrote {path}`` (rc 0)
    or ``{label}: {error}`` (rc 2) on stderr."""
    import argparse
    import sys

    parser = argparse.ArgumentParser(prog=prog, description=description)
    parser.add_argument("--write", action="store_true", help=write_help)
    parser.add_argument("--root", type=pathlib.Path, default=None)
    args = parser.parse_args(argv)
    if not args.write:
        parser.error("nothing to do: pass --write")
    try:
        path = writer(args.root)
    except (ValueError, OSError) as e:
        print(f"{label}: {e}", file=sys.stderr)
        return 2
    print(f"{label}: wrote {path}", file=sys.stderr)
    return 0
