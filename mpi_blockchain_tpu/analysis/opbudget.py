"""OPB rules — the jaxpr op-budget ratchet for the sweep kernel.

At 95.6% VPU utilization the only per-chip speed axis left is doing
FEWER ops per nonce (ROADMAP item 2; AsicBoost, arxiv 1604.00575). The
roofline experiment traces the production tile and counts jaxpr ALU
primitives — 6055 u32 ops/nonce as of the round-4 kernel — but nothing
stopped a refactor from silently re-inflating that count. This pass is
the gate: a committed baseline (``OPBUDGET.json``, written by
``python experiments/roofline.py --write-budget``) pins both the traced
jaxpr census and a *static* ALU census that this stdlib-only pass can
recompute on every run, and the build fails when the static census
grows.

The static census is a weighted AST op count of the kernel's tile path
(``_tile_result`` in ``ops/sha256_pallas.py`` and everything it calls
module-locally): arithmetic/bitwise/compare operators count 1 each,
literal-``range`` loops multiply their body by the trip count (the SHA
rounds), per-iteration conditionals (``if r + 16 < 64``) are evaluated
concretely per trip, and a call to the kernels' variadic folded-sum
helper ``_usum(*terms)`` costs ``len(terms) - 1`` adds (its runtime
loops would otherwise hide every add it emits from the proxy). It is a
deterministic *proxy*, not the jaxpr count — any edit that adds vector
ops raises it, which is all a ratchet needs; the traced census in the
baseline stays the physically-meaningful number.

Since the extended-midstate refactor (ISSUE 15) the nonce-invariant
per-template precompute lives in ``ops/sha256_sched.py``
(``extend_midstate``); its census is recorded SEPARATELY
(``static_host_alu_ops`` in the baseline) so hoisting work out of the
tile registers as a per-nonce DECREASE rather than moved-ops noise. The
host census is informational (per-template work amortizes over the
whole sweep) — only the per-nonce census is ratcheted.

  OPB001  the static ALU census of the kernel source exceeds the
          committed budget — op-count work may only ratchet DOWN. If
          the increase is justified, re-trace with
          ``python experiments/roofline.py --write-budget`` and commit
          the new OPBUDGET.json (its diff is the review surface); the
          CLI's ``--rebaseline`` only accepts a LOWER census.
  OPB002  OPBUDGET.json is missing, unparseable, or lacks the required
          keys — the ratchet gate is not armed.
  OPB003  a census entry function is missing from its source (a rename
          left the gate counting nothing) — fired for the kernel entry
          always, and for the host entry when the baseline carries a
          host census.

Override keys: ``opbudget_json`` (baseline path), ``kernel_src``
(kernel source path), ``host_src`` (per-template precompute source) —
the drift-fixture seams.
"""
from __future__ import annotations

import ast
import pathlib

from . import Finding, rel_path
from .budget import (int_key_error, read_json_object, refuse_upward,
                     require_amendable, write_json_budget)

BASELINE_NAME = "OPBUDGET.json"
MOVER = "python experiments/roofline.py --write-budget"
KERNEL_SRC = "mpi_blockchain_tpu/ops/sha256_pallas.py"
CENSUS_ENTRY = "_tile_result"
HOST_SRC = "mpi_blockchain_tpu/ops/sha256_sched.py"
HOST_ENTRY = "extend_midstate"
REQUIRED_KEYS = ("alu_ops_per_nonce", "static_alu_ops")
#: The kernels' variadic folded-sum helper: a call costs len(args) - 1
#: adds (see module docstring).
_FOLDED_SUM_FNS = ("_usum",)

#: Operators that occupy an ALU slot (the ratchet counts these).
_ALU_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod,
            ast.Pow, ast.LShift, ast.RShift, ast.BitAnd, ast.BitOr,
            ast.BitXor)
_UNROLL_CAP = 4096   # literal-range trip counts beyond this count once


class _StaticCensus:
    """Weighted AST ALU-op counter with literal-range unrolling."""

    def __init__(self, tree: ast.Module):
        self.funcs: dict[str, ast.FunctionDef] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs.setdefault(node.name, node)
        self._memo: dict[str, int] = {}
        self._stack: set[str] = set()

    # ---- constant mini-evaluator (loop vars + literals) ------------------

    def _eval(self, e: ast.expr, env: dict):
        """int/bool value, or None when not statically known."""
        if isinstance(e, ast.Constant) and isinstance(
                e.value, (int, bool)):
            return e.value
        if isinstance(e, ast.Name):
            return env.get(e.id)
        if isinstance(e, ast.UnaryOp):
            v = self._eval(e.operand, env)
            if v is None:
                return None
            if isinstance(e.op, ast.USub):
                return -v
            if isinstance(e.op, ast.Not):
                return not v
            if isinstance(e.op, ast.Invert):
                return ~v
            return None
        if isinstance(e, ast.BinOp):
            lo, hi = self._eval(e.left, env), self._eval(e.right, env)
            if lo is None or hi is None:
                return None
            try:
                return {
                    ast.Add: lambda: lo + hi, ast.Sub: lambda: lo - hi,
                    ast.Mult: lambda: lo * hi,
                    ast.FloorDiv: lambda: lo // hi,
                    ast.Mod: lambda: lo % hi,
                    ast.LShift: lambda: lo << hi,
                    ast.RShift: lambda: lo >> hi,
                    ast.BitAnd: lambda: lo & hi,
                    ast.BitOr: lambda: lo | hi,
                    ast.BitXor: lambda: lo ^ hi,
                }[type(e.op)]()
            except (KeyError, ZeroDivisionError, ValueError):
                return None
        if isinstance(e, ast.Compare) and len(e.ops) == 1:
            lo = self._eval(e.left, env)
            hi = self._eval(e.comparators[0], env)
            if lo is None or hi is None:
                return None
            op = e.ops[0]
            table = {ast.Lt: lambda: lo < hi, ast.LtE: lambda: lo <= hi,
                     ast.Gt: lambda: lo > hi, ast.GtE: lambda: lo >= hi,
                     ast.Eq: lambda: lo == hi,
                     ast.NotEq: lambda: lo != hi}
            fn = table.get(type(op))
            return fn() if fn else None
        return None

    def _range_values(self, it: ast.expr, env: dict) -> list[int] | None:
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords
                and 1 <= len(it.args) <= 3):
            return None
        vals = [self._eval(a, env) for a in it.args]
        if any(v is None for v in vals):
            return None
        try:
            values = list(range(*vals))
        except (TypeError, ValueError):
            return None
        return values if len(values) <= _UNROLL_CAP else None

    # ---- costs -----------------------------------------------------------

    def func_cost(self, name: str) -> int | None:
        if name in self._memo:
            return self._memo[name]
        fn = self.funcs.get(name)
        if fn is None or name in self._stack:
            return None
        self._stack.add(name)
        cost = self._block(fn.body, {})
        self._stack.discard(name)
        self._memo[name] = cost
        return cost

    def _block(self, stmts: list[ast.stmt], env: dict) -> int:
        return sum(self._stmt(s, env) for s in stmts)

    def _stmt(self, s: ast.stmt, env: dict) -> int:
        if isinstance(s, ast.For):
            values = self._range_values(s.iter, env)
            if values is not None and isinstance(s.target, ast.Name):
                return sum(self._block(
                    s.body, {**env, s.target.id: v}) for v in values)
            return self._expr(s.iter, env) + self._block(s.body, env) \
                + self._block(s.orelse, env)
        if isinstance(s, ast.While):
            return self._expr(s.test, env) + self._block(s.body, env)
        if isinstance(s, ast.If):
            test = self._eval(s.test, env)
            if test is True:
                return self._block(s.body, env)
            if test is False:
                return self._block(s.orelse, env)
            return self._expr(s.test, env) + max(
                self._block(s.body, env), self._block(s.orelse, env))
        if isinstance(s, ast.Assign):
            return self._expr(s.value, env) + sum(
                self._expr(t, env) for t in s.targets)
        if isinstance(s, ast.AugAssign):
            alu = 1 if isinstance(s.op, _ALU_OPS) else 0
            return alu + self._expr(s.value, env) + \
                self._expr(s.target, env)
        if isinstance(s, ast.AnnAssign):
            return self._expr(s.value, env) if s.value else 0
        if isinstance(s, (ast.Return, ast.Expr)):
            return self._expr(s.value, env) if s.value is not None else 0
        if isinstance(s, ast.With):
            return sum(self._expr(i.context_expr, env)
                       for i in s.items) + self._block(s.body, env)
        if isinstance(s, ast.Try):
            return (self._block(s.body, env)
                    + sum(self._block(h.body, env) for h in s.handlers)
                    + self._block(s.orelse, env)
                    + self._block(s.finalbody, env))
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Import, ast.ImportFrom,
                          ast.Pass, ast.Global, ast.Nonlocal)):
            return 0
        # Fallback: cost of any expressions hanging off the statement.
        return sum(self._expr(e, env) for e in ast.iter_child_nodes(s)
                   if isinstance(e, ast.expr))

    def _expr(self, e: ast.expr | None, env: dict) -> int:
        if e is None:
            return 0
        if isinstance(e, ast.BinOp):
            alu = 1 if isinstance(e.op, _ALU_OPS) else 0
            return alu + self._expr(e.left, env) + \
                self._expr(e.right, env)
        if isinstance(e, ast.BoolOp):
            return (len(e.values) - 1) + sum(
                self._expr(v, env) for v in e.values)
        if isinstance(e, ast.Compare):
            return len(e.ops) + self._expr(e.left, env) + sum(
                self._expr(c, env) for c in e.comparators)
        if isinstance(e, ast.UnaryOp):
            alu = 1 if isinstance(e.op, (ast.Invert, ast.USub)) else 0
            return alu + self._expr(e.operand, env)
        if isinstance(e, ast.IfExp):
            test = self._eval(e.test, env)
            if test is True:
                return self._expr(e.body, env)
            if test is False:
                return self._expr(e.orelse, env)
            return self._expr(e.test, env) + max(
                self._expr(e.body, env), self._expr(e.orelse, env))
        if isinstance(e, ast.Call):
            cost = sum(self._expr(a, env) for a in e.args) + sum(
                self._expr(k.value, env) for k in e.keywords)
            if isinstance(e.func, ast.Name):
                if e.func.id in _FOLDED_SUM_FNS:
                    # _usum(*terms) sums its arguments: the runtime loop
                    # inside it is invisible to this walker, so charge
                    # the adds at the call site (conservative: uniform
                    # folding only ever lowers the true vector count).
                    return cost + max(0, len(e.args) - 1)
                inner = self.func_cost(e.func.id)
                if inner is not None:
                    cost += inner
            return cost + self._expr(e.func, env)
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            gens = e.generators
            if len(gens) == 1 and isinstance(gens[0].target, ast.Name) \
                    and not gens[0].ifs:
                values = self._range_values(gens[0].iter, env)
                if values is not None:
                    return sum(self._expr(
                        e.elt, {**env, gens[0].target.id: v})
                        for v in values)
            return self._expr(e.elt, env) + sum(
                self._expr(g.iter, env) for g in gens)
        # Structural nodes: sum over child expressions.
        return sum(self._expr(c, env) for c in ast.iter_child_nodes(e)
                   if isinstance(c, ast.expr))


def static_alu_census(src: pathlib.Path,
                      entry: str = CENSUS_ENTRY) -> int | None:
    """The weighted static ALU op count of the kernel's tile path, or
    None when the entry function is absent. Raises SyntaxError/OSError
    for an unreadable source."""
    tree = ast.parse(src.read_text(), filename=str(src))
    return _StaticCensus(tree).func_cost(entry)


def _paths(root: pathlib.Path, overrides: dict
           ) -> tuple[pathlib.Path, pathlib.Path, pathlib.Path]:
    baseline = pathlib.Path(overrides.get("opbudget_json",
                                          root / BASELINE_NAME))
    src = pathlib.Path(overrides.get("kernel_src", root / KERNEL_SRC))
    host = pathlib.Path(overrides.get("host_src", root / HOST_SRC))
    return baseline, src, host


def _rel(path: pathlib.Path, root: pathlib.Path) -> str:
    return rel_path(path, root)


def load_baseline(baseline: pathlib.Path) -> tuple[dict | None, str]:
    """(budget dict, error message) — dict None iff invalid."""
    data, err = read_json_object(baseline)
    if data is None:
        return None, err
    for key in REQUIRED_KEYS:
        err = int_key_error(data, baseline.name, key, MOVER,
                            positive=True)
        if err:
            return None, err
    return data, ""


def run_opbudget(root: pathlib.Path, overrides=None,
                 notes=None) -> list[Finding]:
    overrides = overrides or {}
    baseline_path, src, host_src = _paths(root, overrides)
    baseline, err = load_baseline(baseline_path)
    if baseline is None:
        return [Finding(_rel(baseline_path, root), 1, "OPB002",
                        f"op-budget ratchet is not armed: {err}")]
    src_rel = _rel(src, root)
    try:
        tree = ast.parse(src.read_text(), filename=str(src))
    except SyntaxError as e:
        return [Finding(src_rel, e.lineno or 1, "OPB000",
                        f"syntax error: {e.msg}")]
    except OSError as e:
        return [Finding(src_rel, 1, "OPB003",
                        f"kernel source unreadable: {e}")]
    census = _StaticCensus(tree)
    entry_fn = census.funcs.get(CENSUS_ENTRY)
    if entry_fn is None:
        return [Finding(src_rel, 1, "OPB003",
                        f"census entry '{CENSUS_ENTRY}' not found in "
                        f"{src.name} — the op-budget gate is counting "
                        f"nothing; update CENSUS_ENTRY in "
                        f"analysis/opbudget.py alongside the rename")]
    findings: list[Finding] = []
    # Host-side per-template precompute: counted separately so a hoist
    # out of the tile is a per-nonce decrease, never moved-ops noise.
    # Informational (amortized per template), but a baseline that CLAIMS
    # a host census while the entry is gone means a rename disarmed it.
    if isinstance(baseline.get("static_host_alu_ops"), int):
        host_rel = _rel(host_src, root)
        host_cost = None
        try:
            host_cost = static_alu_census(host_src, HOST_ENTRY)
        except (OSError, SyntaxError) as e:
            findings.append(Finding(host_rel, 1, "OPB003",
                                    f"host census source unreadable: {e}"))
        else:
            if host_cost is None:
                findings.append(Finding(
                    host_rel, 1, "OPB003",
                    f"host census entry '{HOST_ENTRY}' not found in "
                    f"{host_src.name} but the committed baseline carries "
                    f"static_host_alu_ops — update HOST_ENTRY in "
                    f"analysis/opbudget.py alongside the rename"))
            elif notes is not None and \
                    host_cost != baseline["static_host_alu_ops"]:
                notes.append(
                    f"opbudget: host per-template census {host_cost} "
                    f"differs from the committed "
                    f"{baseline['static_host_alu_ops']} — refresh with "
                    f"roofline.py --write-budget")
    current = census.func_cost(CENSUS_ENTRY) or 0
    budget = baseline["static_alu_ops"]
    if current > budget:
        findings.append(Finding(
            src_rel, entry_fn.lineno, "OPB001",
            f"static ALU op census grew: {current} > budget {budget} "
            f"(committed jaxpr census: "
            f"{baseline['alu_ops_per_nonce']} ALU ops/nonce). The op "
            f"count only ratchets DOWN; if this increase is justified, "
            f"re-trace with `python experiments/roofline.py "
            f"--write-budget` and commit the OPBUDGET.json diff"))
    elif current < budget and notes is not None:
        notes.append(f"opbudget: static census {current} is below the "
                     f"budget {budget} — ratchet it down with "
                     f"--rebaseline (or roofline.py --write-budget)")
    return findings


def rebaseline(root: pathlib.Path,
               overrides=None) -> tuple[int, int, pathlib.Path]:
    """Writes the current static census into the baseline, refusing to
    RAISE it (the ratchet). Returns (old, new, path). Raises ValueError
    when the new census is higher, the source/entry is missing, or
    there is no valid baseline to amend — a missing/corrupt
    OPBUDGET.json must be bootstrapped by ``roofline.py
    --write-budget`` (which traces the jaxpr census too); writing a
    baseline without ``alu_ops_per_nonce`` here would just disarm the
    gate with OPB002 on the next run."""
    overrides = overrides or {}
    baseline_path, src, host_src = _paths(root, overrides)
    current = static_alu_census(src)
    if current is None:
        raise ValueError(f"census entry '{CENSUS_ENTRY}' not found in "
                         f"{src} — nothing to baseline")
    old_data, err = load_baseline(baseline_path)
    old_data = require_amendable(old_data, err, MOVER)
    old = old_data["static_alu_ops"]
    refuse_upward(current, old, census_label="static census",
                  policy="The op budget only ratchets down",
                  mover=MOVER, baseline_name=BASELINE_NAME)
    data = dict(old_data)
    data["static_alu_ops"] = current
    if isinstance(old_data.get("static_host_alu_ops"), int):
        host_cost = static_alu_census(host_src, HOST_ENTRY)
        if host_cost is not None:
            data["static_host_alu_ops"] = host_cost
    data.setdefault("source", KERNEL_SRC)
    data.setdefault("census_entry", CENSUS_ENTRY)
    write_json_budget(baseline_path, data)
    return old, current, baseline_path
