"""CONC rules — thread-escape race detection over the threaded substrate.

PRs 2-7 grew a genuinely multi-threaded host: the meshwatch shard
flusher, the perfwatch HTTP endpoint, bench's GIL-free rank threads, and
the device-init watchdog all run daemon threads beside the miner loop.
The classic drift bug is a future edit mutating state from the main
thread that a daemon thread also mutates — a torn shard seq, a lost
ring record — with no lock, which no test catches until it flakes.

The pass is flow-aware: it finds every thread ENTRY POINT in a module
(``threading.Thread(target=...)``, ``threading.Timer(s, fn)``, executor
``submit``/``map``), takes the module-local call-graph closure of the
targets (the *thread body*), and classifies every mutation of shared
state as thread-side or host-side:

* module-global state — a name assigned at module top level and mutated
  via ``global`` re-assignment, subscript assignment, or a mutating
  method call (``append``/``update``/``pop``/...);
* instance state — ``self.attr`` assignment/augmentation/subscript, or
  a mutating method call on ``self.attr``. Mutations inside
  ``__init__`` are construction, not sharing, and are ignored.

A mutation site is *synchronized* when it sits lexically inside a
``with`` block whose context expression names a lock (``self._lock``,
``_active_lock``, ``rlock``, ``mutex``, ``cond``/``condition`` —
matched per name token, see ``_is_lockish``).
State handed through ``queue.Queue`` never trips the rules (put/get are
not in the mutator set), and the telemetry registry's thread-safe API
(``counter``/``gauge``/``histogram`` calls) is not a tracked mutation
at all — those are exactly the sanctioned alternatives the rules point
at.

  CONC001  state mutated both inside and outside a thread body with NO
           lock at any site — an unsynchronized cross-thread race.
  CONC002  state mutated both inside and outside a thread body where
           SOME sites hold a lock and the flagged one does not —
           inconsistent locking, which is as racy as none.

Known limits (docs/static_analysis.md): module-local analysis (a thread
started in module A mutating module B's state crosses the horizon);
reads are not tracked (a racy read-vs-write pair is invisible); lock
identity is by name, not object (two different locks spelled ``_lock``
look synchronized).

Scope: every ``.py`` in the package plus ``experiments/`` (override key
``conc_files``).
"""
from __future__ import annotations

import ast
import pathlib
import re

from . import Finding, override_files, rel_path, source_cached
from .callgraph import CallGraph, call_name, dotted

#: Method names whose call mutates the receiver in place.
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popleft",
    "appendleft", "clear", "add", "discard", "update", "setdefault",
    "sort", "reverse",
}

#: Executor methods whose first argument runs on a worker thread.
_EXECUTOR_SPAWNS = {"submit", "map"}


def _is_lockish(expr: ast.expr) -> bool:
    """True when a ``with`` context expression names a synchronizer.

    Matched per name TOKEN (split on ``.``/``_``), not by raw substring:
    ``self._lock``, ``_active_lock``, ``rlock``, ``mutex``, ``cond`` /
    ``condition`` all match, while ``deadline_seconds`` must not (its
    'cond' is an accident of 'seconds') and ``trace_block`` /
    ``_begin_block`` must not either (their 'block' ends in 'lock' by
    the same accident)."""
    text = dotted(expr)
    if not text and isinstance(expr, ast.Call):
        text = dotted(expr.func)
    tokens = re.split(r"[._]+", text.lower())
    return any(tok.startswith(("lock", "mutex", "cond"))
               or (tok.endswith(("lock", "mutex"))
                   and not tok.endswith("block"))
               for tok in tokens if tok)


def _module_level_names(tree: ast.Module) -> set[str]:
    names: set[str] = set()
    for stmt in tree.body:
        targets: list[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
    return names


def _thread_targets(tree: ast.Module, graph: CallGraph,
                    owner_of: dict[int, "object"]) -> list:
    """FuncInfos that run on a spawned thread (module-local resolution)."""
    targets = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        exprs: list[ast.expr] = []
        if name == "Thread":
            exprs += [kw.value for kw in node.keywords
                      if kw.arg == "target"]
        elif name == "Timer":
            if len(node.args) >= 2:
                exprs.append(node.args[1])
            exprs += [kw.value for kw in node.keywords
                      if kw.arg == "function"]
        elif name in _EXECUTOR_SPAWNS and node.args:
            # pool.submit(fn, ...) / pool.map(fn, xs): heuristic — any
            # `.submit`/`.map` attribute call; a dict's .map does not
            # exist, and a false resolve only adds benign closure.
            if isinstance(node.func, ast.Attribute):
                exprs.append(node.args[0])
        caller = owner_of.get(id(node))
        for expr in exprs:
            targets.extend(graph.resolve_ref(expr, caller))
    return targets


class _MutationCollector(ast.NodeVisitor):
    """Collects (state key, lineno, locked?) mutations in one function.

    State keys: ("global", name) for module-level state,
    ("attr", cls, name) for instance state.
    """

    def __init__(self, info, module_names: set[str]):
        self.info = info
        self.module_names = module_names
        self.globals_declared: set[str] = set()
        self.sites: list[tuple[tuple, int, bool]] = []
        self._with_depth = 0

    # -- lock scope --------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        lockish = any(_is_lockish(item.context_expr)
                      for item in node.items)
        if lockish:
            self._with_depth += 1
        self.generic_visit(node)
        if lockish:
            self._with_depth -= 1

    def _locked(self) -> bool:
        return self._with_depth > 0

    # -- declarations ------------------------------------------------------

    def visit_Global(self, node: ast.Global) -> None:
        self.globals_declared.update(node.names)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is self.info.node:
            self.generic_visit(node)
        # Nested defs are separate FuncInfos — don't double-count.

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- mutation forms ----------------------------------------------------

    def _key_for_target(self, target: ast.expr) -> tuple | None:
        if isinstance(target, ast.Name):
            if target.id in self.globals_declared:
                return ("global", target.id)
            return None
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name):
            if target.value.id == "self" and self.info.cls is not None:
                return ("attr", self.info.cls, target.attr)
            return None
        if isinstance(target, ast.Subscript):
            return self._key_for_receiver(target.value)
        return None

    def _key_for_receiver(self, recv: ast.expr) -> tuple | None:
        """State key for a mutated RECEIVER (subscript base / method
        owner): a module-level name or a self attribute."""
        if isinstance(recv, ast.Name) and recv.id in self.module_names:
            return ("global", recv.id)
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and \
                recv.value.id == "self" and self.info.cls is not None:
            return ("attr", self.info.cls, recv.attr)
        return None

    def _record(self, key: tuple | None, lineno: int) -> None:
        if key is not None:
            self.sites.append((key, lineno, self._locked()))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record(self._key_for_target(t), node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record(self._key_for_target(node.target), node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record(self._key_for_target(node.target), node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            self._record(self._key_for_receiver(node.func.value),
                         node.lineno)
        self.generic_visit(node)


def _render_key(key: tuple) -> str:
    if key[0] == "global":
        return f"module global '{key[1]}'"
    return f"instance state '{key[1]}.{key[2]}'"


#: Cheap text prefilter: a module with none of these tokens cannot spawn
#: a thread, so the graph/closure work is skipped (keeps the grown pass
#: set inside the make-check time budget).
_SPAWN_TOKENS = ("Thread(", "Timer(", ".submit(", ".map(")


def _scan_module(root: pathlib.Path, path: pathlib.Path) -> list[Finding]:
    rel = rel_path(path, root)
    try:
        text, tree, err = source_cached(path)
    except OSError:
        return []
    if not any(tok in text for tok in _SPAWN_TOKENS):
        return []
    if tree is None:
        return [Finding(rel, err[0], "CONC000",
                        f"syntax error: {err[1]}")]

    graph = CallGraph()
    graph.add_module(rel, tree)
    owners = graph.owner_map(rel)
    targets = _thread_targets(tree, graph, owners)
    if not targets:
        return []
    thread_quals = set(graph.reachable(targets))

    module_names = _module_level_names(tree)
    # key -> list of (qual, lineno, locked, in_thread)
    by_key: dict[tuple, list[tuple[str, int, bool, bool]]] = {}
    for info in graph.functions.values():
        if info.module != rel:
            continue
        if info.name == "__init__":
            continue    # construction precedes sharing
        collector = _MutationCollector(info, module_names)
        collector.visit(info.node)
        in_thread = info.qual in thread_quals
        for key, lineno, locked in collector.sites:
            by_key.setdefault(key, []).append(
                (info.qual, lineno, locked, in_thread))

    findings: list[Finding] = []
    for key, sites in sorted(by_key.items()):
        inside = [s for s in sites if s[3]]
        outside = [s for s in sites if not s[3]]
        if not inside or not outside:
            continue
        any_locked = any(s[2] for s in sites)
        for qual, lineno, locked, in_thread in sites:
            if locked:
                continue
            side = "inside" if in_thread else "outside"
            if not any_locked:
                findings.append(Finding(
                    rel, lineno, "CONC001",
                    f"{_render_key(key)} is mutated both inside and "
                    f"outside a thread body with no lock — this "
                    f"({side}-thread) site races the other side; guard "
                    f"every mutation with one Lock/RLock, hand the data "
                    f"through a queue, or use the telemetry registry's "
                    f"thread-safe API"))
            else:
                findings.append(Finding(
                    rel, lineno, "CONC002",
                    f"{_render_key(key)} is lock-guarded at some sites "
                    f"but this ({side}-thread) mutation is not — "
                    f"inconsistent locking is as racy as none; take the "
                    f"same lock here"))
    return findings


def _scoped_files(root: pathlib.Path) -> list[pathlib.Path]:
    """The threaded-substrate scope (package + experiments/) — the ONE
    copy shared by the conc, lock, future, and thread families."""
    pkg = root / "mpi_blockchain_tpu"
    files = [p for p in pkg.rglob("*.py") if "__pycache__" not in p.parts]
    exp = root / "experiments"
    if exp.is_dir():
        files += [p for p in exp.glob("*.py")]
    return sorted(files)


def run_conc_lint(root: pathlib.Path, overrides=None,
                  notes=None) -> list[Finding]:
    files = override_files(overrides, "conc_files",
                           lambda: _scoped_files(root))
    findings: list[Finding] = []
    for path in files:
        findings.extend(_scan_module(root, path))
    return findings
