"""DON rules — buffer-donation correctness for the device dispatch path.

The async-pipeline refactor (ROADMAP item 1) dispatches sweep N+1 with
DONATED buffers while the host drains sweep N — donation is what makes
the double-buffer handoff zero-copy. Donation bugs are silent on CPU
(XLA quietly copies instead) and catastrophic on TPU: a donated buffer
read after the call returns garbage, and a forgotten donation doubles
HBM pressure exactly where the pipeline needs it least. Nothing dynamic
tests this before a TPU run, so it is linted statically:

  DON001  use-after-donate — a local value passed in a
          ``donate_argnums`` position of a jit'd callable is read again
          after the call (before any rebind). The donated buffer's
          storage belongs to the device after dispatch; the later read
          sees garbage (or, on backends that copy, hides a perf bug
          that detonates on TPU).
  DON002  a sweep-shaped dispatch with no donation declared: a built
          device program (the ``self._fn(k)(...)``/factory-call shape,
          or a module-local jit'd name) whose call THREADS a buffer —
          the same name appears as an argument and as an assignment
          target of the result (``nonces, prev = fn(prev, ...)``).
          That is the double-buffer pipeline shape; the threaded
          buffer must be donated (``donate_argnums``/``donate=...``)
          or the dispatch pays a device-side copy per sweep.
  DON003  donation declared on an argument that aliases live host
          state — an attribute (``self.buf``) or module-global passed
          in a donated position. The host alias outlives the call, and
          any later read through it is DON001 invisible to a
          per-function pass; donate only call-local buffers.

Declarations are tracked module-locally: ``fn = jax.jit(body,
donate_argnums=(0,))``, decorator forms (``@jax.jit(...)`` /
``@functools.partial(jax.jit, donate_argnums=...)``), and
``functools.partial`` nesting. Cross-module declaration/call pairs are
out of scope (the call-graph builder's known limits); DON002's
factory-call shape is the deliberate catch-all for dispatches whose jit
wrapper lives elsewhere — a site that genuinely donates can carry a
``donate``/``donate_argnums`` keyword or a justified inline
suppression.

Scope (override key ``donation_files``): ``models/``, ``backend/``,
``parallel/``, ``resilience/dispatch.py``, ``resilience/elastic.py``.
"""
from __future__ import annotations

import ast
import pathlib

from . import Finding, override_files, package_scope, rel_path
from .callgraph import call_name, dotted
from .sync_lint import DEVICE_FACTORIES, _FACTORY_PREFIXES

_JIT_NAMES = ("jax.jit", "jit", "pjit", "jax.pjit")


def _donate_positions(call: ast.Call) -> set[int] | None:
    """The literal donate_argnums positions of a jit(...) call; an
    EMPTY set when donation is declared but positions are not literal
    ints (donate_argnames, a computed tuple) — still a declaration, so
    DON002 must honor it even though DON001/DON003 cannot resolve the
    positions; None when the call declares no donation at all."""
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                return {e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int)}
            return set()     # non-positional: declared, positions unknown
    return None


def _jit_donations(expr: ast.expr) -> set[int] | None:
    """Donated positions when ``expr`` is a jit wrapper (possibly under
    functools.partial nesting); None when it is not a jit wrapper or
    declares no donation."""
    if not isinstance(expr, ast.Call):
        return None
    d = dotted(expr.func)
    if d in _JIT_NAMES:
        return _donate_positions(expr)
    if d in ("functools.partial", "partial") and expr.args:
        inner = _jit_donations(expr.args[0])
        mine = _donate_positions(expr)
        if inner is None and mine is None:
            return None
        return (inner or set()) | (mine or set())
    return None


def _collect_donated(tree: ast.Module) -> dict[str, set[int]]:
    """{callable name: donated positions} declared module-locally."""
    donated: dict[str, set[int]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            pos = _jit_donations(node.value)
            if pos is not None:
                donated[node.targets[0].id] = pos
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                pos = _jit_donations(deco) if isinstance(deco, ast.Call) \
                    else None
                if pos is not None:
                    donated[node.name] = pos
    return donated


def _name_events(fn: ast.AST, name: str) -> list[tuple[int, bool]]:
    """Sorted (lineno, is_store) events for ``name`` in a function."""
    events: list[tuple[int, bool]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id == name:
            events.append((node.lineno,
                           isinstance(node.ctx, (ast.Store, ast.Del))))
    return sorted(events)


def _is_dispatch_call(node: ast.Call,
                      jit_names: dict[str, set[int]]) -> bool:
    """A call that dispatches a built device program (DON002 subject)."""
    if isinstance(node.func, ast.Call):
        inner = call_name(node.func)
        return inner in DEVICE_FACTORIES or \
            any(inner.startswith(p) for p in _FACTORY_PREFIXES)
    return call_name(node) in jit_names


def _site_declares_donation(node: ast.Call) -> bool:
    keys = {kw.arg for kw in node.keywords}
    if {"donate", "donate_argnums", "donate_argnames"} & keys:
        return True
    if isinstance(node.func, ast.Call):
        inner_keys = {kw.arg for kw in node.func.keywords}
        return bool({"donate", "donate_argnums", "donate_argnames"}
                    & inner_keys)
    return False


class _FnChecker:
    """Per-function DON checks (nested defs are walked with the
    enclosing function — the closure dispatch idiom)."""

    def __init__(self, rel: str, fn: ast.AST,
                 donated: dict[str, set[int]],
                 jit_names: dict[str, set[int]],
                 globals_: set[str], findings: list[Finding]):
        self.rel = rel
        self.fn = fn
        self.donated = donated
        self.jit_names = jit_names
        self.globals_ = globals_
        self.findings = findings

    def check(self) -> None:
        for node in ast.walk(self.fn):
            if isinstance(node, ast.Call):
                self._check_donated_site(node)
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                self._check_threading(node)

    # -- DON001 / DON003 ---------------------------------------------------

    def _check_donated_site(self, node: ast.Call) -> None:
        positions = self.donated.get(call_name(node))
        if not positions or not isinstance(node.func,
                                           (ast.Name, ast.Attribute)):
            return
        for pos in sorted(positions):
            if pos >= len(node.args):
                continue
            arg = node.args[pos]
            if isinstance(arg, ast.Attribute) or (
                    isinstance(arg, ast.Name) and arg.id in self.globals_):
                label = dotted(arg) or call_name(node)
                self.findings.append(Finding(
                    self.rel, arg.lineno, "DON003",
                    f"donated argument {pos} of '{call_name(node)}' is "
                    f"'{label}', which aliases live host state — the "
                    f"alias outlives the dispatch and any later read "
                    f"through it sees a donated (garbage) buffer; "
                    f"donate only call-local buffers, or drop the "
                    f"donation for this argument"))
            elif isinstance(arg, ast.Name):
                self._check_use_after(node, pos, arg)

    def _check_use_after(self, call: ast.Call, pos: int,
                         arg: ast.Name) -> None:
        # The call's whole source extent counts as the call: a multiline
        # argument list must not read as a "later" load of its own arg.
        call_end = getattr(call, "end_lineno", None) or call.lineno
        for lineno, is_store in _name_events(self.fn, arg.id):
            if call.lineno <= lineno <= call_end and is_store:
                return          # `buf = fn(buf, ...)`: rebound from the
                #                 call's own output — the donation idiom
            if lineno <= call_end:
                continue
            if is_store:
                return          # rebound before any later read
            self.findings.append(Finding(
                self.rel, lineno, "DON001",
                f"'{arg.id}' is read here after being donated to "
                f"'{call_name(call)}' on line {call.lineno} "
                f"(donate_argnums position {pos}) — the buffer's "
                f"storage belongs to the device after dispatch and "
                f"this read sees garbage; rebind the name from the "
                f"call's outputs, or drop the donation"))
            return              # one finding per donation site

    # -- DON002 ------------------------------------------------------------

    def _check_threading(self, node: ast.Assign) -> None:
        call = node.value
        if not _is_dispatch_call(call, self.jit_names):
            return
        if _site_declares_donation(call):
            return
        name = call_name(call)
        # Any module-local donation declaration counts — including
        # donate_argnames / computed positions (empty position set).
        if name in self.donated:
            return
        targets: set[str] = set()
        for t in node.targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name) and \
                        isinstance(n.ctx, ast.Store):
                    targets.add(n.id)
        arg_names = {a.id for a in call.args if isinstance(a, ast.Name)}
        threaded = sorted(targets & arg_names)
        if threaded:
            self.findings.append(Finding(
                self.rel, node.lineno, "DON002",
                f"sweep-shaped dispatch threads "
                f"{', '.join(repr(t) for t in threaded)} through the "
                f"device call with no donation declared — the "
                f"double-buffer pipeline shape pays a device-side copy "
                f"per dispatch without donate_argnums; declare the "
                f"donation on the jit wrapper (or a donate= keyword at "
                f"the site), or suppress with a written justification "
                f"(docs/static_analysis.md §DON)"))


def _module_globals(tree: ast.Module) -> set[str]:
    names: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            names.add(node.target.id)
    return names


def _scoped_files(root: pathlib.Path) -> list[pathlib.Path]:
    return package_scope(
        root, subdirs=("models", "backend", "parallel"),
        extras=("resilience/dispatch.py", "resilience/elastic.py"))


def run_donation_lint(root: pathlib.Path, overrides=None,
                      notes=None) -> list[Finding]:
    files = override_files(overrides, "donation_files",
                           lambda: _scoped_files(root))
    findings: list[Finding] = []
    for path in files:
        path = pathlib.Path(path)
        rel = rel_path(path, root)
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            findings.append(Finding(rel, e.lineno or 1, "DON000",
                                    f"syntax error: {e.msg}"))
            continue
        except OSError:
            continue
        donated = _collect_donated(tree)
        jit_names = dict(donated)
        # jit'd names with NO donation also participate in DON002.
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and dotted(node.value.func) in _JIT_NAMES:
                jit_names.setdefault(node.targets[0].id, set())
        globals_ = _module_globals(tree)

        # Outermost functions only: the checker walks each function's
        # whole subtree, so nested defs (dispatch closures) are covered
        # by their enclosing function's walk and never re-visited —
        # visit() stops recursing at the first function boundary.
        def visit(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    _FnChecker(rel, child, donated, jit_names,
                               globals_, findings).check()
                else:
                    visit(child)
        visit(tree)
    return findings
