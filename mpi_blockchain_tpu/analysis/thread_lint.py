"""THR/TBW rules — thread lifecycle + the blocking-wait budget ratchet.

The dynamic half of this story already happened: PR 10's trace-smoke
found a reaping bug where a helper thread outlived its run and wedged
interpreter shutdown. This pass catches that class statically, and adds
the third committed ratchet: a census of every place the concurrent
substrate can BLOCK, pinned in ``WAITBUDGET.json`` so ROADMAP items 2
(8-chip scale-out) and 4 (serving front door) cannot silently accrete
new places to hang.

  THR001  non-daemon thread with no join on any exit path: a
          ``threading.Thread``/``Timer`` constructed without
          ``daemon=True`` (or a later ``t.daemon = True``) whose handle
          is never ``.join()``-ed / ``.cancel()``-ed in the module — it
          outlives the run and wedges interpreter shutdown (the
          trace-smoke reaping bug class, now caught before any run).
  THR002  thread target writing instance/global state with no lock
          while the host side READS it: CONC001/2 require mutation on
          both sides; a thread-side unlocked write racing a host-side
          read is the same torn-value bug and was invisible until now.
          Single-writer designs justify-suppress with the rationale
          inline.
  TBW001  the static blocking-wait census of the sweep-scope sources —
          ``with lock:`` acquires, ``.result()``, ``.get()``,
          ``.join()``, ``.wait()``, ``.acquire()`` — exceeds the
          committed ``WAITBUDGET.json``. Wait sites only ratchet DOWN;
          a justified increase goes through the sanctioned mover
          (``python -m mpi_blockchain_tpu.analysis.thread_lint
          --write``) and a reviewed baseline diff, and the baseline's
          ``sites`` section records WHICH seam sanctions each site, so
          the review surface names the hang budget it is growing.
  TBW002  ``WAITBUDGET.json`` missing, unparseable, or lacking
          ``static_wait_sites``/``sites`` — the ratchet is not armed.
  TBW003  the census scope resolves to no readable source file — the
          gate is counting nothing (update ``WAIT_SCOPE`` alongside a
          refactor).

Census counting rules (deterministic, dtype-free): ``.result(`` always
counts (bounded or not — a bounded wait is still a wait site);
``.get(``/``.join(`` count only with no positional args (excusing
``dict.get(key)`` and ``str.join(seq)``); ``.wait(`` and ``.acquire(``
always count; each lockish ``with`` item counts once (the CONC token
rule). ``--rebaseline-waits`` (the CLI) refuses to move the budget UP.

Scope: THR rules run over the package + ``experiments/`` (override key
``thread_files``); the TBW census runs over ``WAIT_SCOPE`` (override
keys ``wait_files``, ``waitbudget_json``).
"""
from __future__ import annotations

import ast
import pathlib

from . import Finding, override_files, rel_path, source_cached
from .budget import (int_key_error, mover_main, read_json_object,
                     refuse_upward, require_amendable, write_json_budget)
from .callgraph import CallGraph, call_name, dotted
from .conc_lint import (_MutationCollector, _is_lockish,
                        _module_level_names, _scoped_files,
                        _thread_targets)

BASELINE_NAME = "WAITBUDGET.json"
REQUIRED_KEYS = ("static_wait_sites", "sites")
MOVER = "python -m mpi_blockchain_tpu.analysis.thread_lint --write"

#: The concurrent-substrate sources whose blocking-wait sites are
#: budgeted: everything between the mine loop and the device program
#: that can park a thread.
WAIT_SCOPE = (
    "mpi_blockchain_tpu/models/miner.py",
    "mpi_blockchain_tpu/models/fused.py",
    "mpi_blockchain_tpu/backend/__init__.py",
    "mpi_blockchain_tpu/backend/cpu.py",
    "mpi_blockchain_tpu/backend/tpu.py",
    "mpi_blockchain_tpu/parallel/mesh.py",
    "mpi_blockchain_tpu/resilience/dispatch.py",
    "mpi_blockchain_tpu/resilience/elastic.py",
    "mpi_blockchain_tpu/meshwatch/shard.py",
    "mpi_blockchain_tpu/meshwatch/pipeline.py",
    "mpi_blockchain_tpu/perfwatch/server.py",
    "mpi_blockchain_tpu/service/mempool.py",
    "mpi_blockchain_tpu/service/frontdoor.py",
)

#: file -> the seam that sanctions its wait sites, recorded per site in
#: the committed baseline so every budget review names what it grows.
WAIT_SEAMS = {
    "mpi_blockchain_tpu/models/miner.py":
        "pipelined consume (bounded by MPIBT_DISPATCH_TIMEOUT) + "
        "done-callback drain",
    "mpi_blockchain_tpu/resilience/dispatch.py":
        "single-flight dispatch worker (ladder RLock)",
    "mpi_blockchain_tpu/resilience/elastic.py":
        "guarded_collective watchdog (timeout-bounded rendezvous)",
    "mpi_blockchain_tpu/meshwatch/shard.py":
        "daemon shard flusher (interval wait + bounded close join)",
    "mpi_blockchain_tpu/meshwatch/pipeline.py":
        "pipeline profiler ring lock (short critical sections)",
    "mpi_blockchain_tpu/perfwatch/server.py":
        "metrics server lifecycle (bounded close join)",
    "mpi_blockchain_tpu/service/mempool.py":
        "mempool heap/index lock (short critical sections, no IO held)",
    "mpi_blockchain_tpu/service/frontdoor.py":
        "template-feed lock + admission gate (handler-thread critical "
        "sections; retries bounded by the `service` policy leash)",
}
_UNSANCTIONED = "unsanctioned — justify in the WAITBUDGET.json review"

_WAIT_METHODS_ALWAYS = {"result", "wait", "acquire"}
_WAIT_METHODS_BARE = {"get", "join"}      # positional args = not a wait


def _census_label(node: ast.Call) -> str | None:
    name = call_name(node)
    if not isinstance(node.func, ast.Attribute):
        return None
    if name in _WAIT_METHODS_ALWAYS:
        return f".{name}()"
    if name in _WAIT_METHODS_BARE and not node.args:
        return f".{name}()"
    return None


def static_wait_census(
        root: pathlib.Path, files: list[pathlib.Path]
) -> tuple[int, dict[str, int], list[dict],
           list[tuple[str, int, str]]]:
    """(total, per-label counts, per-site records, syntax errors) over
    the scoped files. Site records carry the sanctioning seam."""
    total = 0
    by_label: dict[str, int] = {}
    sites: list[dict] = []
    errors: list[tuple[str, int, str]] = []
    for path in sorted(pathlib.Path(p) for p in files):
        rel = rel_path(path, root)
        seam = WAIT_SEAMS.get(rel.replace("\\", "/"), _UNSANCTIONED)
        try:
            _, tree, err = source_cached(path)
        except OSError:
            continue
        if tree is None:
            errors.append((rel, err[0], err[1]))
            continue
        found: list[tuple[int, str]] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.With):
                for item in node.items:
                    if _is_lockish(item.context_expr):
                        found.append((node.lineno, "with-lock"))
            elif isinstance(node, ast.Call):
                label = _census_label(node)
                if label is not None:
                    found.append((node.lineno, label))
        for lineno, label in sorted(found):
            total += 1
            by_label[label] = by_label.get(label, 0) + 1
            sites.append({"file": rel, "line": lineno, "label": label,
                          "seam": seam})
    return total, by_label, sites, errors


def _paths(root: pathlib.Path, overrides: dict
           ) -> tuple[pathlib.Path, list[pathlib.Path]]:
    baseline = pathlib.Path(overrides.get("waitbudget_json",
                                          root / BASELINE_NAME))
    files = override_files(overrides, "wait_files",
                           lambda: [root / p for p in WAIT_SCOPE])
    return baseline, files


def load_baseline(baseline: pathlib.Path) -> tuple[dict | None, str]:
    """(budget dict, error message) — dict None iff invalid."""
    data, err = read_json_object(baseline)
    if data is None:
        return None, err
    err = int_key_error(data, baseline.name, "static_wait_sites", MOVER)
    if err:
        return None, err
    if not isinstance(data.get("sites"), list):
        return None, (f"{baseline.name} lacks the per-site 'sites' "
                      f"seam record — regenerate it with `{MOVER}`")
    return data, ""


# ---- THR001/THR002 ---------------------------------------------------------

_THREAD_CTORS = {"Thread", "Timer"}
_SPAWN_TOKENS = ("Thread(", "Timer(", ".submit(", ".map(")


def _truthy_const(expr: ast.expr | None) -> bool | None:
    """True/False for a constant; None when not statically known."""
    if expr is None:
        return None
    if isinstance(expr, ast.Constant):
        return bool(expr.value)
    return None


def _target_matches(expr: ast.expr, target: ast.expr) -> bool:
    """Does ``expr`` (a receiver) denote the same handle as the
    constructor's assignment ``target`` (Name or self.attr)?"""
    if isinstance(target, ast.Name):
        return isinstance(expr, ast.Name) and expr.id == target.id
    if isinstance(target, ast.Attribute) and \
            isinstance(target.value, ast.Name):
        return (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == target.value.id
                and expr.attr == target.attr)
    return False


def _thr001(rel: str, tree: ast.Module) -> list[Finding]:
    findings: list[Finding] = []
    assigns: list[tuple[ast.expr | None, ast.Call]] = []
    daemon_sets: list[ast.Assign] = []
    reap_calls: list[ast.Call] = []
    for node in ast.walk(tree):          # one walk collects everything
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                call_name(node.value) in _THREAD_CTORS and \
                len(node.targets) == 1:
            assigns.append((node.targets[0], node.value))
        elif isinstance(node, ast.Assign) and \
                isinstance(node.targets[0], ast.Attribute) and \
                node.targets[0].attr == "daemon" and \
                _truthy_const(node.value):
            daemon_sets.append(node)
        elif isinstance(node, ast.Expr) and \
                isinstance(node.value, ast.Call):
            call = node.value
            # threading.Thread(...).start() — unassigned, unjoinable.
            recv = call.func.value if isinstance(call.func, ast.Attribute) \
                else None
            if isinstance(recv, ast.Call) and \
                    call_name(recv) in _THREAD_CTORS:
                assigns.append((None, recv))
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("join", "cancel"):
            reap_calls.append(node)
    for target, ctor in assigns:
        d = dotted(ctor.func)
        if d and d.split(".")[0] not in ("threading", "Thread", "Timer"):
            continue    # some_module.Thread lookalike: out of scope
        daemon = None
        for kw in ctor.keywords:
            if kw.arg == "daemon":
                daemon = _truthy_const(kw.value)
                if daemon is None:
                    daemon = True    # dynamic: assume daemonish (polarity)
        reaped = False
        if target is not None:
            daemon = daemon or any(
                _target_matches(n.targets[0].value, target)
                for n in daemon_sets)
            reaped = any(_target_matches(n.func.value, target)
                         for n in reap_calls)
        if daemon or reaped:
            continue
        handle = ("it is never bound to a handle" if target is None else
                  "its handle is never .join()-ed or .cancel()-ed in "
                  "this module")
        findings.append(Finding(
            rel, ctor.lineno, "THR001",
            f"non-daemon {call_name(ctor)} and {handle} — it outlives "
            f"the run and wedges interpreter shutdown (the trace-smoke "
            f"reaping bug class); pass daemon=True, or join/cancel it "
            f"on every exit path (docs/static_analysis.md §THR)"))
    return findings


def _lock_held_quals(rel: str, graph: CallGraph) -> set[str]:
    """Quals whose EVERY module-local call site sits lexically inside a
    ``with lock:`` extent (and that have at least one call site) — the
    single-flight-worker idiom: ``search()`` takes the ladder RLock and
    everything it calls (``_step_down``, ``_checked_search``) runs
    lock-held without spelling the ``with`` again. One lexical hop,
    like SPMD004's ``_rendezvous`` rule; deeper indirection is out of
    scope."""
    sites: dict[str, list[bool]] = {}
    for info in graph.functions.values():
        if info.module != rel:
            continue

        def walk(nodes, held: bool, info=info) -> None:
            for child in nodes:
                if isinstance(child,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(child, ast.With):
                    inner = held or any(_is_lockish(i.context_expr)
                                        for i in child.items)
                    walk(child.body, inner)
                    continue
                if isinstance(child, ast.Call):
                    for callee in graph.resolve_call(child, info):
                        if callee.module == rel:
                            sites.setdefault(callee.qual,
                                             []).append(held)
                walk(ast.iter_child_nodes(child), held)

        walk(ast.iter_child_nodes(info.node), False)
    return {qual for qual, flags in sites.items() if flags and all(flags)}


def _thr002(rel: str, tree: ast.Module,
            graph: CallGraph) -> list[Finding]:
    owners = graph.owner_map(rel)
    targets = _thread_targets(tree, graph, owners)
    if not targets:
        return []
    thread_quals = set(graph.reachable(targets))
    module_names = _module_level_names(tree)

    # Thread-side unlocked mutations and host-side mutation keys.
    thread_writes: list[tuple[tuple, int]] = []
    host_mutated: set[tuple] = set()
    host_infos = []
    for info in graph.functions.values():
        if info.module != rel or info.name == "__init__":
            continue
        in_thread = info.qual in thread_quals
        collector = _MutationCollector(info, module_names)
        collector.visit(info.node)
        for key, line, locked in collector.sites:
            if in_thread and not locked:
                thread_writes.append((key, line, info.qual))
            if not in_thread:
                host_mutated.add(key)
        if not in_thread:
            host_infos.append(info)
    if not thread_writes:
        return []
    # Only now pay for the expensive context: functions whose every
    # call site is lock-held (the single-flight idiom), and host-side
    # READS. A read that is part of a host-side MUTATION still keys
    # into host_mutated, which defers the pair to CONC below.
    held_quals = _lock_held_quals(rel, graph)
    thread_writes = [(key, line) for key, line, qual in thread_writes
                     if qual not in held_quals]
    if not thread_writes:
        return []
    host_read: set[tuple] = set()
    for info in host_infos:
        for n in ast.walk(info.node):
            if isinstance(n, ast.Name) and \
                    isinstance(n.ctx, ast.Load) and \
                    n.id in module_names:
                host_read.add(("global", n.id))
            elif isinstance(n, ast.Attribute) and \
                    isinstance(n.ctx, ast.Load) and \
                    isinstance(n.value, ast.Name) and \
                    n.value.id == "self" and info.cls is not None:
                host_read.add(("attr", info.cls, n.attr))
    findings = []
    for key, line in sorted(set(thread_writes)):
        if key in host_mutated:
            continue    # both-sides mutation is CONC001/CONC002's call
        if key not in host_read:
            continue
        name = (f"module global '{key[1]}'" if key[0] == "global"
                else f"instance state '{key[1]}.{key[2]}'")
        findings.append(Finding(
            rel, line, "THR002",
            f"{name} is written by a thread target with no lock while "
            f"the host side reads it — a torn read CONC cannot see "
            f"(it tracks mutation pairs, not read-vs-write); guard the "
            f"write and the read with one lock, or justify the "
            f"single-writer design inline "
            f"(docs/static_analysis.md §THR)"))
    return findings


# ---- the pass --------------------------------------------------------------


def run_thread_lint(root: pathlib.Path, overrides=None,
                    notes=None) -> list[Finding]:
    overrides = overrides or {}
    findings: list[Finding] = []
    for path in override_files(overrides, "thread_files",
                               lambda: _scoped_files(root)):
        path = pathlib.Path(path)
        rel = rel_path(path, root)
        try:
            text, tree, err = source_cached(path)
        except OSError:
            continue
        if not any(tok in text for tok in _SPAWN_TOKENS):
            continue
        if tree is None:
            findings.append(Finding(rel, err[0], "THR000",
                                    f"syntax error: {err[1]}"))
            continue
        graph = CallGraph()
        graph.add_module(rel, tree)
        findings.extend(_thr001(rel, tree))
        findings.extend(_thr002(rel, tree, graph))

    # ---- the TBW ratchet ----------------------------------------------
    baseline_path, files = _paths(root, overrides)
    baseline, err = load_baseline(baseline_path)
    if baseline is None:
        findings.append(Finding(rel_path(baseline_path, root), 1,
                                "TBW002",
                                f"blocking-wait ratchet is not armed: "
                                f"{err}"))
        return findings
    readable = [p for p in files if pathlib.Path(p).is_file()]
    if not readable:
        findings.append(Finding(
            "mpi_blockchain_tpu", 1, "TBW003",
            "blocking-wait census scope resolves to no readable source "
            "file — the gate is counting nothing; update WAIT_SCOPE in "
            "analysis/thread_lint.py alongside the refactor"))
        return findings
    total, by_label, sites, errors = static_wait_census(root, readable)
    findings.extend(Finding(rel, lineno, "TBW000",
                            f"syntax error: {msg}")
                    for rel, lineno, msg in errors)
    budget = baseline["static_wait_sites"]
    if total > budget:
        anchor = (sites[0]["file"], sites[0]["line"]) if sites else (
            rel_path(pathlib.Path(readable[0]), root), 1)
        breakdown = ", ".join(f"{k}×{v}"
                              for k, v in sorted(by_label.items()))
        findings.append(Finding(
            anchor[0], anchor[1], "TBW001",
            f"static blocking-wait census grew: {total} > budget "
            f"{budget} ({breakdown}). Places the sweep scope can hang "
            f"only ratchet DOWN (ROADMAP item 2's 8-chip bring-up "
            f"depends on it); if this increase is justified, re-census "
            f"with `python -m mpi_blockchain_tpu.analysis.thread_lint "
            f"--write` and commit the WAITBUDGET.json diff — the "
            f"baseline's sites section must name the sanctioning seam"))
    elif total < budget and notes is not None:
        notes.append(f"thread_lint: static wait census {total} is below "
                     f"the budget {budget} — ratchet it down with "
                     f"--rebaseline-waits (or the --write mover)")
    return findings


# ---- the ratchet movers ----------------------------------------------------


def rebaseline_waits(root: pathlib.Path,
                     overrides=None) -> tuple[int, int, pathlib.Path]:
    """Writes the current static wait census into the baseline, refusing
    to RAISE it (the ratchet). Returns (old, new, path). Raises
    ValueError when the census is higher, the scope is empty, or there
    is no valid baseline to amend — bootstrapping (and any justified
    raise) is the sanctioned mover's job (``thread_lint --write``)."""
    overrides = overrides or {}
    baseline_path, files = _paths(root, overrides)
    readable = [p for p in files if pathlib.Path(p).is_file()]
    if not readable:
        raise ValueError("wait census scope resolves to no readable "
                         "source file — nothing to baseline")
    total, by_label, sites, errors = static_wait_census(root, readable)
    if errors:
        raise ValueError(f"census scope has syntax errors: {errors[0]}")
    old_data, err = load_baseline(baseline_path)
    old_data = require_amendable(old_data, err, MOVER)
    old = old_data["static_wait_sites"]
    refuse_upward(total, old, census_label="static wait census",
                  policy="Blocking-wait sites only ratchet down",
                  mover=MOVER, baseline_name=BASELINE_NAME)
    data = dict(old_data)
    data["static_wait_sites"] = total
    data["by_label"] = dict(sorted(by_label.items()))
    data["sites"] = sites
    # Same ordering as write_budget (WAIT_SCOPE declaration order), so
    # a ratchet-down never reorders the committed review surface.
    data["scope"] = [rel_path(pathlib.Path(p), root) for p in readable]
    write_json_budget(baseline_path, data)
    return old, total, baseline_path


def write_budget(root: pathlib.Path | None = None,
                 overrides=None) -> pathlib.Path:
    """The one sanctioned mover: full rewrite of WAITBUDGET.json (the
    census may move either way; the committed diff — including the
    per-site seam records — is the review surface)."""
    from . import default_root

    root = root if root is not None else default_root()
    baseline_path, files = _paths(root, overrides or {})
    readable = [p for p in files if pathlib.Path(p).is_file()]
    total, by_label, sites, errors = static_wait_census(root, readable)
    if errors:
        raise ValueError(f"census scope has syntax errors: {errors[0]}")
    data = {
        "static_wait_sites": total,
        "by_label": dict(sorted(by_label.items())),
        "sites": sites,
        "scope": [rel_path(pathlib.Path(p), root) for p in readable],
        "writer": MOVER,
    }
    write_json_budget(baseline_path, data)
    return baseline_path


def main(argv=None) -> int:
    return mover_main(
        argv,
        prog="python -m mpi_blockchain_tpu.analysis.thread_lint",
        description="the sanctioned WAITBUDGET.json mover: re-censuses "
                    "the sweep scope's blocking-wait sites (with their "
                    "sanctioning seams) and rewrites the committed "
                    "budget",
        write_help="re-census and rewrite WAITBUDGET.json",
        label="thread_lint", writer=write_budget)


if __name__ == "__main__":
    import sys
    sys.exit(main())
