"""HDR rules — the frozen 80-byte header byte-layout cross-check.

Every backend depends on the exact same serialization (chain.hpp's FROZEN
table); a silently reordered or resized field is the AsicBoost-class drift
this pass exists to catch. The canonical layout is pinned HERE, and four
independent encodings of it are checked against it:

  HDR001  C++ BlockHeader struct field order/width differs from canonical
  HDR002  header size constant (kHeaderSize / HEADER_SIZE) is not 80
  HDR003  chain.cpp serialize()/deserialize() offsets differ from canonical
  HDR004  a Python-side layout anchor (HeaderFields codec, set_nonce slice,
          jnp kernel nonce word index, golden-byte test offsets) disagrees

The nonce MUST live in SHA-256 chunk 2 at word 3 (byte offset 76 = 64 +
3*4): the midstate optimization in every backend assumes it.
"""
from __future__ import annotations

import pathlib
import re

from . import Finding, rel_path
from .cparse import extract_function_body, parse_struct_fields

CANONICAL = (("version", 4), ("prev_hash", 32), ("data_hash", 32),
             ("timestamp", 4), ("bits", 4), ("nonce", 4))
HEADER_SIZE = 80
NONCE_OFFSET = 76           # == 64 (chunk 1) + 3 (word index) * 4


def canonical_offsets() -> dict[str, tuple[int, int]]:
    out, off = {}, 0
    for name, width in CANONICAL:
        out[name] = (off, width)
        off += width
    return out


def _rel(path: pathlib.Path, root: pathlib.Path) -> str:
    return rel_path(path, root)


def _check_struct(findings, hpp: pathlib.Path, rel: str):
    fields = parse_struct_fields(hpp, "BlockHeader")
    if not fields:
        findings.append(Finding(rel, 1, "HDR001",
                                "struct BlockHeader not found / no parsable "
                                "data members"))
        return
    got = [(f.name, f.width) for f in fields]
    if got != list(CANONICAL):
        for i, (g, c) in enumerate(zip(got, CANONICAL)):
            if g != c:
                findings.append(Finding(
                    rel, fields[i].line, "HDR001",
                    f"BlockHeader field {i} is {g[0]}[{g[1]}B]; the frozen "
                    f"layout requires {c[0]}[{c[1]}B] here (full layout: "
                    f"{[n for n, _ in CANONICAL]})"))
                break
        else:
            findings.append(Finding(
                rel, fields[0].line, "HDR001",
                f"BlockHeader has {len(got)} data members; the frozen "
                f"layout has {len(CANONICAL)}"))
    total = sum(w for _, w in got)
    if total != HEADER_SIZE:
        findings.append(Finding(
            rel, fields[0].line, "HDR002",
            f"BlockHeader fields total {total} bytes; the frozen header "
            f"is {HEADER_SIZE}"))
    text = hpp.read_text(errors="replace")
    m = re.search(r"kHeaderSize\s*=\s*(\d+)", text)
    if m and int(m.group(1)) != HEADER_SIZE:
        findings.append(Finding(
            rel, text[:m.start()].count("\n") + 1, "HDR002",
            f"kHeaderSize = {m.group(1)}; the frozen header is "
            f"{HEADER_SIZE}"))


def _serializer_offsets(body: str, buf: str) -> dict[str, int]:
    """Field -> byte offset from store_le32/load_le32/memcpy calls against
    buffer variable ``buf`` in a serialize/deserialize body."""
    offsets: dict[str, int] = {}
    for m in re.finditer(
            rf"store_le32\(\s*{buf}\s*(?:\+\s*(\d+))?\s*,\s*(\w+)\s*\)",
            body):
        offsets[m.group(2)] = int(m.group(1) or 0)
    for m in re.finditer(
            rf"(\w+)\s*=\s*load_le32\(\s*{buf}\s*(?:\+\s*(\d+))?\s*\)",
            body):
        offsets[m.group(1).split(".")[-1]] = int(m.group(2) or 0)
    for m in re.finditer(
            rf"memcpy\(\s*{buf}\s*(?:\+\s*(\d+))?\s*,\s*[\w.]*?(\w+)\s*,",
            body):
        offsets[m.group(2)] = int(m.group(1) or 0)
    for m in re.finditer(
            rf"memcpy\(\s*[\w.]*?(\w+)\s*,\s*{buf}\s*(?:\+\s*(\d+))?\s*,",
            body):
        offsets[m.group(1)] = int(m.group(2) or 0)
    return offsets


def _check_serializer(findings, cpp: pathlib.Path, rel: str):
    canon = canonical_offsets()
    for fn_re, buf, label in (
            (r"void\s+BlockHeader::serialize\s*\(", "out", "serialize"),
            (r"BlockHeader\s+BlockHeader::deserialize\s*\(", "in",
             "deserialize")):
        body = extract_function_body(cpp, fn_re)
        if not body:
            findings.append(Finding(rel, 1, "HDR003",
                                    f"BlockHeader::{label} not found"))
            continue
        got = _serializer_offsets(body, buf)
        normalized = {k.removeprefix("h."): v for k, v in got.items()}
        for field, (off, _w) in canon.items():
            if field not in normalized:
                findings.append(Finding(
                    rel, 1, "HDR003",
                    f"BlockHeader::{label} never touches field "
                    f"'{field}'"))
            elif normalized[field] != off:
                findings.append(Finding(
                    rel, 1, "HDR003",
                    f"BlockHeader::{label} places '{field}' at offset "
                    f"{normalized[field]}; the frozen layout puts it at "
                    f"{off}"))


def _check_python_codec(findings, core_init: pathlib.Path, rel: str):
    canon = canonical_offsets()
    text = core_init.read_text(errors="replace")
    lines = text.splitlines()

    def lineno(pat: str) -> int:
        for i, ln in enumerate(lines, 1):
            if re.search(pat, ln):
                return i
        return 1

    # Every anchor FAILS CLOSED: a regex that no longer matches is itself
    # a finding, so a refactor cannot silently disable this leg of the
    # cross-check.
    def anchor(pattern: str, what: str):
        m = re.search(pattern, text)
        if m is None:
            findings.append(Finding(
                rel, 1, "HDR004",
                f"could not locate {what} in {rel} — the Python-codec "
                f"layout anchor is gone; update analysis/header_layout.py "
                f"alongside the refactor"))
        return m

    m = anchor(r'unpack_from\("<I",\s*header80,\s*(\d+)\)',
               "the HeaderFields version unpack_from('<I', ...)")
    if m and int(m.group(1)) != canon["version"][0]:
        findings.append(Finding(
            rel, lineno(r'unpack_from\("<I"'), "HDR004",
            f"HeaderFields.unpack reads version at {m.group(1)}; the "
            f"frozen layout puts it at {canon['version'][0]}"))
    m = anchor(r'unpack_from\("<III",\s*header80,\s*(\d+)\)',
               "the HeaderFields timestamp/bits/nonce unpack_from('<III')")
    if m and int(m.group(1)) != canon["timestamp"][0]:
        findings.append(Finding(
            rel, lineno(r'unpack_from\("<III"'), "HDR004",
            f"HeaderFields.unpack reads timestamp/bits/nonce from "
            f"{m.group(1)}; the frozen layout starts them at "
            f"{canon['timestamp'][0]}"))
    slices = [(int(a), int(b)) for a, b in
              re.findall(r"header80\[(\d+):(\d+)\]", text)]
    expected = [(canon["prev_hash"][0],
                 canon["prev_hash"][0] + canon["prev_hash"][1]),
                (canon["data_hash"][0],
                 canon["data_hash"][0] + canon["data_hash"][1])]
    if not slices:
        findings.append(Finding(
            rel, 1, "HDR004",
            f"could not locate the HeaderFields hash-field slices "
            f"(header80[a:b]) in {rel} — layout anchor gone"))
    for sl in slices:
        if sl not in expected:
            findings.append(Finding(
                rel, lineno(rf"header80\[{sl[0]}:{sl[1]}\]"), "HDR004",
                f"HeaderFields slices header80[{sl[0]}:{sl[1]}]; frozen "
                f"hash fields live at {expected}"))
    m = anchor(r"header80\[:(\d+)\]", "the set_nonce prefix slice")
    if m and int(m.group(1)) != NONCE_OFFSET:
        findings.append(Finding(
            rel, lineno(r"header80\[:(\d+)\]"), "HDR004",
            f"set_nonce keeps header80[:{m.group(1)}]; the frozen nonce "
            f"offset is {NONCE_OFFSET}"))
    m = anchor(r"HEADER_SIZE\s*=\s*(\d+)", "the HEADER_SIZE constant")
    if m and int(m.group(1)) != HEADER_SIZE:
        findings.append(Finding(
            rel, lineno(r"HEADER_SIZE\s*="), "HDR002",
            f"Python HEADER_SIZE = {m.group(1)}; the frozen header is "
            f"{HEADER_SIZE}"))


def _check_jnp_kernel(findings, sha_jnp: pathlib.Path, rel: str):
    text = sha_jnp.read_text(errors="replace")
    m = (re.search(r"NONCE_WORD_INDEX\s*=\s*(\d+)", text)
         or re.search(r"i\s*!=\s*(\d+)\s+else\s+nonce_word", text))
    if m is None:
        findings.append(Finding(
            rel, 1, "HDR004",
            "could not locate the chunk-2 nonce word index "
            "(NONCE_WORD_INDEX constant or the inline tail_w "
            "substitution) in the jnp kernel"))
        return
    idx = int(m.group(1))
    if 64 + idx * 4 != NONCE_OFFSET:
        findings.append(Finding(
            rel, text[:m.start()].count("\n") + 1, "HDR004",
            f"jnp kernel substitutes the nonce at chunk-2 word {idx} "
            f"(byte {64 + idx * 4}); the frozen nonce offset is "
            f"{NONCE_OFFSET}"))


def _check_golden_test(findings, test_path: pathlib.Path, rel: str):
    canon = canonical_offsets()
    valid = {(off, off + w) for off, w in canon.values()}
    text = test_path.read_text(errors="replace")
    for m in re.finditer(r"cand\[(\d+):(\d+)\]", text):
        sl = (int(m.group(1)), int(m.group(2)))
        if sl not in valid:
            findings.append(Finding(
                rel, text[:m.start()].count("\n") + 1, "HDR004",
                f"golden-byte test slices cand[{sl[0]}:{sl[1]}], which is "
                f"not a frozen field span {sorted(valid)}"))


def run_header_layout(root: pathlib.Path, overrides=None,
                      notes=None) -> list[Finding]:
    overrides = overrides or {}
    pkg = root / "mpi_blockchain_tpu"
    hpp = overrides.get("chain_hpp", pkg / "core" / "src" / "chain.hpp")
    cpp = overrides.get("chain_cpp", pkg / "core" / "src" / "chain.cpp")
    core_init = overrides.get("core_init", pkg / "core" / "__init__.py")
    # NONCE_WORD_INDEX's single source of truth moved to the per-template
    # precompute module with the extended-midstate refactor (ISSUE 15);
    # both jax kernels import it from there.
    sha_jnp = overrides.get("sha_jnp", pkg / "ops" / "sha256_sched.py")
    golden = overrides.get("header_test",
                           root / "tests" / "test_header_layout.py")

    findings: list[Finding] = []
    _check_struct(findings, hpp, _rel(hpp, root))
    _check_serializer(findings, cpp, _rel(cpp, root))
    _check_python_codec(findings, core_init, _rel(core_init, root))
    _check_jnp_kernel(findings, sha_jnp, _rel(sha_jnp, root))
    if golden.exists():
        _check_golden_test(findings, golden, _rel(golden, root))
    elif notes is not None:
        notes.append(f"header: golden-byte test {golden} absent; skipped")
    return findings
