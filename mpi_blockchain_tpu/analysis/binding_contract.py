"""BIND rules — the C ABI / ctypes / pybind11 contract checker.

The ctypes binding re-declares every ``extern "C"`` prototype by hand; a
drifted arity or width there corrupts arguments silently (a uint32 passed
where C reads uint64 reads stack garbage — the exact class of bug that
breaks cross-backend hash equivalence). The pybind11 module re-implements
the same Python surface a second time. Both duplications are checked here:

  BIND001  exported C symbol has no ctypes argtypes declaration
  BIND002  argtypes arity differs from the C parameter count
  BIND003  an argtype is incompatible with the C parameter type
  BIND004  restype missing or incompatible with the C return type
  BIND005  ctypes declares a symbol the C ABI does not export
  BIND006  ctypes veneer exposes a name the pybind11 surface lacks
  BIND007  pybind11 exposes a name the ctypes veneer lacks
"""
from __future__ import annotations

import ast
import pathlib
import re

from . import Finding, rel_path
from .cparse import parse_extern_c_funcs, strip_comments

# C parameter type -> acceptable ctypes spellings. Byte buffers cross as
# c_char_p (immutable bytes in) or POINTER(c_uint8) (out buffers) — both
# are uint8_t* at the ABI level.
ARG_OK = {
    "uint8_t*": {"c_char_p", "POINTER(c_uint8)"},
    "char*": {"c_char_p"},
    "uint32_t*": {"POINTER(c_uint32)"},
    "uint64_t*": {"POINTER(c_uint64)"},
    "void*": {"c_void_p"},
    "uint8_t": {"c_uint8"},
    "uint16_t": {"c_uint16"},
    "uint32_t": {"c_uint32"},
    "uint64_t": {"c_uint64"},
    "int64_t": {"c_int64"},
    "int32_t": {"c_int32"},
    "size_t": {"c_size_t"},
    "int": {"c_int"},
}
RET_OK = {
    "void*": {"c_void_p"},
    "uint64_t": {"c_uint64"},
    "uint32_t": {"c_uint32"},
    "int64_t": {"c_int64"},
    "int": {"c_int"},
}

# Surface names legitimately present on one binding only (documented in
# docs/static_analysis.md; keep this list short and justified).
SURFACE_ASYMMETRY_OK = {
    "NOT_FOUND",   # ctypes-only sentinel; pybind11 returns None in-band
}


def _rel(path: pathlib.Path, root: pathlib.Path) -> str:
    return rel_path(path, root)


def _ctypes_expr_name(node: ast.expr, aliases: dict[str, str]) -> str:
    """Canonical spelling of an argtypes/restype element expression."""
    if isinstance(node, ast.Attribute):        # ctypes.c_char_p
        return node.attr
    if isinstance(node, ast.Name):             # _u8p / c_int
        return aliases.get(node.id, node.id)
    if isinstance(node, ast.Call):             # ctypes.POINTER(ctypes.c_X)
        fn = _ctypes_expr_name(node.func, aliases)
        args = ",".join(_ctypes_expr_name(a, aliases) for a in node.args)
        return f"{fn}({args})"
    if isinstance(node, ast.Constant) and node.value is None:
        return "None"
    return ast.dump(node)


def parse_ctypes_decls(path: pathlib.Path):
    """(argtypes, restypes, lines): per-symbol declarations from the
    ``_lib.cc_x.argtypes = [...]`` / ``.restype = ...`` assignments."""
    tree = ast.parse(path.read_text(), filename=str(path))
    aliases: dict[str, str] = {}
    argtypes: dict[str, list[str]] = {}
    restypes: dict[str, str] = {}
    lines: dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if isinstance(tgt, ast.Name):          # _u8p = ctypes.POINTER(...)
            aliases[tgt.id] = _ctypes_expr_name(node.value, aliases)
            continue
        if not (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Attribute)
                and isinstance(tgt.value.value, ast.Name)
                and tgt.value.value.id == "_lib"):
            continue
        sym = tgt.value.attr
        lines.setdefault(sym, node.lineno)
        if tgt.attr == "argtypes" and isinstance(node.value,
                                                 (ast.List, ast.Tuple)):
            argtypes[sym] = [_ctypes_expr_name(e, aliases)
                             for e in node.value.elts]
        elif tgt.attr == "restype":
            restypes[sym] = _ctypes_expr_name(node.value, aliases)
    return argtypes, restypes, lines


def parse_pybind_surface(path: pathlib.Path):
    """(module_names, class_members): names bound in the pybind11 module."""
    text = strip_comments(path.read_text(errors="replace"))
    module = set(re.findall(r'\bm\.def\(\s*"(\w+)"', text))
    module |= set(re.findall(r'\bm\.attr\("(\w+)"\)', text))
    module |= set(re.findall(r'py::class_<\w+>\(m,\s*"(\w+)"\)', text))
    members = set(re.findall(r'(?<!m)\.def\(\s*"(\w+)"', text))
    members |= set(re.findall(r'\.def_property_readonly\(\s*"(\w+)"', text))
    return module, members


def parse_ctypes_surface(path: pathlib.Path):
    """(module_names, class_members): the public veneer surface of the
    ctypes binding module — top-level functions/constants plus the methods,
    properties, and __init__-assigned attributes of class Node."""
    tree = ast.parse(path.read_text(), filename=str(path))
    module: set[str] = set()
    members: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and not node.name.startswith("_"):
            module.add(node.name)
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and not tgt.id.startswith("_"):
                    module.add(tgt.id)
        elif isinstance(node, ast.ClassDef) and not node.name.startswith("_"):
            module.add(node.name)
            for item in node.body:
                if isinstance(item, ast.FunctionDef):
                    if not item.name.startswith("_"):
                        members.add(item.name)
                    if item.name == "__init__":
                        for sub in ast.walk(item):
                            if (isinstance(sub, ast.Attribute)
                                    and isinstance(sub.ctx, ast.Store)
                                    and isinstance(sub.value, ast.Name)
                                    and sub.value.id == "self"
                                    and not sub.attr.startswith("_")):
                                members.add(sub.attr)
    return module, members


def run_binding_contract(root: pathlib.Path, overrides=None,
                         notes=None) -> list[Finding]:
    overrides = overrides or {}
    pkg = root / "mpi_blockchain_tpu"
    capi = overrides.get("capi", pkg / "core" / "src" / "capi.cpp")
    binding = overrides.get("ctypes_binding",
                            pkg / "core" / "_ctypes_binding.py")
    pybind = overrides.get("pybind",
                           pkg / "core" / "src" / "pybind_module.cpp")

    findings: list[Finding] = []
    cfuncs = parse_extern_c_funcs(capi)
    argtypes, restypes, decl_lines = parse_ctypes_decls(binding)
    capi_rel, binding_rel = _rel(capi, root), _rel(binding, root)

    for name, fn in sorted(cfuncs.items()):
        if name not in argtypes:
            findings.append(Finding(
                capi_rel, fn.line, "BIND001",
                f"exported symbol {name} has no ctypes argtypes "
                f"declaration in {binding_rel}"))
            continue
        declared = argtypes[name]
        line = decl_lines.get(name, 1)
        if len(declared) != len(fn.params):
            findings.append(Finding(
                binding_rel, line, "BIND002",
                f"{name}: argtypes arity {len(declared)} != C parameter "
                f"count {len(fn.params)} "
                f"({', '.join(p.ctype for p in fn.params)})"))
        else:
            for i, (p, d) in enumerate(zip(fn.params, declared)):
                ok = ARG_OK.get(p.ctype, set())
                if d not in ok:
                    findings.append(Finding(
                        binding_rel, line, "BIND003",
                        f"{name}: argtypes[{i}] is {d}; C declares "
                        f"'{p.name}: {p.ctype}' (expected one of "
                        f"{sorted(ok) or ['<unmappable>']})"))
        declared_ret = restypes.get(name)
        if fn.ret == "void":
            if declared_ret not in (None, "None"):
                findings.append(Finding(
                    binding_rel, line, "BIND004",
                    f"{name}: restype {declared_ret} declared but C "
                    f"returns void"))
        else:
            ok = RET_OK.get(fn.ret, set())
            if declared_ret is None:
                findings.append(Finding(
                    binding_rel, line, "BIND004",
                    f"{name}: no restype declared; C returns {fn.ret} "
                    f"(ctypes would silently truncate through the c_int "
                    f"default)"))
            elif declared_ret not in ok:
                findings.append(Finding(
                    binding_rel, line, "BIND004",
                    f"{name}: restype {declared_ret} incompatible with C "
                    f"return {fn.ret} (expected one of {sorted(ok)})"))

    for name in sorted(set(argtypes) - set(cfuncs)):
        findings.append(Finding(
            binding_rel, decl_lines.get(name, 1), "BIND005",
            f"ctypes declares {name} but {capi_rel} exports no such "
            f"symbol"))

    # pybind11 <-> ctypes veneer surface parity.
    pb_module, pb_members = parse_pybind_surface(pybind)
    ct_module, ct_members = parse_ctypes_surface(binding)
    pybind_rel = _rel(pybind, root)
    for name in sorted((ct_module - pb_module) - SURFACE_ASYMMETRY_OK):
        findings.append(Finding(
            pybind_rel, 1, "BIND006",
            f"ctypes veneer exposes module-level '{name}' but the pybind11 "
            f"module does not bind it"))
    for name in sorted((ct_members - pb_members) - SURFACE_ASYMMETRY_OK):
        findings.append(Finding(
            pybind_rel, 1, "BIND006",
            f"ctypes Node exposes '{name}' but the pybind11 Node does not "
            f"bind it"))
    for name in sorted((pb_module - ct_module) - SURFACE_ASYMMETRY_OK):
        findings.append(Finding(
            binding_rel, 1, "BIND007",
            f"pybind11 binds module-level '{name}' but the ctypes veneer "
            f"does not expose it"))
    for name in sorted((pb_members - ct_members) - SURFACE_ASYMMETRY_OK):
        findings.append(Finding(
            binding_rel, 1, "BIND007",
            f"pybind11 Node binds '{name}' but the ctypes Node does not "
            f"expose it"))
    return findings
