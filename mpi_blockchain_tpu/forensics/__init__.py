"""Chain forensics: merge per-node causal logs, reconstruct what happened.

The simulation layer emits per-node Lamport-stamped event logs
(``telemetry/causal.py``); this package is the *reader* side — the tool
an operator points at a ``sim --events-dump`` artifact (or a crash
flight-recorder dump's ``causal`` section) to answer the questions a
reorg or a partition actually raises:

* **merge** — one causally-consistent total order over all nodes' events
  (sorted by ``(lamport, node, seq)``; a deliver can never sort before
  its send).
* **fork_tree** — the block DAG reconstructed from mine events: fork
  points, per-node final tips, the canonical chain, and the orphaned
  (reorged-away) blocks.
* **reorg audit** — which rank adopted which suffix, which announcements
  addressed to it were dropped vs partition-deferred, and whether that
  loss explains the fork it had to heal from.
* **convergence stats** — announcement propagation latency (in sim
  steps) and the run's overall convergence picture.
* **trace_export** — the merged order as Chrome trace-event JSON
  (logical time on the timeline axis), viewable in Perfetto.

CLI::

    python -m mpi_blockchain_tpu.forensics --events causal.json \\
        [--trace trace.json] [--json]

Everything here is a pure function of the dump: running the CLI twice on
the same artifact (or on two same-seed runs) produces byte-identical
reports — the determinism tests assert this.
"""
from __future__ import annotations

from ..telemetry.causal import load_causal_dump  # noqa: F401
from .attack_audit import attack_audit  # noqa: F401
from .fork_tree import (build_fork_tree, convergence_stats,  # noqa: F401
                        reorg_audit)
from .merge import merge_events, node_order  # noqa: F401
from .trace_export import to_chrome_trace  # noqa: F401


def analyze_dump(dump: dict) -> dict:
    """The full forensics report for one causal dump (the CLI's payload).
    Dumps carrying ``attack_*`` events (the adversarial scenario engine,
    the live-bus attackers) additionally get the attack audit: what each
    selfish/eclipse/flood strategy did and what it achieved."""
    merged = merge_events(dump)
    tree = build_fork_tree(merged)
    return {
        "meta": dump.get("meta", {}),
        "nodes": node_order(dump),
        "events_merged": len(merged),
        "fork_tree": tree,
        "reorg_audit": reorg_audit(merged, tree),
        "convergence": convergence_stats(merged, tree),
        "attack_audit": attack_audit(merged, tree),
    }
