"""Attack audit: what each adversary strategy did and what it achieved.

Consumes the ``attack_*`` causal vocabulary the scenario engine's
strategies (and the live-bus attackers) emit alongside the standard
mine/send/deliver/adopt events, and cross-references it with the fork
tree so every attack's OUTCOME is checkable, not just its attempt:

* **selfish mining** — every ``attack_withhold`` / ``attack_release`` /
  ``attack_abandon``, the reorg depth each release caused (adopt events
  whose winning tip is the released private tip), and the revenue
  ledger: the attacker's blocks on the canonical chain vs everyone
  else's.
* **eclipse** — the attack window, the victim's isolated fork (blocks
  the victim mined or adopted during the window that ended up orphaned),
  and post-heal convergence (the victim's first adopt after the window,
  with its rollback depth).
* **stale-tip flood** — every ``attack_flood`` and the matching
  ``sync_rejected`` rejections, counted by rejection path (sync budget /
  linkage / retarget bits), plus the invariant that matters: no
  post-flood adopt ever names a flooded victim adopting from the
  flooder (chains untouched).

Like everything in this package, the audit is a pure function of the
dump — same artifact (or same-seed run), byte-identical report.
"""
from __future__ import annotations


def _descends_from(blocks: dict, tip: str, ancestor: str,
                   ancestor_height: int) -> bool:
    """True when ``ancestor`` is on the chain ending at ``tip`` (walked
    via mine-event prev links, bounded by the ancestor's height)."""
    h = tip
    while h in blocks:
        if h == ancestor:
            return True
        if blocks[h].get("height", 0) <= ancestor_height:
            return False
        h = blocks[h].get("prev")
    return h == ancestor


def _reason_path(reason: str) -> str:
    """Buckets a sync_rejected reason string into its rejection path."""
    if "budget" in reason:
        return "budget"
    if "linkage" in reason:
        return "linkage"
    if "bits" in reason:
        return "bits"
    return "other"


def _selfish_audit(merged: list[dict], tree: dict) -> list[dict]:
    attackers = sorted({e["node"] for e in merged
                        if e.get("kind") == "attack_withhold"},
                       key=str)
    out = []
    blocks = tree["blocks"]
    canonical = set(tree["canonical_chain"])
    for node in attackers:
        withheld = [e for e in merged
                    if e.get("kind") == "attack_withhold"
                    and e["node"] == node]
        releases = [e for e in merged
                    if e.get("kind") == "attack_release"
                    and e["node"] == node]
        abandons = [e for e in merged
                    if e.get("kind") == "attack_abandon"
                    and e["node"] == node]
        release_audits = []
        for rel in releases:
            tip = rel.get("tip")
            tip_height = rel.get("height", 0)
            # The reorgs this release caused: adopts whose winning tip
            # is the released private tip or a DESCENDANT mined on it
            # before everyone healed (slow receivers adopt the grown
            # chain, not the release-time tip), after the release.
            depths = [e.get("rolled_back", 0) for e in merged
                      if e.get("kind") == "adopt"
                      and e.get("lamport", 0) > rel.get("lamport", 0)
                      and e.get("rolled_back")
                      and _descends_from(blocks, e.get("new_tip"), tip,
                                         tip_height)]
            release_audits.append({
                "step": rel.get("step"),
                "count": rel.get("count"),
                "tip": tip,
                "reorgs_caused": len(depths),
                "max_reorg_depth": max(depths, default=0),
            })
        mined = {h for h, b in blocks.items() if b.get("miner") == node}
        revenue = len(mined & canonical)
        out.append({
            "node": node,
            "withheld_total": len(withheld),
            "releases": release_audits,
            "released_total": sum(r.get("count", 0) for r in releases),
            "abandoned_total": sum(a.get("count", 0) for a in abandons),
            "revenue_blocks": revenue,
            "revenue_share": (round(revenue / len(canonical), 4)
                              if canonical else 0.0),
        })
    return out


def _eclipse_audit(merged: list[dict], tree: dict) -> list[dict]:
    out = []
    blocks = tree["blocks"]
    canonical = set(tree["canonical_chain"])
    for start in [e for e in merged
                  if e.get("kind") == "attack_eclipse_start"]:
        victim = start.get("victim")
        until = start.get("until_step", 0)
        end = next((e for e in merged
                    if e.get("kind") == "attack_eclipse_end"
                    and e.get("victim") == victim
                    and e.get("step", 0) >= start.get("step", 0)), None)
        window = (start.get("step", 0),
                  end.get("step") if end else until or None)
        # The victim's isolated fork: blocks it mined inside the window
        # that never made the canonical chain.
        isolated = sorted(
            h for h, b in blocks.items()
            if b.get("miner") == victim and h not in canonical
            and window[0] <= b.get("step", 0)
            and (window[1] is None or b.get("step", 0) < window[1]))
        # Post-heal convergence: the victim's first adopt after the
        # window closed, and whether its final tip is canonical.
        heal = next((e for e in merged
                     if e.get("kind") == "adopt"
                     and str(e.get("node")) == str(victim)
                     and window[1] is not None
                     and e.get("step", 0) >= window[1]), None)
        out.append({
            "attacker": start.get("attacker"),
            "victim": victim,
            "window": list(window),
            "victim_height_at_start": start.get("victim_height"),
            "victim_height_at_end": (end or {}).get("victim_height"),
            "isolated_fork": isolated,
            "isolated_fork_len": len(isolated),
            "post_heal_adopt": (None if heal is None else {
                "step": heal.get("step"),
                "rolled_back": heal.get("rolled_back"),
                "adopted": heal.get("adopted"),
                "new_tip": heal.get("new_tip"),
            }),
            # On-canonical-chain, not tip-equality: at scale the dump
            # records consensus events only (no per-append delivers), so
            # a victim's recorded tip can be a stale ancestor of the
            # canonical tip while its real chain is canonical.
            "victim_tip_canonical": (
                tree["tips"].get(str(victim)) in canonical),
        })
    return out


def _flood_audit(merged: list[dict], tree: dict) -> list[dict]:
    attackers = sorted({e["node"] for e in merged
                        if e.get("kind") == "attack_flood"}, key=str)
    out = []
    known_blocks = set(tree["blocks"])
    for node in attackers:
        floods = [e for e in merged if e.get("kind") == "attack_flood"
                  and e["node"] == node]
        # Rejections attributed to this flooder (the victim names the
        # peer it rejected).
        rejections = [e for e in merged
                      if e.get("kind") == "sync_rejected"
                      and str(e.get("peer")) == str(node)]
        by_path: dict[str, int] = {}
        for r in rejections:
            path = _reason_path(r.get("reason", ""))
            by_path[path] = by_path.get(path, 0) + 1
        # The invariant: no adopt FROM the flooder ever installed a tip
        # that was never mined. A flooder may also run an honest chain
        # (its mined blocks have mine events and may be legitimately
        # adopted); a FORGED suffix's tip has no mine event anywhere, so
        # adopting one is exactly "a forged suffix got through".
        breaches = [e for e in merged if e.get("kind") == "adopt"
                    and str(e.get("peer", "")) == str(node)
                    and e.get("new_tip") not in known_blocks]
        victims = {str(r.get("node")) for r in rejections}
        out.append({
            "node": node,
            "attacks": len(floods),
            "rejections": len(rejections),
            "rejections_by_path": dict(sorted(by_path.items())),
            "victims": sorted(victims),
            "chains_untouched": not breaches,
        })
    return out


def attack_audit(merged: list[dict], tree: dict) -> dict:
    """The attack section of ``analyze_dump`` (empty dict when the dump
    carries no ``attack_*`` events — plain fault runs stay unchanged)."""
    if not any(str(e.get("kind", "")).startswith("attack_")
               for e in merged):
        return {}
    return {
        "selfish": _selfish_audit(merged, tree),
        "eclipse": _eclipse_audit(merged, tree),
        "flood": _flood_audit(merged, tree),
    }
