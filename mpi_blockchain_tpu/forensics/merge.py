"""Merge per-node causal logs into one causally-consistent total order.

Lamport clocks give a partial order: if event *a* happened-before *b*
(same node, or a send and its receipt), then ``lamport(a) < lamport(b)``
— receipt merges with ``max + 1``, so the strict inequality holds by
construction. Sorting by ``(lamport, node, seq)`` therefore yields a
total order that *extends* the causal partial order: concurrent events
(incomparable in happened-before) are tie-broken deterministically by
node id, then by the per-node sequence number. The same dump always
merges to the same list — there is no wall clock anywhere in the key.
"""
from __future__ import annotations


def _node_key(node) -> tuple:
    """Deterministic cross-type ordering: numeric node ids first (by
    value), then named pseudo-nodes ("bus") lexicographically."""
    s = str(node)
    try:
        return (0, int(s), "")
    except ValueError:
        return (1, 0, s)


def causal_sort_key(event: dict) -> tuple:
    return (event.get("lamport", 0), _node_key(event.get("node")),
            event.get("seq", 0))


def merge_events(dump: dict) -> list[dict]:
    """All events from every node's log, in one causal total order."""
    merged: list[dict] = []
    for events in dump.get("nodes", {}).values():
        merged.extend(events)
    merged.sort(key=causal_sort_key)
    return merged


def node_order(dump: dict) -> list[str]:
    """The dump's node ids in merge order (numeric first, then names)."""
    return sorted(dump.get("nodes", {}), key=_node_key)
