"""Merged causal order as Chrome trace-event JSON (Perfetto-viewable).

The timeline axis is LOGICAL time: ``ts = lamport * TICK_US``. That is a
deliberate choice — the causal logs carry no wall clock (determinism
contract), and for consensus forensics the interesting axis is
happened-before, not microseconds. Each simulation node renders as its
own process row (the bus pseudo-node included), every event as a short
complete slice, and every announcement as a flow arrow from its send to
each deliver — the fork-and-heal story reads directly off the Perfetto
canvas (load the file at ui.perfetto.dev, or chrome://tracing).
"""
from __future__ import annotations

from .merge import _node_key

TICK_US = 10          # microseconds of timeline per Lamport tick
SLICE_US = 8          # slice width; < TICK_US so consecutive ticks split


def _pid(node) -> int:
    """Stable numeric pid per node: numeric ids map to id+1, pseudo-nodes
    ("bus") to 0 so the bus row sorts first."""
    try:
        return int(str(node)) + 1
    except ValueError:
        return 0


def to_chrome_trace(merged: list[dict]) -> dict:
    """Trace-event JSON (object form) for one merged causal order."""
    events: list[dict] = []
    nodes = sorted({e.get("node") for e in merged}, key=_node_key)
    for node in nodes:
        events.append({"ph": "M", "name": "process_name",
                       "pid": _pid(node), "tid": 0,
                       "args": {"name": f"node {node}"}})
    sends: dict[str, dict] = {}
    for e in merged:
        if e.get("kind") == "send" and e.get("hash") not in sends:
            sends[e["hash"]] = e
    for e in merged:
        args = {k: v for k, v in e.items()
                if k not in ("kind", "node", "lamport")}
        ts = e.get("lamport", 0) * TICK_US
        pid = _pid(e.get("node"))
        events.append({"ph": "X", "cat": "sim", "name": e.get("kind", "?"),
                       "ts": ts, "dur": SLICE_US, "pid": pid, "tid": 0,
                       "args": args})
        # Flow arrows: send -> every deliver of the same announcement.
        if e.get("kind") == "send":
            events.append({"ph": "s", "cat": "announce", "id": e["hash"],
                           "name": "announce", "ts": ts, "pid": pid,
                           "tid": 0})
        elif e.get("kind") == "deliver" and e.get("hash") in sends:
            events.append({"ph": "f", "bp": "e", "cat": "announce",
                           "id": e["hash"], "name": "announce",
                           "ts": ts, "pid": pid, "tid": 0})
    return {"traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {"clock": "lamport",
                         "tick_us": TICK_US,
                         "source": "mpi_blockchain_tpu.forensics"}}
