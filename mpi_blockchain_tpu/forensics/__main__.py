"""CLI: python -m mpi_blockchain_tpu.forensics

Point it at a causal event dump (``sim --events-dump PATH``, or a flight
recorder artifact's ``causal`` section re-wrapped) and it reconstructs
the cross-rank story: merged causal order, fork tree, reorg audit,
convergence stats, and optionally a Perfetto-viewable Chrome trace.

    python -m mpi_blockchain_tpu.forensics --events causal.json
    python -m mpi_blockchain_tpu.forensics --events causal.json --json
    python -m mpi_blockchain_tpu.forensics --events causal.json \\
        --trace trace.json     # load at ui.perfetto.dev

The report is a pure function of the dump: identical input (or two
same-seed sim runs) -> byte-identical output.
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys

from . import analyze_dump, load_causal_dump, merge_events, to_chrome_trace


def _human_report(report: dict, out) -> None:
    tree = report["fork_tree"]
    conv = report["convergence"]
    print(f"nodes: {', '.join(report['nodes'])}", file=out)
    print(f"events merged: {report['events_merged']}", file=out)
    print(f"blocks: {len(tree['blocks'])} "
          f"(canonical {len(tree['canonical_chain'])}, "
          f"orphaned {len(tree['orphaned'])})", file=out)
    print(f"fork points: {len(tree['fork_points'])}", file=out)
    for prev, sibs in tree["fork_points"].items():
        print(f"  {prev} -> {', '.join(sibs)}", file=out)
    print(f"converged: {tree['converged']} "
          f"(canonical tip {tree['canonical_tip']}, "
          f"height {conv['canonical_height']})", file=out)
    print(f"tips: " + ", ".join(f"{n}={t}"
                                for n, t in tree["tips"].items()),
          file=out)
    lat = conv["delivery_latency_steps"]
    print(f"announcements: {conv['announcements']}, "
          f"deliveries: {conv['deliveries']}, "
          f"latency steps p50/max: {lat['p50']}/{lat['max']}", file=out)
    attacks = report.get("attack_audit") or {}
    if attacks:
        print("attacks:", file=out)
        for s in attacks.get("selfish", []):
            print(f"  selfish node {s['node']}: withheld "
                  f"{s['withheld_total']}, released {s['released_total']} "
                  f"in {len(s['releases'])} release(s), abandoned "
                  f"{s['abandoned_total']}; revenue {s['revenue_blocks']} "
                  f"canonical blocks ({s['revenue_share']:.1%})",
                  file=out)
            for r in s["releases"]:
                print(f"    release step {r['step']}: {r['count']} "
                      f"block(s) -> {r['reorgs_caused']} reorg(s), max "
                      f"depth {r['max_reorg_depth']} (tip {r['tip']})",
                      file=out)
        for e in attacks.get("eclipse", []):
            heal = e["post_heal_adopt"]
            heal_s = ("no post-heal adopt" if heal is None else
                      f"post-heal adopt at step {heal['step']} rolled "
                      f"back {heal['rolled_back']} for {heal['adopted']}")
            print(f"  eclipse {e['attacker']} -> victim {e['victim']} "
                  f"window {e['window']}: isolated fork "
                  f"{e['isolated_fork_len']} block(s) "
                  f"({','.join(e['isolated_fork']) or 'none'}); {heal_s}; "
                  f"victim tip canonical: {e['victim_tip_canonical']}",
                  file=out)
        for f in attacks.get("flood", []):
            paths = ", ".join(f"{k}={v}" for k, v in
                              f["rejections_by_path"].items())
            print(f"  flood node {f['node']}: {f['attacks']} attack(s), "
                  f"{f['rejections']} rejection(s) [{paths}]; chains "
                  f"untouched: {f['chains_untouched']}", file=out)
    print(f"reorgs: {conv['reorgs']}", file=out)
    for a in report["reorg_audit"]:
        loss = ("dropped=" + ",".join(a["announcements_dropped"])
                if a["announcements_dropped"] else "")
        defer = ("deferred=" + ",".join(
            a["announcements_partition_deferred"])
            if a["announcements_partition_deferred"] else "")
        why = " ".join(x for x in (loss, defer) if x) or "no recorded loss"
        print(f"  step {a['step']}: node {a['node']} rolled back "
              f"{a['rolled_back']} ({','.join(a['rolled_back_hashes'])}) "
              f"adopting {a['adopted']} -> {a['new_tip']}; {why} "
              f"[loss_explains_fork={a['loss_explains_fork']}]", file=out)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_blockchain_tpu.forensics",
        description="merge per-node causal logs; reconstruct fork tree, "
                    "reorg audit, convergence stats; export Chrome trace")
    parser.add_argument("--events", required=True, metavar="PATH",
                        help="causal event dump (sim --events-dump PATH)")
    parser.add_argument("--trace", metavar="PATH", default=None,
                        help="also write Chrome trace-event JSON here "
                             "(view at ui.perfetto.dev)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the full report as sorted JSON")
    args = parser.parse_args(argv)

    try:
        dump = load_causal_dump(args.events)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"forensics: cannot read events dump: {e}", file=sys.stderr)
        return 2

    report = analyze_dump(dump)
    if args.trace:
        trace = to_chrome_trace(merge_events(dump))
        pathlib.Path(args.trace).write_text(
            json.dumps(trace, sort_keys=True))
        print(f"trace: {args.trace} ({len(trace['traceEvents'])} events)",
              file=sys.stderr)
    try:
        if args.as_json:
            print(json.dumps(report, sort_keys=True, indent=2))
        else:
            _human_report(report, sys.stdout)
    except BrokenPipeError:
        # `forensics ... | head` is normal usage for a multi-line report;
        # a closed pipe is the reader's choice, not our failure.
        sys.stderr.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
