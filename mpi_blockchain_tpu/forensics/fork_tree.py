"""Fork-tree reconstruction, reorg audit, convergence statistics.

All inputs are merged causal events (``merge.merge_events``); all
outputs are plain JSON-able dicts whose content is a pure function of
the dump — deterministic across runs with the same seed.

Event vocabulary consumed here (emitted by ``simulation.py``, schema in
docs/forensics.md):

* ``mine``   {hash, prev, height}            — defines a block
* ``send``   {hash, deliver_step}            — announcement enqueued
* ``deliver`` {hash, sender, result}         — announcement received
* ``drop`` / ``defer`` {hash, sender, receiver} — bus loss events
* ``adopt``  {old_tip, new_tip, adopted, rolled_back,
  rolled_back_hashes} — suffix adoption (rolled_back > 0 == a reorg)
"""
from __future__ import annotations

from .merge import _node_key


def build_fork_tree(merged: list[dict]) -> dict:
    """The block DAG + per-node tip history distilled from the events.

    ``mine`` events define blocks (hash -> prev edges); replaying each
    node's mine/deliver/adopt events yields its final tip. The canonical
    chain is walked back from the agreed tip (converged run) or from the
    highest final tip (tie-broken lexicographically) — every known block
    off that chain is orphaned, i.e. reorged away or never adopted.
    """
    blocks: dict[str, dict] = {}
    children: dict[str, list[str]] = {}
    tips: dict[str, str] = {}
    heights: dict[str, int] = {}
    for e in merged:
        kind = e.get("kind")
        node = str(e.get("node"))
        if kind == "mine":
            h = e["hash"]
            blocks[h] = {"prev": e.get("prev"), "height": e.get("height"),
                         "miner": e.get("node"), "lamport": e.get("lamport"),
                         "step": e.get("step")}
            children.setdefault(e.get("prev"), []).append(h)
            tips[node] = h
            heights[node] = e.get("height", 0)
        elif kind == "deliver" and e.get("result") == "appended":
            tips[node] = e["hash"]
            heights[node] = e.get("height", 0)
        elif kind == "adopt":
            tips[node] = e["new_tip"]
            heights[node] = e.get("height", 0)
    for sibs in children.values():
        sibs.sort(key=lambda h: (blocks[h]["height"], h))

    final_tips = sorted(set(tips.values()))
    converged = len(final_tips) == 1
    canonical_tip = None
    if tips:
        # Converged: the shared tip. Not converged: highest final tip
        # (deterministic: height desc, then hash) so the audit still has
        # a reference chain to diff the losers against.
        tip_height = {t: max(heights.get(n, 0)
                             for n in tips if tips[n] == t)
                      for t in final_tips}
        canonical_tip = sorted(final_tips,
                               key=lambda t: (-tip_height[t], t))[0]
    canonical: list[str] = []
    seen: set[str] = set()
    h = canonical_tip
    while h in blocks and h not in seen:   # seen-guard: corrupt dumps
        canonical.append(h)
        seen.add(h)
        h = blocks[h]["prev"]
    canonical.reverse()
    orphaned = sorted(set(blocks) - set(canonical))
    fork_points = {prev: sibs for prev, sibs in sorted(children.items())
                   if len(sibs) > 1}
    return {
        "blocks": {h: blocks[h] for h in sorted(blocks)},
        "fork_points": fork_points,
        "tips": {n: tips[n] for n in sorted(tips, key=_node_key)},
        "canonical_tip": canonical_tip,
        "canonical_chain": canonical,
        "orphaned": orphaned,
        "converged": converged,
    }


def _winning_suffix(tree: dict, new_tip: str, adopted: int) -> list[str]:
    """The (up to ``adopted``-long) chain suffix ending at new_tip, as far
    back as the mine events recorded it — the blocks the loser had to
    take on when it healed."""
    out: list[str] = []
    blocks = tree["blocks"]
    h = new_tip
    while h in blocks and len(out) < adopted:
        out.append(h)
        h = blocks[h]["prev"]
    out.reverse()
    return out


def reorg_audit(merged: list[dict], tree: dict) -> list[dict]:
    """One audit entry per reorg: who healed, from which suffix, and
    whether bus losses (drops / partition deferrals) of the winning
    blocks' announcements to that node explain why it forked at all."""
    losses: dict[tuple, list[dict]] = {}
    for e in merged:
        if e.get("kind") in ("drop", "defer"):
            key = (str(e.get("receiver")), e.get("hash"))
            losses.setdefault(key, []).append(
                {"kind": e["kind"], "step": e.get("step"),
                 "sender": e.get("sender")})
    audits: list[dict] = []
    for e in merged:
        if e.get("kind") != "adopt" or not e.get("rolled_back"):
            continue
        node = str(e.get("node"))
        suffix = _winning_suffix(tree, e["new_tip"], e.get("adopted", 0))
        dropped, deferred = [], []
        for h in suffix:
            for loss in losses.get((node, h), []):
                if loss["step"] <= e.get("step", 0):
                    target = (dropped if loss["kind"] == "drop"
                              else deferred)
                    if h not in target:
                        target.append(h)
        audits.append({
            "node": e.get("node"),
            "step": e.get("step"),
            "lamport": e.get("lamport"),
            "old_tip": e.get("old_tip"),
            "new_tip": e.get("new_tip"),
            "rolled_back": e.get("rolled_back"),
            "rolled_back_hashes": e.get("rolled_back_hashes", []),
            "adopted": e.get("adopted"),
            "winning_suffix": suffix,
            "announcements_dropped": dropped,
            "announcements_partition_deferred": deferred,
            "loss_explains_fork": bool(dropped or deferred),
        })
    return audits


def convergence_stats(merged: list[dict], tree: dict) -> dict:
    """Propagation + convergence picture: how long announcements took to
    land (in sim steps), and where the run ended up."""
    first_send: dict[str, dict] = {}
    latencies: list[int] = []
    deliveries = 0
    slowest: dict | None = None
    for e in merged:
        if e.get("kind") == "send":
            first_send.setdefault(e["hash"], e)
        elif e.get("kind") == "deliver":
            deliveries += 1
            send = first_send.get(e.get("hash"))
            if send is not None:
                lat = max(0, e.get("step", 0) - send.get("step", 0))
                latencies.append(lat)
                if slowest is None or lat > slowest["latency_steps"]:
                    slowest = {"hash": e.get("hash"),
                               "latency_steps": lat,
                               "receiver": e.get("node")}
    latencies.sort()
    n = len(latencies)
    stats = {
        "announcements": len(first_send),
        "deliveries": deliveries,
        "delivery_latency_steps": {
            "count": n,
            "mean": round(sum(latencies) / n, 3) if n else None,
            "p50": latencies[n // 2] if n else None,
            "max": latencies[-1] if n else None,
        },
        "slowest_delivery": slowest,
        "final_step": max((e.get("step", 0) for e in merged), default=0),
        "final_lamport": max((e.get("lamport", 0) for e in merged),
                             default=0),
        "converged": tree["converged"],
        "canonical_height": (tree["blocks"][tree["canonical_tip"]]["height"]
                             if tree.get("canonical_tip") in tree["blocks"]
                             else None),
        "reorgs": sum(1 for e in merged
                      if e.get("kind") == "adopt" and e.get("rolled_back")),
        "blocks_orphaned": len(tree["orphaned"]),
    }
    return stats
