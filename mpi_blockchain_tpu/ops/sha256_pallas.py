"""Pallas TPU kernel: fused double-SHA-256 nonce sweep.

The hot op of the framework (SURVEY.md §7 step 5). Design, per the TPU
kernel playbook:

  * Grid over nonce tiles; each program sweeps a (ROWS, 128) uint32 tile of
    nonces resident in VMEM — 128 lanes to match the VPU, ROWS sublanes to
    amortize control overhead. No HBM traffic inside the kernel at all: the
    nonce values are synthesized from program_id with iota, and only the
    per-tile (count, min_nonce) reduction leaves the chip.
  * Both compressions are fully unrolled straight-line vector code (Mosaic
    compiles this quickly, unlike the XLA CPU backend) with the rotating
    16-word schedule window, so the live set is ~24 (ROWS,128) u32 registers.
  * The chunk-1 midstate and the constant chunk-2 words arrive via scalar
    prefetch (SMEM); only the nonce word varies per lane.

Bit-exactness: identical round structure to core/src/sha256.cpp
(sha256d_from_midstate); verified against the C++ oracle in
tests/test_pallas.py and, on real TPU, by the backend-equivalence suite.

Measured scaling (v5e single chip, axon tunnel, 2026-07-29): dispatch
overhead dominates below ~2^26 nonces/dispatch (2^20 ≈ 12 MH/s, 2^22 ≈
50 MH/s); the kernel saturates the VPU from 2^26 up (967 MH/s at 2^28 with
this round algebra). Callers that care about throughput must batch big —
see bench.py — or stay device-resident (models/fused.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .sha256_jnp import IV, K, NONCE_WORD_INDEX, NOT_FOUND_U32

_U32 = jnp.uint32
_LANES = 128
_ROWS = 64                      # 64*128 = 8192 nonces per grid program
TILE = _ROWS * _LANES


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _bswap32(x):
    return ((x & np.uint32(0xFF)) << np.uint32(24)) \
         | ((x & np.uint32(0xFF00)) << np.uint32(8)) \
         | ((x >> np.uint32(8)) & np.uint32(0xFF00)) \
         | (x >> np.uint32(24))


def _compress_unrolled(state, w):
    """64 unrolled SHA-256 rounds with a rotating schedule window.

    state: tuple of 8 (ROWS,128) u32; w: list of 16 (ROWS,128) u32.

    Round-function algebra (measured +4% at the 2^28-batch VPU plateau):
      * ch(e,f,g)  = g ^ (e & (f ^ g))          — 3 ops vs 4
      * maj(a,b,c) = b ^ ((a^b) & (b^c))        — and this round's b^c is
        last round's a^b, so one xor+and+xor with a cached term vs 5 ops
      * w[r+16] is only expanded while some future round consumes it
        (r+16 < 64); the classic rotating window wastes 16 expansions.
    """
    window = list(w)
    a, b, c, d, e, f, g, h = state
    ab_prev = None
    # errstate: uniform inputs are numpy scalars whose modular uint32 adds
    # fold at trace time; the wraparound is the algorithm, not an error.
    with np.errstate(over="ignore"):
        for r in range(64):
            wi = window[r]
            S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = g ^ (e & (f ^ g))
            t1 = h + S1 + ch + np.uint32(K[r]) + wi
            S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            ab = a ^ b
            bc = (b ^ c) if ab_prev is None else ab_prev
            maj = b ^ (ab & bc)
            ab_prev = ab
            t2 = S0 + maj
            h, g, f, e = g, f, e, d + t1
            d, c, b, a = c, b, a, t1 + t2
            # w[r+16] = w[r] + s0(w[r+1]) + w[r+9] + s1(w[r+14])
            if r + 16 < 64:
                w1, w14 = window[r + 1], window[r + 14]
                s0 = _rotr(w1, 7) ^ _rotr(w1, 18) ^ (w1 >> np.uint32(3))
                s1 = _rotr(w14, 17) ^ _rotr(w14, 19) ^ (w14 >> np.uint32(10))
                window.append(wi + s0 + window[r + 9] + s1)
        out = (a, b, c, d, e, f, g, h)
        return tuple(o + s for o, s in zip(out, state))


def _tile_result(midstate_ref, tail_ref, base, *, difficulty_bits: int):
    """(count, biased_min) for the 8192-nonce tile starting at base.

    Uniform words stay SCALAR (SMEM values / numpy constants) — only the
    nonce word is a vector. jnp promotion then keeps every all-uniform
    intermediate on the scalar core: rounds 0-2 of hash 1 (the nonce enters
    at round 3), the uniform terms of the message schedule, and hash 2's
    constant padding words cost no VPU work, and numpy folds the
    all-constant parts at trace time.
    """
    row = jax.lax.broadcasted_iota(_U32, (_ROWS, _LANES), 0)
    lane = jax.lax.broadcasted_iota(_U32, (_ROWS, _LANES), 1)
    nonces = base + row * np.uint32(_LANES) + lane

    # Chunk 2 of the first hash: uniform words from SMEM, nonce in word 3.
    w1 = [tail_ref[i] if i != NONCE_WORD_INDEX else _bswap32(nonces)
          for i in range(16)]
    st1 = tuple(midstate_ref[i] for i in range(8))
    d1 = _compress_unrolled(st1, w1)
    # Second hash: one padded chunk whose first 8 words are digest 1.
    w2 = list(d1) + [np.uint32(0x80000000)] \
        + [np.uint32(0)] * 6 + [np.uint32(256)]
    st2 = tuple(np.uint32(v) for v in IV)
    d2 = _compress_unrolled(st2, w2)

    # Leading-zero-bits difficulty check on the big-endian digest.
    h0, h1 = d2[0], d2[1]
    dbits = int(difficulty_bits)
    if dbits <= 0:
        qual = jnp.ones_like(h0, dtype=jnp.bool_)
    elif dbits < 32:
        qual = h0 < np.uint32(1 << (32 - dbits))
    elif dbits == 32:
        qual = h0 == np.uint32(0)
    elif dbits < 64:
        qual = (h0 == np.uint32(0)) & (h1 < np.uint32(1 << (64 - dbits)))
    else:
        qual = (h0 == np.uint32(0)) & (h1 == np.uint32(0))

    # Mosaic has no unsigned reductions, so the min runs on bias-flipped
    # int32 (x ^ 0x80000000 is order-isomorphic uint32 -> int32); the
    # caller unbiases. The 0xFFFFFFFF sentinel biases to int32 max — the
    # identity.
    count = jnp.sum(qual.astype(jnp.int32))
    biased = jax.lax.bitcast_convert_type(
        jnp.where(qual, nonces, NOT_FOUND_U32) ^ np.uint32(0x80000000),
        jnp.int32)
    return count, jnp.min(biased)


def _sweep_kernel(midstate_ref, tail_ref, base_ref, count_ref, min_ref, *,
                  difficulty_bits: int, early_exit: bool):
    """Grid sweep: one tile per program, sequential on the core.

    Programs accumulate into one (1,1) SMEM cell: initialize at program 0,
    then reduce. With early_exit, tiles after the first qualifying one skip
    their hash work (tiles are ascending, so min_nonce cannot change).
    """
    pid = pl.program_id(0)

    @pl.when(pid == 0)
    def _():
        count_ref[0, 0] = jnp.int32(0)
        min_ref[0, 0] = jnp.int32(0x7FFFFFFF)

    def tile():
        base = base_ref[0] + (pid * np.uint32(TILE)).astype(_U32)
        c, m = _tile_result(midstate_ref, tail_ref, base,
                            difficulty_bits=difficulty_bits)
        count_ref[0, 0] += c
        min_ref[0, 0] = jnp.minimum(min_ref[0, 0], m)

    if early_exit:
        @pl.when(count_ref[0, 0] == 0)
        def _():
            tile()
    else:
        tile()


def _out_vma(*xs) -> frozenset:
    """Union of the inputs' varying-manual-axes (vma) sets.

    Under shard_map with check_vma=True (the JAX >= 0.9 default), pallas
    outputs must declare which mesh axes they vary over; they inherit the
    union of the inputs' axes (the per-device base_nonce carries the
    'miners' axis). Outside shard_map — or on a JAX predating the vma
    machinery, where jax.typeof does not exist — every set is empty.
    Unit-tested under a real check_vma=True trace in
    tests/test_pallas_interpret.py (the interpret-mode pallas execution
    path cannot carry vma itself; see that module's docstring)."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return frozenset().union(*(getattr(typeof(x), "vma", frozenset())
                               for x in xs))


def pallas_sweep_core(midstate, tail_w, base_nonce, *, batch_size: int,
                      difficulty_bits: int, interpret: bool = False,
                      early_exit: bool = False):
    """Sweeps [base_nonce, base_nonce + batch_size) on one TPU core.

    Same contract as sha256_jnp.sweep_core: returns (count, min_nonce).
    batch_size must be a multiple of the 8192-nonce tile. With
    early_exit=True, tiles after the first qualifying tile are skipped:
    min_nonce is unchanged (lowest-nonce determinism holds) but count is
    only exact up to that tile — use where count is just a found-flag.
    """
    if batch_size % TILE != 0:
        raise ValueError(f"batch_size {batch_size} not a multiple of {TILE}")
    n_tiles = batch_size // TILE

    # A single-program lax.while_loop-over-tiles variant of the early-exit
    # kernel was hardware-benchmarked in round 4 (experiments/hw_round4.py)
    # against this grid + skip-predicate form: identical tips, timing a tie
    # within tunnel noise over 4 rep pairs (grid 1.85-2.55 s, while
    # 1.84-2.16 s per 100 diff-24 blocks), so the extra implementation was
    # deleted rather than kept as an env-selected alternate.
    kernel = functools.partial(_sweep_kernel,
                               difficulty_bits=difficulty_bits,
                               early_exit=early_exit)
    grid = (n_tiles,)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,      # midstate, tail, base — all SMEM scalars
        grid=grid,
        in_specs=[],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i, *_: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i, *_: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
    )
    ms = jnp.asarray(midstate, _U32)
    tw = jnp.asarray(tail_w, _U32)
    bn = jnp.asarray(base_nonce, _U32).reshape((1,))
    # Only pass the kwarg when non-empty, so JAX versions without
    # ShapeDtypeStruct(vma=...) keep working outside shard_map.
    vma = _out_vma(ms, tw, bn)
    vma_kw = {"vma": vma} if vma else {}
    count, min_biased = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((1, 1), jnp.int32, **vma_kw),
                   jax.ShapeDtypeStruct((1, 1), jnp.int32, **vma_kw)],
        grid_spec=grid_spec,
        interpret=interpret,
    )(ms, tw, bn)
    min_nonce = jax.lax.bitcast_convert_type(
        min_biased[0, 0], _U32) ^ np.uint32(0x80000000)
    return count[0, 0], min_nonce


def make_pallas_sweep_fn(batch_size: int, difficulty_bits: int,
                         interpret: bool = False, early_exit: bool = False):
    """jit'd (midstate, tail_w, base_nonce) -> (count, min_nonce)."""
    if batch_size % TILE != 0:
        raise ValueError(f"batch_size {batch_size} not a multiple of {TILE}")

    @jax.jit
    def fn(midstate, tail_w, base_nonce):
        return pallas_sweep_core(midstate, tail_w, base_nonce,
                                 batch_size=batch_size,
                                 difficulty_bits=difficulty_bits,
                                 interpret=interpret,
                                 early_exit=early_exit)
    return fn
