"""Pallas TPU kernel: fused double-SHA-256 nonce sweep.

The hot op of the framework (SURVEY.md §7 step 5). Design, per the TPU
kernel playbook:

  * Grid over nonce tiles; each program sweeps a (ROWS, 128) uint32 tile of
    nonces resident in VMEM — 128 lanes to match the VPU, ROWS sublanes to
    amortize control overhead. No HBM traffic inside the kernel at all: the
    nonce values are synthesized from program_id with iota, and only the
    per-tile (count, min_nonce) reduction leaves the chip.
  * Both compressions are fully unrolled straight-line vector code (Mosaic
    compiles this quickly, unlike the XLA CPU backend) with the rotating
    16-word schedule window, so the live set is ~24 (ROWS,128) u32 registers.
  * The EXTENDED midstate (ops/sha256_sched.py) arrives via scalar prefetch
    (SMEM): chunk-1 midstate, the nonce-invariant state entering round 4,
    the folded round-3 constants, and the w16/w17/rc18/rc19 schedule
    prefix. Hash 1 therefore runs a 60-round residue; only the nonce word
    varies per lane.
  * Uniform terms are summed BEFORE vector terms everywhere (``_usum``):
    template scalars stay on the scalar core, compile-time chunk-2/padding
    constants fold at trace time (numpy), and a uniform sum that folds to
    exactly zero is elided rather than added.
  * The second compression materializes only digest words 0-1 — all
    ``difficulty_mask`` reads — so its final round skips the e-chain
    update and six of the eight feed-forward adds (h1's add is also
    elided when difficulty_bits <= 32 never reads it).

The per-nonce op count of ``_tile_result`` is a committed, ratcheted
budget: OPBUDGET.json pins the traced jaxpr census (the scoreboard
``experiments/roofline.py --write-budget`` moves) and chainlint's
``opbudget`` pass recomputes a static proxy on every run. Edits that add
vector work here fail `make check` until the budget diff is reviewed.

Bit-exactness: identical round algebra to core/src/sha256.cpp
(sha256d_from_midstate) — uint32 modular addition is associative, so the
uniform-first regrouping is exact; verified against the C++ oracle in
tests/test_pallas.py, tests/test_kernel_equivalence.py and, on real TPU,
by the backend-equivalence suite.

Measured scaling (v5e single chip, axon tunnel, 2026-07-29): dispatch
overhead dominates below ~2^26 nonces/dispatch (2^20 ≈ 12 MH/s, 2^22 ≈
50 MH/s); the kernel saturates the VPU from 2^26 up (967 MH/s at 2^28 with
the round-4 round algebra). Callers that care about throughput must batch
big — see bench.py — or stay device-resident (models/fused.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .sha256_jnp import IV, K, NOT_FOUND_U32
from .sha256_sched import (CHUNK2_TAIL_CONST, DIGEST_PAD_CONST, EXT_A0,
                           EXT_A1, EXT_A2, EXT_E0, EXT_E1, EXT_E2, EXT_RC18,
                           EXT_RC19, EXT_RC_A, EXT_RC_E, EXT_W16, EXT_W17,
                           EXT_WORDS, extend_midstate)

_U32 = jnp.uint32
_LANES = 128
_ROWS = 64                      # 64*128 = 8192 nonces per grid program
TILE = _ROWS * _LANES


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _bswap32(x):
    return ((x & np.uint32(0xFF)) << np.uint32(24)) \
         | ((x & np.uint32(0xFF00)) << np.uint32(8)) \
         | ((x >> np.uint32(8)) & np.uint32(0xFF00)) \
         | (x >> np.uint32(24))


def _sigma0(x):
    return _rotr(x, 7) ^ _rotr(x, 18) ^ (x >> np.uint32(3))


def _sigma1(x):
    return _rotr(x, 17) ^ _rotr(x, 19) ^ (x >> np.uint32(10))


def _usum(*terms):
    """Sum of uint32 terms, uniform terms first — the constant-folding
    seam of the kernel. Uniform (0-d: numpy constants and SMEM scalar
    reads) terms are summed before any vector term joins, so all-uniform
    partial sums fold at trace time or stay on the scalar core, and each
    vector term costs exactly one (ROWS, LANES) add; a uniform partial
    sum that is concretely zero is skipped outright. Exact under uint32
    modular arithmetic (addition is associative and commutative)."""
    uni = [t for t in terms if np.ndim(t) == 0
           and not (isinstance(t, (int, np.integer)) and int(t) == 0)]
    vec = [t for t in terms if np.ndim(t) != 0]
    acc = None
    for t in uni:
        acc = t if acc is None else acc + t
    if acc is not None and isinstance(acc, (int, np.integer)) \
            and int(acc) == 0:
        acc = None
    for t in vec:
        acc = t if acc is None else acc + t
    return np.uint32(0) if acc is None else acc


def _h1_tail_rounds(state, w):
    """Rounds 4..63 of the chunk-2 compression from the extended
    midstate (rounds 0..3 are per-template precompute).

    state: the 8 state words entering round 4 — a3/e3 vector, the rest
    uniform SMEM scalars; w: the 16-word window aligned at word 4
    (w[i - 4] == W[i]), expansions appended in place. Returns the 8
    post-round-63 words WITHOUT the feed-forward add (the caller adds
    the original midstate — the entry state here is not it).

    Round-function algebra (measured +4% at the 2^28-batch VPU plateau):
      * ch(e,f,g)  = g ^ (e & (f ^ g))          — 3 ops vs 4
      * maj(a,b,c) = b ^ ((a^b) & (b^c))        — and this round's b^c is
        last round's a^b, so one xor+and+xor with a cached term vs 5 ops
      * w[r+16] is only expanded while some future round consumes it
        (r+16 < 64); the classic rotating window wastes 16 expansions.
    """
    a, b, c, d, e, f, g, h = state
    ab_prev = None
    # errstate: uniform inputs are numpy scalars whose modular uint32 adds
    # fold at trace time; the wraparound is the algorithm, not an error.
    with np.errstate(over="ignore"):
        for r in range(4, 64):
            wi = w[r - 4]
            S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = g ^ (e & (f ^ g))
            t1 = _usum(h, S1, ch, np.uint32(K[r]), wi)
            S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            ab = a ^ b
            bc = (b ^ c) if ab_prev is None else ab_prev
            maj = b ^ (ab & bc)
            ab_prev = ab
            t2 = _usum(S0, maj)
            h, g, f, e = g, f, e, _usum(d, t1)
            d, c, b, a = c, b, a, _usum(t1, t2)
            # W[r+16] = W[r] + s0(W[r+1]) + W[r+9] + s1(W[r+14])
            if r + 16 < 64:
                w.append(_usum(wi, _sigma0(w[r - 3]), w[r + 5],
                               _sigma1(w[r + 10])))
        return a, b, c, d, e, f, g, h


def _h2_digest_h01(d1, *, need_h1: bool):
    """The second compression, specialized to digest words 0-1 — all the
    difficulty mask ever reads (h0 = a63 + IV[0], h1 = a62 + IV[1]).

    The a-chain's last two values are the LAST thing the compression
    produces, so every round still runs — but the final round's e-chain
    update exists only for h4..h7 and is elided, as are the feed-forward
    adds of words 2..7 (and word 1's when difficulty_bits <= 32 never
    reads h1). Message: the 8 digest-1 words + the fixed 256-bit padding
    (compile-time constants, so the early rounds' uniform terms and the
    schedule's constant expansion terms fold at trace time).
    """
    w = list(d1) + [np.uint32(v) for v in DIGEST_PAD_CONST]
    a, b, c, d, e, f, g, h = (np.uint32(v) for v in IV)
    ab_prev = None
    a_prev = None
    with np.errstate(over="ignore"):
        for r in range(64):
            wi = w[r]
            S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = g ^ (e & (f ^ g))
            t1 = _usum(h, S1, ch, np.uint32(K[r]), wi)
            S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            ab = a ^ b
            bc = (b ^ c) if ab_prev is None else ab_prev
            maj = b ^ (ab & bc)
            ab_prev = ab
            t2 = _usum(S0, maj)
            if r < 63:
                h, g, f, e = g, f, e, _usum(d, t1)
                d, c, b, a = c, b, a, _usum(t1, t2)
            else:
                a_prev = a                      # a62 — feeds h1 only
                a = _usum(t1, t2)               # a63 — feeds h0
            if r + 16 < 64:
                w.append(_usum(wi, _sigma0(w[r + 1]), w[r + 9],
                               _sigma1(w[r + 14])))
        h0 = _usum(a, np.uint32(IV[0]))
        h1 = _usum(a_prev, np.uint32(IV[1])) if need_h1 else None
    return h0, h1


def _tile_result(ext_ref, base, *, difficulty_bits: int):
    """(count, biased_min) for the 8192-nonce tile starting at base.

    ext_ref holds the EXT_WORDS-word extended midstate
    (sha256_sched.extend_midstate) as uniform SMEM scalars — only the
    nonce word is a vector. jnp promotion plus ``_usum``'s uniform-first
    grouping keep every all-uniform intermediate on the scalar core and
    fold the all-constant parts at trace time; what remains is the
    per-nonce residue OPBUDGET.json budgets.
    """
    row = jax.lax.broadcasted_iota(_U32, (_ROWS, _LANES), 0)
    lane = jax.lax.broadcasted_iota(_U32, (_ROWS, _LANES), 1)
    nonces = base + row * np.uint32(_LANES) + lane

    with np.errstate(over="ignore"):
        # Chunk 2 of the first hash, from round 4: round 3 is the two
        # folded adds, the schedule prefix arrives precomputed.
        w3 = _bswap32(nonces)
        a3 = _usum(ext_ref[EXT_RC_A], w3)
        e3 = _usum(ext_ref[EXT_RC_E], w3)
        w18 = _usum(ext_ref[EXT_RC18], _sigma0(w3))
        w19 = _usum(w3, ext_ref[EXT_RC19])
        window = [np.uint32(v) for v in CHUNK2_TAIL_CONST] \
            + [ext_ref[EXT_W16], ext_ref[EXT_W17], w18, w19]
        st4 = (a3, ext_ref[EXT_A2], ext_ref[EXT_A1], ext_ref[EXT_A0],
               e3, ext_ref[EXT_E2], ext_ref[EXT_E1], ext_ref[EXT_E0])
        out = _h1_tail_rounds(st4, window)
        # Feed-forward against the ORIGINAL chunk-1 midstate.
        d1 = [_usum(o, ext_ref[i]) for i, o in enumerate(out)]
        h0, h1 = _h2_digest_h01(d1, need_h1=difficulty_bits > 32)

    # Leading-zero-bits difficulty check on the big-endian digest.
    dbits = int(difficulty_bits)
    if dbits <= 0:
        qual = jnp.ones_like(h0, dtype=jnp.bool_)
    elif dbits < 32:
        qual = h0 < np.uint32(1 << (32 - dbits))
    elif dbits == 32:
        qual = h0 == np.uint32(0)
    elif dbits < 64:
        qual = (h0 == np.uint32(0)) & (h1 < np.uint32(1 << (64 - dbits)))
    else:
        qual = (h0 == np.uint32(0)) & (h1 == np.uint32(0))

    # Mosaic has no unsigned reductions, so the min runs on bias-flipped
    # int32 (x ^ 0x80000000 is order-isomorphic uint32 -> int32); the
    # caller unbiases. The 0xFFFFFFFF sentinel biases to int32 max — the
    # identity.
    count = jnp.sum(qual.astype(jnp.int32))
    biased = jax.lax.bitcast_convert_type(
        jnp.where(qual, nonces, NOT_FOUND_U32) ^ np.uint32(0x80000000),
        jnp.int32)
    return count, jnp.min(biased)


def _sweep_kernel(ext_ref, base_ref, count_ref, min_ref, *,
                  difficulty_bits: int, early_exit: bool):
    """Grid sweep: one tile per program, sequential on the core.

    Programs accumulate into one (1,1) SMEM cell: initialize at program 0,
    then reduce. With early_exit, tiles after the first qualifying one skip
    their hash work (tiles are ascending, so min_nonce cannot change).
    """
    pid = pl.program_id(0)

    @pl.when(pid == 0)
    def _():
        count_ref[0, 0] = jnp.int32(0)
        min_ref[0, 0] = jnp.int32(0x7FFFFFFF)

    def tile():
        base = base_ref[0] + (pid * np.uint32(TILE)).astype(_U32)
        c, m = _tile_result(ext_ref, base, difficulty_bits=difficulty_bits)
        count_ref[0, 0] += c
        min_ref[0, 0] = jnp.minimum(min_ref[0, 0], m)

    if early_exit:
        @pl.when(count_ref[0, 0] == 0)
        def _():
            tile()
    else:
        tile()


def _out_vma(*xs) -> frozenset:
    """Union of the inputs' varying-manual-axes (vma) sets.

    Under shard_map with check_vma=True (the JAX >= 0.9 default), pallas
    outputs must declare which mesh axes they vary over; they inherit the
    union of the inputs' axes (the per-device base_nonce carries the
    'miners' axis). Outside shard_map — or on a JAX predating the vma
    machinery, where jax.typeof does not exist — every set is empty.
    Unit-tested under a real check_vma=True trace in
    tests/test_pallas_interpret.py (the interpret-mode pallas execution
    path cannot carry vma itself; see that module's docstring)."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return frozenset().union(*(getattr(typeof(x), "vma", frozenset())
                               for x in xs))


def pallas_sweep_core_ext(ext, base_nonce, *, batch_size: int,
                          difficulty_bits: int, interpret: bool = False,
                          early_exit: bool = False):
    """Sweeps [base_nonce, base_nonce + batch_size) on one TPU core from
    an (EXT_WORDS,) extended-midstate payload.

    Same contract as sha256_jnp.sweep_core_ext: returns (count,
    min_nonce). batch_size must be a multiple of the 8192-nonce tile.
    With early_exit=True, tiles after the first qualifying tile are
    skipped: min_nonce is unchanged (lowest-nonce determinism holds) but
    count is only exact up to that tile — use where count is just a
    found-flag.
    """
    if batch_size % TILE != 0:
        raise ValueError(f"batch_size {batch_size} not a multiple of {TILE}")
    n_tiles = batch_size // TILE

    # A single-program lax.while_loop-over-tiles variant of the early-exit
    # kernel was hardware-benchmarked in round 4 (experiments/hw_round4.py)
    # against this grid + skip-predicate form: identical tips, timing a tie
    # within tunnel noise over 4 rep pairs (grid 1.85-2.55 s, while
    # 1.84-2.16 s per 100 diff-24 blocks), so the extra implementation was
    # deleted rather than kept as an env-selected alternate.
    kernel = functools.partial(_sweep_kernel,
                               difficulty_bits=difficulty_bits,
                               early_exit=early_exit)
    grid = (n_tiles,)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,      # extended midstate, base — SMEM scalars
        grid=grid,
        in_specs=[],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i, *_: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i, *_: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
    )
    xt = jnp.asarray(ext, _U32)
    bn = jnp.asarray(base_nonce, _U32).reshape((1,))
    # Only pass the kwarg when non-empty, so JAX versions without
    # ShapeDtypeStruct(vma=...) keep working outside shard_map.
    vma = _out_vma(xt, bn)
    vma_kw = {"vma": vma} if vma else {}
    count, min_biased = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((1, 1), jnp.int32, **vma_kw),
                   jax.ShapeDtypeStruct((1, 1), jnp.int32, **vma_kw)],
        grid_spec=grid_spec,
        interpret=interpret,
    )(xt, bn)
    min_nonce = jax.lax.bitcast_convert_type(
        min_biased[0, 0], _U32) ^ np.uint32(0x80000000)
    return count[0, 0], min_nonce


def pallas_sweep_core(midstate, tail_w, base_nonce, *, batch_size: int,
                      difficulty_bits: int, interpret: bool = False,
                      early_exit: bool = False):
    """(midstate, tail) convenience flavor of ``pallas_sweep_core_ext``:
    extends the midstate inline (numpy callers fold it on the host at
    trace time; traced callers pay a handful of scalar ops per dispatch).
    The production paths extend once per template and call the ext core
    directly (host: backend/tpu.py, device: models/fused.py)."""
    if batch_size % TILE != 0:
        raise ValueError(f"batch_size {batch_size} not a multiple of {TILE}")
    ext = extend_midstate(midstate, tail_w)
    return pallas_sweep_core_ext(ext, base_nonce, batch_size=batch_size,
                                 difficulty_bits=difficulty_bits,
                                 interpret=interpret, early_exit=early_exit)


def make_pallas_sweep_fn(batch_size: int, difficulty_bits: int,
                         interpret: bool = False, early_exit: bool = False):
    """jit'd (midstate, tail_w, base_nonce) -> (count, min_nonce)."""
    if batch_size % TILE != 0:
        raise ValueError(f"batch_size {batch_size} not a multiple of {TILE}")

    @jax.jit
    def fn(midstate, tail_w, base_nonce):
        return pallas_sweep_core(midstate, tail_w, base_nonce,
                                 batch_size=batch_size,
                                 difficulty_bits=difficulty_bits,
                                 interpret=interpret,
                                 early_exit=early_exit)
    return fn
