"""Device-side sha256d sweep kernels.

Two implementations of the same op, bit-exact with the C++ core:
  sha256_jnp    — pure jax.numpy, fully XLA-fused (portable: cpu/tpu)
  sha256_pallas — hand-tiled Pallas TPU kernel (VMEM-resident rounds)

Both consume the EXTENDED midstate produced by
``sha256_sched.extend_midstate`` from ``core.header_midstate``'s
(midstate, tail) pair, so the per-nonce cost is the nonce-dependent
residue of the two SHA-256 compressions: hash 1 enters at round 4 with
the nonce-invariant schedule prefix precomputed per template, and hash 2
materializes only the digest words the difficulty mask reads (SURVEY.md
§7 step 5 midstate optimization, extended per ISSUE 15 / AsicBoost).
"""
from __future__ import annotations

import functools

from .sha256_jnp import (make_sweep_fn, sweep_core,  # noqa: F401
                         sweep_core_ext, sweep_jnp)
from .sha256_sched import EXT_WORDS, extend_midstate  # noqa: F401


def select_kernel(kernel: str, batch_size: int, difficulty_bits: int,
                  shard: bool = False, early_exit: bool = False):
    """Resolves the sweep kernel policy in ONE place (backends + mesh).

    kernel: {"auto", "jnp", "pallas"}; auto => pallas on a real TPU, jnp
    elsewhere. Returns (fn, effective_kernel_name). With shard=False the fn
    is jit'd and callable from the host as (midstate, tail_w, base_nonce);
    with shard=True it is the unjitted EXT core (ext, base) ->
    (count, min_nonce) for use inside shard_map — the caller supplies the
    extended-midstate payload (``extend_midstate``: once per template on
    the host in backend/tpu.py, once per block on-device in
    models/fused.py). Only an "auto" choice falls back from pallas to jnp
    (with a visible warning, so bench labels stay honest); an EXPLICIT
    "pallas" request that cannot be honored raises ConfigError — a user's
    explicit choice must never silently degrade.

    early_exit=True (pallas only — the jnp kernel ignores it and sweeps the
    full batch) skips tiles past the first qualifying one: min_nonce stays
    exact, count degrades to a found-flag. For mine loops, not benches.
    """
    import jax

    from ..config import ConfigError

    requested = kernel
    if kernel == "auto":
        kernel = "pallas" if jax.default_backend() == "tpu" else "jnp"
    if kernel == "pallas":
        try:
            from .sha256_pallas import (TILE, make_pallas_sweep_fn,
                                        pallas_sweep_core_ext)
            # Eager checks, so bad requests surface here instead of
            # raising mid-trace inside a caller's mine loop: Mosaic can
            # only lower on a real TPU, and batches must tile evenly.
            if jax.default_backend() != "tpu":
                raise ConfigError(
                    f"kernel='pallas' requires a TPU platform (current: "
                    f"{jax.default_backend()})")
            if batch_size % TILE != 0:
                raise ConfigError(
                    f"batch_size {batch_size} not a multiple of {TILE}")
            if shard:
                return functools.partial(
                    pallas_sweep_core_ext, batch_size=batch_size,
                    difficulty_bits=difficulty_bits,
                    early_exit=early_exit), "pallas"
            return make_pallas_sweep_fn(batch_size, difficulty_bits,
                                        early_exit=early_exit), "pallas"
        except Exception as e:
            if requested == "pallas":
                if isinstance(e, ConfigError):
                    raise
                raise ConfigError(
                    f"kernel='pallas' requested but unavailable "
                    f"({type(e).__name__}: {e})") from e
            from ..utils.logging import get_logger
            get_logger().warning(
                "pallas sweep kernel unavailable (%s: %s); falling back to "
                "the jnp kernel", type(e).__name__, e)
            kernel = "jnp"
    if kernel != "jnp":
        raise ConfigError(f"unknown sweep kernel {kernel!r}")
    if shard:
        return (lambda ext, base: sweep_core_ext(
            ext, base, batch_size, difficulty_bits)), "jnp"
    return make_sweep_fn(batch_size, difficulty_bits), "jnp"
