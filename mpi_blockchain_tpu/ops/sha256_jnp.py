"""Pure-jnp vectorized double-SHA-256 nonce sweep.

TPU-first design notes (SURVEY.md §7 step 4):
  * Everything is uint32 vector ALU work on the VPU — there is no matmul in
    SHA-256, so the MXU is idle by construction; the win over the CPU is the
    (8,128)-lane vector unit sweeping a whole nonce batch per instruction.
  * The 64 rounds x 2 compressions are Python-unrolled at trace time into a
    flat chain of elementwise uint32 ops; XLA fuses the entire sweep into one
    kernel, keeping all per-nonce state in registers/VMEM (HBM traffic is just
    the nonce batch in and two scalars out).
  * No data-dependent control flow: a fixed-size batch is swept, reduced to
    (count, min qualifying nonce), and the host decides whether to continue —
    the jit-compatible replacement for the reference's `break` (SURVEY.md §3.4).

Bit-exactness contract: given the midstate/tail from core.header_midstate,
this computes exactly sha256d(header) for each nonce, matching the C++
sha256d_from_midstate.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# FIPS 180-4 round constants / IV (same values as core/src/sha256.cpp).
K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2], dtype=np.uint32)

IV = np.array([0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
               0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19],
              dtype=np.uint32)

_U32 = jnp.uint32
NOT_FOUND_U32 = np.uint32(0xFFFFFFFF)

# The nonce's position in the header's second SHA-256 chunk: byte offset
# 76 of the frozen layout (chain.hpp) = 64 + NONCE_WORD_INDEX * 4. Both
# device kernels substitute the swept nonce at this word; chainlint HDR004
# cross-checks the value against the C++ struct layout.
NONCE_WORD_INDEX = 3


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _bswap32(x):
    return ((x & np.uint32(0xFF)) << np.uint32(24)) \
         | ((x & np.uint32(0xFF00)) << np.uint32(8)) \
         | ((x >> np.uint32(8)) & np.uint32(0xFF00)) \
         | (x >> np.uint32(24))


def compress(state, w, unroll: int = 8):
    """One SHA-256 compression.

    state: tuple/list of 8 uint32 arrays, all of one shape B
    w:     list of 16 uint32 arrays (message words), each of shape B
    Returns the 8 updated state words.

    Implemented as two lax.scans (message schedule, then the 64 rounds) so
    the traced graph stays tiny: a fully Python-unrolled version takes XLA's
    CPU backend minutes to compile. `unroll` gives XLA straight-line chunks
    to software-pipeline without exploding the graph.
    """
    shape = jnp.shape(w[3]) if jnp.ndim(w[3]) else ()
    W16 = jnp.stack([jnp.broadcast_to(jnp.asarray(x, _U32), shape)
                     for x in w])  # (16, *B)
    # Under shard_map the nonce word varies over the mesh axis while the
    # midstate/IV are replicated; xor-ing a varying zero into the scan carry
    # makes its varying-axes type match the per-round outputs.
    vzero = W16[3] & np.uint32(0)

    # One scan fuses the message schedule into the rounds with a rotating
    # 16-word window (window[k] == w[round+k]), so the live state per nonce
    # is 24 uint32 words — never a materialized (64, B) schedule, which at
    # mining batch sizes would cost O(GiB) of HBM.
    def round_step(carry, k):
        window, (a, b, c, d, e, f, g, h) = carry
        wi = window[0]
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + k + wi
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = S0 + maj
        # Schedule: w[r+16] = w[r] + s0(w[r+1]) + w[r+9] + s1(w[r+14]).
        w1, w14 = window[1], window[14]
        s0 = _rotr(w1, 7) ^ _rotr(w1, 18) ^ (w1 >> np.uint32(3))
        s1 = _rotr(w14, 17) ^ _rotr(w14, 19) ^ (w14 >> np.uint32(10))
        nxt = wi + s0 + window[9] + s1
        window = jnp.concatenate([window[1:], nxt[None]], axis=0)
        return (window, (t1 + t2, a, b, c, d + t1, e, f, g)), None

    st = tuple(jnp.broadcast_to(jnp.asarray(s, _U32), shape) ^ vzero
               for s in state)
    (_, out), _ = jax.lax.scan(round_step, (W16, st), jnp.asarray(K, _U32),
                               unroll=unroll)
    return tuple(o + s for o, s in zip(out, st))


def sha256d_words_from_midstate(midstate, tail_w, nonce_word):
    """Double-SHA256 digest words for a batch of nonces.

    midstate:   (8,) uint32 — state after header chunk 1
    tail_w:     (16,) uint32 — chunk-2 word template (word 3 ignored)
    nonce_word: uint32 array, arbitrary shape B — ALREADY byte-swapped
                (big-endian word of the little-endian nonce bytes)
    Returns 8 uint32 arrays of shape B: the final digest words h0..h7
    (digest bytes are their big-endian concatenation).
    """
    st = tuple(midstate[i] for i in range(8))
    w = [tail_w[i] if i != NONCE_WORD_INDEX else nonce_word
         for i in range(16)]
    d1 = compress(st, w)
    # Second hash: digest-1 words are the message words directly (the digest
    # bytes are their BE encoding, and SHA reads words BE — no swap).
    zero = np.uint32(0)
    w2 = list(d1) + [np.uint32(0x80000000),
                     zero, zero, zero, zero, zero, zero,
                     np.uint32(32 * 8)]
    return compress(tuple(IV), w2)


def difficulty_mask(digest_words, difficulty_bits: int):
    """True where the 256-bit BE digest has >= difficulty_bits leading zeros.

    difficulty_bits is static (compiled per difficulty). Supports 0..64,
    which covers every BASELINE config (max 24) with headroom.
    """
    h0, h1 = digest_words[0], digest_words[1]
    d = int(difficulty_bits)
    if d <= 0:
        return jnp.ones_like(h0, dtype=bool)
    if d < 32:
        return h0 < np.uint32(1 << (32 - d))
    if d == 32:
        return h0 == np.uint32(0)
    if d < 64:
        return (h0 == np.uint32(0)) & (h1 < np.uint32(1 << (64 - d)))
    if d == 64:
        return (h0 == np.uint32(0)) & (h1 == np.uint32(0))
    from ..config import ConfigError
    raise ConfigError(f"difficulty_bits {d} > 64 unsupported")


def sweep_core(midstate, tail_w, base_nonce, batch_size: int,
               difficulty_bits: int):
    """Sweeps nonces [base_nonce, base_nonce + batch_size). Unjitted.

    Returns (count, min_nonce): number of qualifying nonces in the batch and
    the lowest one (0xFFFFFFFF when count == 0 — disambiguated by count, so
    the real nonce 0xFFFFFFFF is handled correctly). Callable inside jit,
    vmap, or shard_map (the mesh winner-select wraps exactly this).
    """
    nonces = jnp.asarray(base_nonce).astype(_U32) \
        + jnp.arange(batch_size, dtype=_U32)
    digest = sha256d_words_from_midstate(jnp.asarray(midstate).astype(_U32),
                                         jnp.asarray(tail_w).astype(_U32),
                                         _bswap32(nonces))
    qual = difficulty_mask(digest, difficulty_bits)
    count = jnp.sum(qual.astype(jnp.int32))
    min_nonce = jnp.min(jnp.where(qual, nonces, NOT_FOUND_U32))
    return count, min_nonce


@functools.partial(jax.jit, static_argnames=("batch_size", "difficulty_bits"))
def sweep_jnp(midstate, tail_w, base_nonce, *, batch_size: int,
              difficulty_bits: int):
    """jit'd single-device sweep (see sweep_core)."""
    return sweep_core(midstate, tail_w, base_nonce, batch_size,
                      difficulty_bits)


def make_sweep_fn(batch_size: int, difficulty_bits: int):
    """Returns sweep(midstate, tail_w, base_nonce) with static args bound."""
    return functools.partial(sweep_jnp, batch_size=batch_size,
                             difficulty_bits=difficulty_bits)
