"""Pure-jnp vectorized double-SHA-256 nonce sweep.

TPU-first design notes (SURVEY.md §7 step 4):
  * Everything is uint32 vector ALU work on the VPU — there is no matmul in
    SHA-256, so the MXU is idle by construction; the win over the CPU is the
    (8,128)-lane vector unit sweeping a whole nonce batch per instruction.
  * The rounds x 2 compressions are Python-unrolled at trace time into a
    flat chain of elementwise uint32 ops; XLA fuses the entire sweep into one
    kernel, keeping all per-nonce state in registers/VMEM (HBM traffic is just
    the nonce batch in and two scalars out).
  * No data-dependent control flow: a fixed-size batch is swept, reduced to
    (count, min qualifying nonce), and the host decides whether to continue —
    the jit-compatible replacement for the reference's `break` (SURVEY.md §3.4).
  * Per-nonce work is the EXTENDED-midstate residue (ops/sha256_sched.py):
    hash 1 enters at round 4 from the per-template round-3 fold (the scan
    runs 60 rounds, not 64), the nonce-invariant schedule prefix
    (w16/w17/rc18/rc19) arrives precomputed, and only digest words 0-1 —
    the only words ``difficulty_mask`` reads — are materialized from the
    second compression.

Bit-exactness contract: given the midstate/tail from core.header_midstate,
this computes exactly sha256d(header) for each nonce, matching the C++
sha256d_from_midstate (uint32 modular addition is associative, so the
extended-midstate regrouping is exact; pinned by the cross-flavor
equivalence fuzz suite in tests/test_kernel_equivalence.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Constants + the frozen chunk-2 layout live with the per-template
# precompute; re-exported here for the existing import surface.
from .sha256_sched import (CHUNK2_TAIL_CONST, DIGEST_PAD_CONST,  # noqa: F401
                           EXT_A0, EXT_A1, EXT_A2, EXT_E0, EXT_E1, EXT_E2,
                           EXT_RC18, EXT_RC19, EXT_RC_A, EXT_RC_E, EXT_W16,
                           EXT_W17, EXT_WORDS, IV, K, NONCE_WORD_INDEX,
                           NOT_FOUND_U32, _rotr, _sigma0, _sigma1,
                           extend_midstate)

_U32 = jnp.uint32


def _bswap32(x):
    return ((x & np.uint32(0xFF)) << np.uint32(24)) \
         | ((x & np.uint32(0xFF00)) << np.uint32(8)) \
         | ((x >> np.uint32(8)) & np.uint32(0xFF00)) \
         | (x >> np.uint32(24))


def compress(state, w, unroll: int = 8, rounds=None, feedforward=None,
             vzero_index: int = 3, out_words: int = 8):
    """One SHA-256 compression (optionally a round suffix of one).

    state: tuple/list of 8 uint32 arrays, all of one shape B
    w:     list of 16 uint32 arrays (message words), each of shape B
    rounds: the K-slice to scan (default the full 64). A suffix call
            passes ``K[4:]`` with ``w`` aligned at word 4 — the rotating
            window is position-relative, so the same scan body serves
            both (the extended-midstate path enters at round 4).
    feedforward: the 8 words added after the last round (SHA's
            feed-forward). Defaults to ``state``; a suffix call passes
            the ORIGINAL midstate, which is not the entry state.
    vzero_index: which w word donates the varying-zero used to align
            the scan carry's varying-axes type under shard_map (must
            name a nonce-dependent word: 3 for a full compression over
            a chunk-2 template, 15 (= w19) for the suffix call).
    out_words: leading digest words to return (2 = just h0/h1, all the
            difficulty mask reads).
    Returns the ``out_words`` updated state words.

    Implemented as one lax.scan so the traced graph stays tiny: a fully
    Python-unrolled version takes XLA's CPU backend minutes to compile.
    `unroll` gives XLA straight-line chunks to software-pipeline without
    exploding the graph.
    """
    shape = jnp.shape(w[vzero_index]) if jnp.ndim(w[vzero_index]) else ()
    W16 = jnp.stack([jnp.broadcast_to(jnp.asarray(x, _U32), shape)
                     for x in w])  # (16, *B)
    # Under shard_map the nonce word varies over the mesh axis while the
    # midstate/IV are replicated; xor-ing a varying zero into the scan carry
    # makes its varying-axes type match the per-round outputs.
    vzero = W16[vzero_index] & np.uint32(0)

    # One scan fuses the message schedule into the rounds with a rotating
    # 16-word window (window[k] == w[round+k]), so the live state per nonce
    # is 24 uint32 words — never a materialized (64, B) schedule, which at
    # mining batch sizes would cost O(GiB) of HBM.
    def round_step(carry, k):
        window, (a, b, c, d, e, f, g, h) = carry
        wi = window[0]
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + k + wi
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = S0 + maj
        # Schedule: w[r+16] = w[r] + s0(w[r+1]) + w[r+9] + s1(w[r+14]).
        nxt = wi + _sigma0(window[1]) + window[9] + _sigma1(window[14])
        window = jnp.concatenate([window[1:], nxt[None]], axis=0)
        return (window, (t1 + t2, a, b, c, d + t1, e, f, g)), None

    ks = jnp.asarray(K if rounds is None else rounds, _U32)
    st = tuple(jnp.broadcast_to(jnp.asarray(s, _U32), shape) ^ vzero
               for s in state)
    (_, out), _ = jax.lax.scan(round_step, (W16, st), ks, unroll=unroll)
    ff = state if feedforward is None else feedforward
    return tuple(o + jnp.asarray(s, _U32)
                 for o, s in zip(out[:out_words], ff))


def sha256d_words_from_midstate(midstate, tail_w, nonce_word):
    """Double-SHA256 digest words for a batch of nonces.

    midstate:   (8,) uint32 — state after header chunk 1
    tail_w:     (16,) uint32 — chunk-2 word template (word 3 ignored)
    nonce_word: uint32 array, arbitrary shape B — ALREADY byte-swapped
                (big-endian word of the little-endian nonce bytes)
    Returns 8 uint32 arrays of shape B: the final digest words h0..h7
    (digest bytes are their big-endian concatenation).
    """
    st = tuple(midstate[i] for i in range(8))
    w = [tail_w[i] if i != NONCE_WORD_INDEX else nonce_word
         for i in range(16)]
    d1 = compress(st, w)
    # Second hash: digest-1 words are the message words directly (the digest
    # bytes are their BE encoding, and SHA reads words BE — no swap).
    w2 = list(d1) + [np.uint32(v) for v in DIGEST_PAD_CONST]
    return compress(tuple(IV), w2)


def sha256d_h01_from_ext(ext, nonce_word):
    """Digest words h0, h1 — all ``difficulty_mask`` reads — from the
    extended midstate (``sha256_sched.extend_midstate``).

    Hash 1 runs only its 60-round residue: round 3 is the two folded
    adds ``rc_a + w3`` / ``rc_e + w3``, the window enters at word 4 with
    the precomputed w16/w17 and the rc18/rc19 partial sums, and the scan
    consumes K[4:]. Hash 2 is a full compression of the 8 digest words
    but materializes only its first two feed-forward outputs.
    """
    w3 = nonce_word
    a3 = ext[EXT_RC_A] + w3
    e3 = ext[EXT_RC_E] + w3
    w18 = ext[EXT_RC18] + _sigma0(w3)
    w19 = w3 + ext[EXT_RC19]
    window = [np.uint32(v) for v in CHUNK2_TAIL_CONST] \
        + [ext[EXT_W16], ext[EXT_W17], w18, w19]
    st4 = (a3, ext[EXT_A2], ext[EXT_A1], ext[EXT_A0],
           e3, ext[EXT_E2], ext[EXT_E1], ext[EXT_E0])
    d1 = compress(st4, window, rounds=K[4:],
                  feedforward=[ext[i] for i in range(8)], vzero_index=15)
    w2 = list(d1) + [np.uint32(v) for v in DIGEST_PAD_CONST]
    return compress(tuple(IV), w2, out_words=2)


def difficulty_mask(digest_words, difficulty_bits: int):
    """True where the 256-bit BE digest has >= difficulty_bits leading zeros.

    difficulty_bits is static (compiled per difficulty). Supports 0..64,
    which covers every BASELINE config (max 24) with headroom. Only
    digest words 0-1 are ever read — the early-exit contract the
    kernels' second compression is specialized around.
    """
    h0, h1 = digest_words[0], digest_words[1]
    d = int(difficulty_bits)
    if d <= 0:
        return jnp.ones_like(h0, dtype=bool)
    if d < 32:
        return h0 < np.uint32(1 << (32 - d))
    if d == 32:
        return h0 == np.uint32(0)
    if d < 64:
        return (h0 == np.uint32(0)) & (h1 < np.uint32(1 << (64 - d)))
    if d == 64:
        return (h0 == np.uint32(0)) & (h1 == np.uint32(0))
    from ..config import ConfigError
    raise ConfigError(f"difficulty_bits {d} > 64 unsupported")


def sweep_core_ext(ext, base_nonce, batch_size: int, difficulty_bits: int):
    """Sweeps nonces [base_nonce, base_nonce + batch_size) from an
    extended-midstate payload (``sha256_sched.extend_midstate``).
    Unjitted; same (count, min_nonce) contract as ``sweep_core``.
    Callable inside jit, vmap, or shard_map (the mesh winner-select
    wraps exactly this)."""
    nonces = jnp.asarray(base_nonce).astype(_U32) \
        + jnp.arange(batch_size, dtype=_U32)
    h01 = sha256d_h01_from_ext(jnp.asarray(ext).astype(_U32),
                               _bswap32(nonces))
    qual = difficulty_mask(h01, difficulty_bits)
    count = jnp.sum(qual.astype(jnp.int32))
    min_nonce = jnp.min(jnp.where(qual, nonces, NOT_FOUND_U32))
    return count, min_nonce


def sweep_core(midstate, tail_w, base_nonce, batch_size: int,
               difficulty_bits: int):
    """Sweeps nonces [base_nonce, base_nonce + batch_size). Unjitted.

    Returns (count, min_nonce): number of qualifying nonces in the batch and
    the lowest one (0xFFFFFFFF when count == 0 — disambiguated by count, so
    the real nonce 0xFFFFFFFF is handled correctly). Convenience wrapper
    that extends the midstate inline; the production paths extend once per
    template (host: backend/tpu.py, device: models/fused.py) and call
    ``sweep_core_ext`` directly.
    """
    ext = extend_midstate(jnp.asarray(midstate).astype(_U32),
                          jnp.asarray(tail_w).astype(_U32))
    return sweep_core_ext(ext, base_nonce, batch_size, difficulty_bits)


@functools.partial(jax.jit, static_argnames=("batch_size", "difficulty_bits"))
def sweep_jnp(midstate, tail_w, base_nonce, *, batch_size: int,
              difficulty_bits: int):
    """jit'd single-device sweep (see sweep_core)."""
    return sweep_core(midstate, tail_w, base_nonce, batch_size,
                      difficulty_bits)


def make_sweep_fn(batch_size: int, difficulty_bits: int):
    """Returns sweep(midstate, tail_w, base_nonce) with static args bound."""
    return functools.partial(sweep_jnp, batch_size=batch_size,
                             difficulty_bits=difficulty_bits)
