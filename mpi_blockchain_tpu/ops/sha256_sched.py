"""Per-template SHA-256d precompute: the extended midstate.

The chunk-1 midstate (``core.header_midstate``) already hoists the first
64 header bytes out of the sweep. This module hoists everything ELSE in
the double hash that is nonce-invariant per template (AsicBoost, arxiv
1604.00575; the inner-for-loop factoring of arxiv 1906.02770):

* **rounds 0..2 of the chunk-2 compression** — the nonce sits at word
  ``NONCE_WORD_INDEX`` (3), so the first three rounds consume only
  template words (data_hash[7], timestamp, bits) and the kernels can
  enter at round 3;
* **the round-3 constants** — round 3's t1 is ``C + w3`` with C
  template-constant, so the two state words it produces fold to
  ``rc_a + w3`` and ``rc_e + w3``: the whole round costs the kernels
  two vector adds;
* **the nonce-invariant message-schedule prefix** — the expansion
  recurrence w[i] = w[i-16] + s0(w[i-15]) + w[i-7] + s1(w[i-2]) first
  touches the nonce at w18 (via s0(w3)), so w16 and w17 are per-template
  constants, and the template-constant partial sums of w18 and w19
  (``rc18 = w2 + s1(w16)``, ``rc19 = s0(w4) + s1(w17)``) fold too.

``extend_midstate`` packs all of it into one ``EXT_WORDS``-word uint32
payload that rides the kernels' existing scalar-prefetch/SMEM path. It
is polymorphic: numpy in, numpy out (the host path — backend/tpu.py
extends once per template per dispatch, no jax import needed) and
traced-jnp in, traced out (models/fused.py extends on-device once per
block, amortized over the whole sweep).

Everything here is nonce-INVARIANT per template; the per-nonce op budget
(OPBUDGET.json, ``analysis/opbudget.py``) therefore counts this module's
work separately (``static_host_alu_ops`` / ``host_ops_per_template``)
from the kernels' per-nonce census — a hoist out of the tile registers
as a per-nonce decrease, not as moved-ops noise.

Bit-exactness: uint32 modular addition is associative, so every fold
here is exact; pinned against the C++ ``sha256d_from_midstate`` oracle
in tests/test_sched.py and the cross-flavor equivalence fuzz suite.

This module is also the single source of truth for the FIPS 180-4
constants (K, IV) and the frozen chunk-2 layout words; the jax kernels
import them from here (chainlint HDR004 cross-checks NONCE_WORD_INDEX
against the C++ struct layout in this file).
"""
from __future__ import annotations

import numpy as np

# FIPS 180-4 round constants / IV (same values as core/src/sha256.cpp).
K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2], dtype=np.uint32)

IV = np.array([0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
               0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19],
              dtype=np.uint32)

NOT_FOUND_U32 = np.uint32(0xFFFFFFFF)

# The nonce's position in the header's second SHA-256 chunk: byte offset
# 76 of the frozen layout (chain.hpp) = 64 + NONCE_WORD_INDEX * 4. Both
# device kernels substitute the swept nonce at this word; chainlint HDR004
# cross-checks the value against the C++ struct layout.
NONCE_WORD_INDEX = 3

# Chunk-2 words 4..15 are fixed by the frozen 80-byte layout, not by the
# template: 0x80000000 pad bit, zeros, 640-bit message length — exactly
# what core/src/sha256.cpp's header_midstate writes. Compile-time
# constants for the kernels (cross-checked against the C++ output in
# tests/test_sched.py).
CHUNK2_TAIL_CONST = np.array([0x80000000] + [0] * 10 + [80 * 8],
                             dtype=np.uint32)
# The second hash's message is the 32-byte digest + the same padding
# shape: words 8..15 are 0x80000000, zeros, 256-bit length.
DIGEST_PAD_CONST = np.array([0x80000000] + [0] * 6 + [32 * 8],
                            dtype=np.uint32)

# ---- extended-midstate payload layout (EXT_WORDS uint32 words) ------------
# [0:8]   the original chunk-1 midstate (hash 1's feed-forward terms)
# [8:14]  the six nonce-invariant state words entering round 4:
#         a2, a1, a0 (the a-chain) and e2, e1, e0 (the e-chain)
# [14]    rc_a: a3 = rc_a + w3   (round 3 folded onto the nonce word)
# [15]    rc_e: e3 = rc_e + w3
# [16]    w16  (nonce-invariant expansion)   — index == word, by design
# [17]    w17  (nonce-invariant expansion)
# [18]    rc18: w18 = rc18 + s0(w3)
# [19]    rc19: w19 = w3 + rc19
EXT_MS = 0
EXT_A2, EXT_A1, EXT_A0 = 8, 9, 10
EXT_E2, EXT_E1, EXT_E0 = 11, 12, 13
EXT_RC_A = 14
EXT_RC_E = 15
EXT_W16 = 16
EXT_W17 = 17
EXT_RC18 = 18
EXT_RC19 = 19
EXT_WORDS = 20


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _sigma0(x):
    """Schedule sigma0: rotr7 ^ rotr18 ^ (x >> 3)."""
    return _rotr(x, 7) ^ _rotr(x, 18) ^ (x >> np.uint32(3))


def _sigma1(x):
    """Schedule sigma1: rotr17 ^ rotr19 ^ (x >> 10)."""
    return _rotr(x, 17) ^ _rotr(x, 19) ^ (x >> np.uint32(10))


def extend_midstate(midstate, tail_w):
    """(EXT_WORDS,) uint32 extended-midstate payload for one template.

    midstate: (8,) uint32 — state after header chunk 1
    tail_w:   (16,) uint32 — chunk-2 word template (word 3 = nonce slot
              ignored; words 4..15 are the frozen layout constants)

    numpy in -> numpy out (host path); traced jnp in -> traced out
    (the fused miner's on-device per-block extension). All arithmetic is
    uint32 modular, bit-exact under any regrouping.
    """
    ms = [midstate[i] for i in range(8)]
    w0, w1, w2 = tail_w[0], tail_w[1], tail_w[2]
    # errstate: the numpy path's modular uint32 adds ARE the algorithm.
    with np.errstate(over="ignore"):
        a, b, c, d, e, f, g, h = ms
        for r, wi in enumerate((w0, w1, w2)):
            S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = g ^ (e & (f ^ g))
            t1 = h + S1 + ch + K[r] + wi
            S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            maj = b ^ ((a ^ b) & (b ^ c))
            t2 = S0 + maj
            h, g, f, e = g, f, e, d + t1
            d, c, b, a = c, b, a, t1 + t2
        # Round 3 folded onto the nonce word: t1 = t1c + w3, so the two
        # state words it produces are rc + w3 each.
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = g ^ (e & (f ^ g))
        t1c = h + S1 + ch + K[3]
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = b ^ ((a ^ b) & (b ^ c))
        rc_a = t1c + S0 + maj
        rc_e = d + t1c
        # Nonce-invariant schedule prefix (w9..w14 are zero, w15 = 640):
        w16 = w0 + _sigma0(w1)
        w17 = w1 + _sigma0(w2) + _sigma1(CHUNK2_TAIL_CONST[11])
        rc18 = w2 + _sigma1(w16)
        rc19 = _sigma0(CHUNK2_TAIL_CONST[0]) + _sigma1(w17)
        vals = ms + [a, b, c, e, f, g, rc_a, rc_e, w16, w17, rc18, rc19]
    if isinstance(midstate, np.ndarray):
        return np.array([np.uint32(v) for v in vals], dtype=np.uint32)
    import jax.numpy as jnp
    return jnp.stack([jnp.asarray(v, jnp.uint32) for v in vals])
