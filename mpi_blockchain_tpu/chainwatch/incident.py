"""chainwatch incident capture: the non-fatal evidence path.

When a rule fires, ``emit_incident`` does three things, none of which
may hurt the run that is still mining:

1. **Signal** — a structured ``incident`` event on the ring (it lands
   in shard ``events_tail``s, the flight recorder, and the forensics
   exporters) plus the ``incidents_total{rule,severity}`` counter.
2. **Record** — the open-episode table ``open_incidents()`` projects
   into shard payloads, ``/healthz`` and ``/incidents``.
3. **Bundle** — when an incident directory is armed, a bounded JSON
   evidence bundle built on ``flight_recorder.snapshot()`` (the same
   body the crash dump writes) plus the incident-specific extras:
   blocktrace/pipeline records for the implicated heights, the
   meshprof span and memory tails, and the last known mesh membership.

Bundles are **rate-limited** (at most one per rule per
``MPIBT_CHAINWATCH_BUNDLE_INTERVAL`` seconds) and **capped**
(``MPIBT_CHAINWATCH_BUNDLE_CAP`` per process), mirroring the flight
recorder's own artifact cap: a flapping detector converges to a
bounded set of files. Every write is atomic (tmp + replace) and every
failure is swallowed to stderr — incident capture must never become
the incident.
"""
from __future__ import annotations

import json
import os
import pathlib
import sys
import threading
import time

from ..telemetry.events import env_number

#: Every key an incident bundle carries — the schema the smoke gate and
#: tests pin. The first block is the shared ``flight_recorder.snapshot``
#: body; the second is the incident overlay.
BUNDLE_KEYS = (
    # shared snapshot body (telemetry/flight_recorder.snapshot)
    "artifact", "reason", "traceback", "wall_time", "pid", "argv",
    "context", "events", "causal", "metrics", "spans",
    # incident overlay
    "rule", "severity", "detail", "heights", "incident_seq",
    "opened_at", "blocktrace", "skew_spans", "memory", "mesh",
    "compiles", "service",
)

#: Bounded tails carried by a bundle (events/causal/spans come from
#: snapshot()'s own last_n; these bound the incident extras).
RECORDS_TAIL_N = 64

_lock = threading.Lock()
_state: dict = {
    "dir": None,               # pathlib.Path | None — bundles armed?
    "seq": 0,                  # incidents this process, lifetime
    "bundles": 0,              # bundles written (cap accounting)
    "last_bundle": {},         # rule -> monotonic time of last bundle
    "open": [],                # open episodes, oldest first
    "mesh": None,              # last known membership (notify_mesh)
}


def configure(directory=None) -> None:
    """(Re)arm the bundle directory; None disarms bundles (events and
    counters still fire). Called by ``chainwatch.install``."""
    with _lock:
        _state["dir"] = (pathlib.Path(directory)
                         if directory is not None else None)


def reset() -> None:
    """Full state reset (test isolation / uninstall)."""
    with _lock:
        _state.update(dir=None, seq=0, bundles=0, last_bundle={},
                      open=[], mesh=None)


def bundle_dir():
    with _lock:
        return _state["dir"]


def notify_mesh(membership: dict) -> None:
    """Record the last known mesh membership (the resilience/elastic
    seam feeds this on eviction) so bundles can carry it."""
    with _lock:
        _state["mesh"] = dict(membership)


def open_incidents() -> list[dict]:
    """Copies of the currently open incident episodes (shard payloads
    and ``/healthz`` carry these)."""
    with _lock:
        return [dict(i) for i in _state["open"]]


def close_incident(rule: str) -> None:
    """Drop ``rule``'s episode from the open table (its hysteresis
    cleared). The counter and any written bundle remain — closing is a
    live-view operation, not a retraction."""
    with _lock:
        _state["open"] = [i for i in _state["open"] if i["rule"] != rule]


def incident_count() -> int:
    """Incidents fired by this process so far (lifetime, not open)."""
    with _lock:
        return _state["seq"]


def emit_incident(*, rule: str, severity: str, detail: dict | None = None,
                  heights: tuple | list = (), source: str = "") -> dict:
    """Fire one incident: event + counter + open-table entry + (armed,
    rate-limited, capped) evidence bundle. Returns the incident record.
    Chainlint rule TEL006 pins the keyword discipline at every call
    site: ``rule=`` and ``severity=`` must be explicit."""
    from ..telemetry import counter
    from ..telemetry.events import emit_event

    detail = dict(detail or {})
    heights = sorted({int(h) for h in heights})
    with _lock:
        _state["seq"] += 1
        seq = _state["seq"]
    record = {"rule": rule, "severity": severity, "detail": detail,
              "heights": heights, "incident_seq": seq,
              "opened_at": time.time(), "source": source}
    counter("incidents_total",
            help="chainwatch incidents fired, by rule and severity",
            rule=rule, severity=severity).inc()
    emit_event({"event": "incident", **record})
    with _lock:
        # One open entry per rule: the rule's hysteresis guarantees one
        # firing per episode, so a duplicate means a fresh episode —
        # replace, keeping the table bounded by the rule catalogue.
        _state["open"] = ([i for i in _state["open"]
                           if i["rule"] != rule] + [dict(record)])
    path = _write_bundle(record)
    if path is not None:
        record["bundle"] = str(path)
    return record


def _write_bundle(record: dict):
    """The rate-limited, capped, atomic bundle write; None when
    disarmed, throttled, capped, or failed (failure prints, never
    raises — the run keeps mining)."""
    min_interval = env_number("MPIBT_CHAINWATCH_BUNDLE_INTERVAL", 30.0,
                              cast=float, minimum=0)
    cap = env_number("MPIBT_CHAINWATCH_BUNDLE_CAP", 8, cast=int,
                     minimum=1)
    now = time.monotonic()
    with _lock:
        directory = _state["dir"]
        if directory is None:
            return None
        if _state["bundles"] >= cap:
            return None
        last = _state["last_bundle"].get(record["rule"])
        if last is not None and now - last < min_interval:
            return None
        _state["last_bundle"][record["rule"]] = now
        _state["bundles"] += 1
        seq = record["incident_seq"]
    try:
        payload = build_bundle(record)
        directory.mkdir(parents=True, exist_ok=True)
        path = directory / f"incident_{seq:04d}_{record['rule']}.json"
        tmp = path.with_suffix(path.suffix + ".tmp")
        tmp.write_text(json.dumps(payload, sort_keys=True, default=str))
        tmp.replace(path)
        return path
    except Exception as e:
        print(f"chainwatch bundle write failed: {e}", file=sys.stderr)
        return None


def build_bundle(record: dict) -> dict:
    """The bundle payload: ``flight_recorder.snapshot()`` (the shared
    evidence body) overlaid with the incident record and its extras.
    Pure builder — no I/O — so tests can pin the schema directly."""
    from ..dispatchwatch import compile_snapshot
    from ..meshprof.memory import memory_snapshot
    from ..meshprof.spans import SKEW_TAIL_N, spans_tail
    from ..meshwatch.pipeline import profiler
    from ..service import service_stats
    from ..telemetry import flight_recorder, mesh_rank

    heights = set(record.get("heights", ()))
    records = profiler().records(tail=RECORDS_TAIL_N)
    if heights:
        # Implicated-height filter: keep dispatches whose meta or any
        # segment is stamped with one of the heights; fall back to the
        # whole tail when nothing matches (evidence beats emptiness).
        hit = [r for r in records
               if r.get("meta", {}).get("height") in heights
               or any(s.get("height") in heights
                      for s in r.get("segments", ()))]
        records = hit or records
    mesh = _state["mesh"]
    payload = flight_recorder.snapshot(
        f"incident:{record['rule']}", tb=None)
    payload.update({
        "artifact": "incident",
        "rule": record["rule"],
        "severity": record["severity"],
        "detail": record["detail"],
        "heights": sorted(heights),
        "incident_seq": record["incident_seq"],
        "opened_at": record["opened_at"],
        "blocktrace": records,
        "skew_spans": spans_tail(SKEW_TAIL_N),
        "memory": memory_snapshot(),
        "mesh": dict(mesh) if mesh else {"rank": mesh_rank(),
                                         "world_size": int(os.environ.get(
                                             "MPIBT_MESH_WORLD", 1))},
        "compiles": compile_snapshot(),
        # Blockserve door stats at fire time ({} on serviceless ranks):
        # a mempool_saturation bundle carries the pool it indicts.
        "service": service_stats(),
    })
    return payload
