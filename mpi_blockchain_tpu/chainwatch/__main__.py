"""CLI: python -m mpi_blockchain_tpu.chainwatch {smoke}

``smoke`` is the CI shape (``make incident-smoke``), pinning BOTH sides
of the watchdog contract end-to-end in real processes:

* **Detection** — a 4-rank cpu ``--mesh-obs`` world where one rank runs
  under a deterministic fault plan (two consecutive injected
  ``backend.cpu.search`` raises) must produce EXACTLY the expected
  incident: the injected faults and their retries are a 4-event burst,
  so with the storm threshold lowered to 3 the faulted rank fires
  ``event_storm`` — once (debounce + hysteresis), non-fatally (the
  retry ladder absorbs the faults; every rank still exits 0), with a
  complete, schema-pinned evidence bundle (``BUNDLE_KEYS``) on disk and
  the open incident carried by the rank's final shard into the merged
  mesh view.

* **False-positive pin** — the same world, same seed/difficulty, no
  fault plan, must produce ZERO incidents: no bundle, no ``incident``
  event, no ``incidents_total`` series in any shard. Every chainwatch
  threshold errs quiet; this is the gate that keeps it true.
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys


def _spawn_rank(rank: int, world: int, obs_dir: str, blocks: int,
                extra_env: dict | None = None, extra: tuple = ()):
    import os
    import subprocess

    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "MPIBT_MESH_RANK": str(rank),
           "MPIBT_MESH_WORLD": str(world),
           "MPIBT_MESH_OBS_INTERVAL": "0.2",
           **(extra_env or {})}
    argv = [sys.executable, "-m", "mpi_blockchain_tpu", "mine",
            "--backend", "cpu", "--difficulty", "8",
            "--blocks", str(blocks), "--mesh-obs", obs_dir, *extra]
    return subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def _run_world(obs: str, blocks: int, faulted_rank: int | None,
               fault_extra: tuple = (), fault_env: dict | None = None,
               world: int = 4) -> str | None:
    """Run the world to completion; every rank must exit 0 (the
    watchdog is non-fatal by contract). Returns an error string."""
    procs = {}
    try:
        for r in range(world):
            if r == faulted_rank:
                procs[r] = _spawn_rank(r, world, obs, blocks,
                                       extra_env=fault_env,
                                       extra=fault_extra)
            else:
                procs[r] = _spawn_rank(r, world, obs, blocks)
        for r, p in procs.items():
            out, err = p.communicate(timeout=120)
            if p.returncode != 0:
                return (f"rank {r} exited rc={p.returncode} "
                        f"(the watchdog must be non-fatal): {err[-800:]}")
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.wait()
    return None


def cmd_smoke(args) -> int:
    """The make incident-smoke gate: exact detection + zero-FP pin."""
    import tempfile

    from ..meshwatch.aggregate import mesh_incidents, read_shards
    from .incident import BUNDLE_KEYS

    victim = 2
    with tempfile.TemporaryDirectory() as tmp:
        tmp = pathlib.Path(tmp)

        # ---- leg 1: the faulted world must yield EXACTLY one incident.
        obs = str(tmp / "mesh_faulted")
        inc_dir = tmp / "incidents"
        plan = tmp / "plan.json"
        # Calls 2 and 3 of the victim's cpu sweep raise: 2 injected
        # faults + 2 retries = a 4-event burst the lowered storm
        # threshold (3 within a wide window) must catch; attempt 3 of
        # the retry ladder succeeds, so the run converges and exits 0.
        plan.write_text(json.dumps({
            "version": 1, "strict": True,
            "faults": [{"site": "backend.cpu.search", "kind": "raise",
                        "call": 2, "times": 2}]}))
        err = _run_world(
            obs, blocks=6, faulted_rank=victim,
            fault_extra=("--fault-plan", str(plan),
                         "--incident-dir", str(inc_dir)),
            fault_env={"MPIBT_CHAINWATCH_STORM_N": "3",
                       "MPIBT_CHAINWATCH_STORM_WINDOW": "60"})
        if err:
            print(f"incident-smoke: {err}", file=sys.stderr)
            return 1
        shards = read_shards(obs)
        incidents = mesh_incidents(shards)
        if [(i["rank"], i["rule"]) for i in incidents] != \
                [(victim, "event_storm")]:
            print(f"incident-smoke: expected exactly one event_storm "
                  f"incident on rank {victim}, got "
                  f"{[(i.get('rank'), i.get('rule')) for i in incidents]}",
                  file=sys.stderr)
            return 1
        inc = incidents[0]
        if inc["severity"] != "warn" or inc["incident_seq"] != 1:
            print(f"incident-smoke: wrong incident identity: {inc}",
                  file=sys.stderr)
            return 1
        bundles = sorted(inc_dir.glob("incident_*.json"))
        if [b.name for b in bundles] != ["incident_0001_event_storm.json"]:
            print(f"incident-smoke: expected exactly one bundle, got "
                  f"{[b.name for b in bundles]}", file=sys.stderr)
            return 1
        bundle = json.loads(bundles[0].read_text())
        missing = set(BUNDLE_KEYS) - set(bundle)
        if missing:
            print(f"incident-smoke: bundle incomplete, missing "
                  f"{sorted(missing)}", file=sys.stderr)
            return 1
        if (bundle["artifact"] != "incident"
                or bundle["rule"] != "event_storm"
                or bundle["reason"] != "incident:event_storm"
                or bundle["detail"].get("events", 0) < 3
                or not any(e.get("event") == "fault_injected"
                           for e in bundle["events"])):
            print(f"incident-smoke: bundle evidence wrong: "
                  f"rule={bundle['rule']!r} reason={bundle['reason']!r} "
                  f"detail={bundle['detail']}", file=sys.stderr)
            return 1
        # The signal must also have reached the metric + event surfaces
        # of the faulted rank's shard.
        vshard = next(s for s in shards if s["rank"] == victim)
        totals = vshard["registry"].get("incidents_total", [])
        if sum(m["value"] for m in totals) != 1 or not any(
                m["labels"] == {"rule": "event_storm", "severity": "warn"}
                for m in totals):
            print(f"incident-smoke: incidents_total wrong: {totals}",
                  file=sys.stderr)
            return 1
        if not any(e.get("event") == "incident"
                   and e.get("rule") == "event_storm"
                   for e in vshard["events_tail"]):
            print("incident-smoke: incident event missing from the "
                  "faulted rank's event tail", file=sys.stderr)
            return 1

        # ---- leg 2: the clean fixed-seed world must yield ZERO.
        obs_clean = str(tmp / "mesh_clean")
        err = _run_world(obs_clean, blocks=6, faulted_rank=None)
        if err:
            print(f"incident-smoke: clean leg: {err}", file=sys.stderr)
            return 1
        clean_shards = read_shards(obs_clean)
        if len(clean_shards) != 4:
            print(f"incident-smoke: clean leg wrote "
                  f"{len(clean_shards)}/4 shards", file=sys.stderr)
            return 1
        false_pos = mesh_incidents(clean_shards)
        if false_pos:
            print(f"incident-smoke: FALSE POSITIVE on a clean run: "
                  f"{false_pos}", file=sys.stderr)
            return 1
        for s in clean_shards:
            if s["registry"].get("incidents_total") or any(
                    e.get("event") == "incident"
                    for e in s["events_tail"]):
                print(f"incident-smoke: clean rank {s['rank']} carries "
                      f"incident residue", file=sys.stderr)
                return 1

    print(json.dumps({"event": "incident_smoke", "ok": True,
                      "incident_rule": inc["rule"],
                      "incident_rank": inc["rank"],
                      "bundle_keys": len(bundle),
                      "clean_incidents": 0}, sort_keys=True))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_blockchain_tpu.chainwatch",
        description="live SLO watchdog: CI smoke")
    sub = parser.add_subparsers(dest="command", required=True)
    p_smk = sub.add_parser(
        "smoke",
        help="the make incident-smoke gate: a fault-injected 4-rank "
             "world must yield exactly the expected incident (complete "
             "bundle), a clean run zero")
    p_smk.set_defaults(fn=cmd_smoke)
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
