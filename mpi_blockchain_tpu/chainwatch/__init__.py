"""chainwatch: the live, in-process SLO watchdog.

Every other lens in the stack is post-hoc (``perfwatch check`` judges
history after the run, meshwatch/meshprof analysis is CLI-driven) or
fatal-only (the flight recorder dumps on abnormal exit). chainwatch
closes the gap between them: streaming anomaly rules (``rules.py``)
evaluated on cadences the run already pays for, and a non-fatal
incident path (``incident.py``) that signals, records, and bundles
evidence while the run keeps mining.

Evaluation cadences (never a new thread on the hot path):

* the meshwatch shard flusher tick (``ShardWriter.payload`` — the
  ~1 Hz daemon-thread cadence every mesh-observed rank already runs);
* the per-block ``blocktrace.observe_block_metrics`` call (both miner
  drivers) — throttled inside ``evaluate`` to at most one full rule
  sweep per ``MPIBT_CHAINWATCH_INTERVAL`` seconds, so a fast block
  cadence pays a clock read, not six rules;
* ``blocktrace/overhead._instrumented_round`` — the audit copy, so the
  ≤3% telemetry overhead gate prices rule evaluation too.

The kill-switch contract matches the rest of telemetry: under
``MPIBT_TELEMETRY_OFF`` (or uninstalled) ``evaluate`` is a flag check
and nothing else — no rule state, no events, no files.
"""
from __future__ import annotations

import threading
import time

from ..telemetry.events import env_number
from ..telemetry.registry import telemetry_disabled
from .incident import (BUNDLE_KEYS, build_bundle, bundle_dir,
                       close_incident, emit_incident, incident_count,
                       notify_mesh, open_incidents)
from .rules import SEVERITIES, Rule, default_rules

__all__ = [
    "BUNDLE_KEYS", "SEVERITIES", "Rule", "build_bundle", "bundle_dir",
    "close_incident", "default_rules", "emit_incident", "evaluate",
    "incident_count", "install", "installed", "notify_eviction",
    "notify_mesh", "open_incidents", "uninstall",
]

_lock = threading.Lock()
_armed = False
_rules: list[Rule] = []
_last_sweep = 0.0


def install(incident_dir=None) -> list[Rule]:
    """Arm the watchdog: fresh rule instances + (optionally) an
    incident-bundle directory. Without a directory the rules still run
    and incidents still signal (event + counter + open table) — only
    the evidence bundles are skipped. Idempotent: re-install rebinds
    the directory and resets rule state."""
    from . import incident as _incident

    global _armed, _last_sweep
    with _lock:
        _rules.clear()
        _rules.extend(default_rules())
        _armed = True
        _last_sweep = 0.0
    _incident.reset()
    _incident.configure(incident_dir)
    return list(_rules)


def uninstall() -> None:
    """Disarm and drop all state (test isolation / CLI teardown)."""
    from . import incident as _incident

    global _armed
    with _lock:
        _armed = False
        _rules.clear()
    _incident.reset()


def installed() -> bool:
    return _armed


def evaluate(height: int | None = None, source: str = "",
             force: bool = False) -> list[dict]:
    """One watchdog step: sample every rule, fire debounced incidents.

    The two leading checks ARE the hot-path cost: disarmed or
    telemetry-off processes pay two reads and return. Armed, a
    monotonic-clock throttle bounds full sweeps to one per
    ``MPIBT_CHAINWATCH_INTERVAL`` seconds (``force=True`` — tests and
    the flush cadence — bypasses it). Returns the incidents fired by
    this step, empty almost always."""
    global _last_sweep
    if not _armed or telemetry_disabled():
        return []
    now = time.monotonic()
    if not force:
        interval = env_number("MPIBT_CHAINWATCH_INTERVAL", 0.25,
                              cast=float, minimum=0)
        if now - _last_sweep < interval:
            return []
    with _lock:
        if not _armed:
            return []
        _last_sweep = now
        rules = list(_rules)
    ctx = {"height": height, "source": source, "now": now}
    fired: list[dict] = []
    for rule in rules:
        was_open = rule.open
        try:
            detail = rule.evaluate(ctx)
        except Exception:
            # A broken detector must never hurt the run it watches;
            # chainlint RES001 exempts this sanctioned swallow point.
            continue
        if detail is not None:
            heights = (height,) if height is not None else ()
            fired.append(emit_incident(rule=rule.name,
                                       severity=rule.severity,
                                       detail=detail, heights=heights,
                                       source=source))
        elif was_open and not rule.open:
            close_incident(rule.name)
    return fired


def notify_eviction(rank: int, reason: str, height: int = 0,
                    live=None) -> dict | None:
    """The resilience/elastic seam: an eviction is a definitive
    membership loss, so it fires the ``stale_rank`` incident
    immediately — no debounce wait on the next cadence tick — and
    records the surviving membership for bundles. No-op while
    disarmed/off (the flag-check contract)."""
    if not _armed or telemetry_disabled():
        return None
    membership = {"live": list(live) if live is not None else [],
                  "evicted": [int(rank)], "reason": str(reason)}
    notify_mesh(membership)
    for rule in _rules:
        if rule.name == "stale_rank":
            if rule.open:
                return None     # episode already open: one incident
            rule.open = True
            rule.fired_total += 1
    return emit_incident(rule="stale_rank", severity="critical",
                         detail={"last_event": "mesh_shrunk",
                                 "rank": int(rank),
                                 "reason": str(reason)},
                         heights=(height,) if height else (),
                         source="eviction")
