"""chainwatch anomaly rules: streaming detectors over live telemetry.

Every rule is a small state machine sampled on the cadences the stack
already pays for — the meshwatch shard flush tick and the per-block
``observe_block_metrics`` call — never a new thread, never a device
query of its own. A rule reads only surfaces that already exist (the
metrics registry, the event ring, the pipeline profiler, the memory
watermarks) so evaluation stays host-only and cheap enough to live
inside the ≤3% telemetry overhead budget (blocktrace/overhead.py
prices it; perfwatch gates it).

The firing discipline (``Rule.evaluate``) is shared by every detector:

* **debounce** — a breach must persist for ``debounce_n`` consecutive
  samples before the rule fires (one noisy sample is weather);
* **hysteresis** — once fired, the rule is an *open episode*: it will
  not fire again until the signal has been clean for ``clear_n``
  consecutive samples (a flapping signal produces ONE incident, not a
  stream);
* **severity** — each rule carries ``warn`` or ``critical``; the
  incident event/counter/bundle all carry it.

The false-positive contract is load-bearing: a clean fixed-seed cpu
mine must produce ZERO incidents (tests/test_chainwatch.py pins it
across seeds, ``make incident-smoke`` pins it end-to-end), so every
threshold errs quiet and every baseline is learned in-run, never
absolute wall-clock.

Thresholds are env-tunable (``MPIBT_CHAINWATCH_*`` — see
docs/observability.md §chainwatch for the catalogue).
"""
from __future__ import annotations

import collections
import time

from ..telemetry.events import env_number

#: Severity levels, mildest first (render/sort order).
SEVERITIES = ("warn", "critical")

#: Event names that count toward the event-storm rule: retries,
#: degradations, collective timeouts, injected faults — the "the run is
#: absorbing damage" burst signals.
STORM_EVENTS = frozenset({
    "retry", "collective_timeout", "backend_rung_unavailable",
    "speculative_dispatch_failed", "backend_probe_failed",
    "fault_injected",
})


class Rule:
    """Debounce/hysteresis wrapper around a boolean ``sample``.

    Subclasses implement ``sample(ctx) -> (breach, detail)``; the base
    class turns that stream into at-most-one firing per open episode.
    ``ctx`` is the evaluation context dict chainwatch passes every rule
    (see ``chainwatch.evaluate``): ``height``, ``source``, ``now``.
    """

    name = "rule"
    severity = "warn"
    debounce_n = 2
    clear_n = 2

    def __init__(self):
        self._breach_streak = 0
        self._clear_streak = 0
        self.open = False
        self.fired_total = 0

    def sample(self, ctx: dict) -> tuple[bool, dict]:
        raise NotImplementedError

    def evaluate(self, ctx: dict) -> dict | None:
        """One sampling step. Returns the firing detail dict exactly
        once per episode (debounced breach while closed), else None."""
        breach, detail = self.sample(ctx)
        if breach:
            self._breach_streak += 1
            self._clear_streak = 0
            if not self.open and self._breach_streak >= self.debounce_n:
                self.open = True
                self.fired_total += 1
                return dict(detail)
        else:
            self._breach_streak = 0
            if self.open:
                self._clear_streak += 1
                if self._clear_streak >= self.clear_n:
                    self.open = False
                    self._clear_streak = 0
        return None

    def reset(self) -> None:
        self.__init__()


# ---- rule catalogue --------------------------------------------------------


class HashrateCollapse(Rule):
    """EWMA hash rate vs the in-run rolling baseline.

    Rate = Δ``hashes_tried_total`` (summed over labelsets in the live
    registry) / Δwall between samples. The first ``warmup_n`` rates
    build the baseline; after warmup the rule breaches while the EWMA
    sits below ``collapse_frac`` of the rolling baseline. Short runs
    never leave warmup, so they can never fire — mining-time variance
    is geometric per block, but the *rate* is stable, which is exactly
    why the rule watches rate and not block latency."""

    name = "hashrate_collapse"
    severity = "critical"
    debounce_n = 3

    def __init__(self):
        super().__init__()
        self.warmup_n = env_number("MPIBT_CHAINWATCH_HASHRATE_WARMUP", 8,
                                   cast=int, minimum=2)
        self.collapse_frac = env_number(
            "MPIBT_CHAINWATCH_HASHRATE_FRAC", 0.4, cast=float, minimum=0)
        self._last = None          # (wall, total hashes)
        self._ewma = None
        self._baseline = None
        self._samples = 0

    @staticmethod
    def _total_hashes() -> float:
        from ..telemetry import default_registry

        snap = default_registry().snapshot().get("hashes_tried_total", [])
        return float(sum(m.get("value", 0) for m in snap))

    def sample(self, ctx):
        now = ctx.get("now", time.monotonic())
        total = self._total_hashes()
        if self._last is None:
            self._last = (now, total)
            return False, {}
        dt = now - self._last[0]
        dh = total - self._last[1]
        if dt <= 0 or dh <= 0:
            # No new work between samples (same flush tick, idle rank):
            # not evidence of collapse, not a sample.
            return False, {}
        self._last = (now, total)
        rate = dh / dt
        self._ewma = rate if self._ewma is None else \
            0.3 * rate + 0.7 * self._ewma
        self._samples += 1
        if self._samples <= self.warmup_n:
            self._baseline = self._ewma if self._baseline is None else \
                0.2 * self._ewma + 0.8 * self._baseline
            return False, {}
        # Past warmup the baseline keeps drifting SLOWLY so a long run's
        # legitimate plateau shift is absorbed, while a collapse is not.
        self._baseline = 0.02 * self._ewma + 0.98 * self._baseline
        breach = self._ewma < self.collapse_frac * self._baseline
        return breach, {"ewma_rate": round(self._ewma, 3),
                        "baseline_rate": round(self._baseline, 3),
                        "collapse_frac": self.collapse_frac}


class CollectiveSkewSpike(Rule):
    """``collective_skew_ms`` p95 (live registry histogram, per site)
    over the absolute bound. The histogram is populated by
    ``meshprof.analyzer.publish_skew`` (the meshwatch analyze/skew CLIs
    and the elastic supervisor's publishes); a world that never
    publishes skew never feeds this rule."""

    name = "collective_skew_spike"
    severity = "warn"

    def __init__(self):
        super().__init__()
        self.bound_ms = env_number("MPIBT_CHAINWATCH_SKEW_MS", 1000.0,
                                   cast=float, minimum=0)
        self.min_count = env_number("MPIBT_CHAINWATCH_SKEW_MIN_ROUNDS", 4,
                                    cast=int, minimum=1)

    def sample(self, ctx):
        from ..telemetry import default_registry

        worst = None
        for m in default_registry().snapshot().get("collective_skew_ms", []):
            p95 = m.get("p95")
            if p95 is None or m.get("count", 0) < self.min_count:
                continue
            if worst is None or p95 > worst[0]:
                worst = (p95, m.get("labels", {}).get("site", ""))
        if worst is None or worst[0] <= self.bound_ms:
            return False, {}
        return True, {"skew_p95_ms": round(worst[0], 3),
                      "site": worst[1], "bound_ms": self.bound_ms}


class HbmWatermarkGrowth(Rule):
    """Per-device ``last_bytes_in_use`` vs the first-seen in-run
    baseline: sustained growth past ``growth_factor``× (above an
    absolute floor, so cpu-host noise can't trip it) is the OOM
    precursor worth an incident before the allocator kills the run.
    Processes that never imported jax sample ``{}`` and never fire."""

    name = "hbm_watermark_growth"
    severity = "warn"
    debounce_n = 3

    def __init__(self):
        super().__init__()
        self.growth_factor = env_number(
            "MPIBT_CHAINWATCH_HBM_GROWTH", 1.5, cast=float, minimum=1)
        self.floor_bytes = env_number(
            "MPIBT_CHAINWATCH_HBM_FLOOR", 64 * 1024 * 1024,
            cast=int, minimum=0)
        self._baseline: dict[str, float] = {}

    def sample(self, ctx):
        from ..meshprof.memory import memory_snapshot

        worst = None
        for dev, mark in memory_snapshot().items():
            cur = mark.get("last_bytes_in_use", 0)
            base = self._baseline.setdefault(dev, cur)
            if base <= 0 or cur < self.floor_bytes:
                continue
            ratio = cur / base
            if ratio > self.growth_factor and (
                    worst is None or ratio > worst[0]):
                worst = (ratio, dev, cur, base)
        if worst is None:
            return False, {}
        return True, {"device": worst[1], "growth": round(worst[0], 3),
                      "bytes_in_use": worst[2], "baseline_bytes": worst[3],
                      "growth_factor": self.growth_factor}


class StaleRank(Rule):
    """Mesh membership damage straight off the event ring:
    ``mesh_shrunk`` (an eviction), ``mesh_rank_stale``/
    ``mesh_rank_failed`` (the aggregator's transition announcements) or
    ``rank_death`` since the last sample. Membership loss is definitive
    — no debounce — and the episode stays open until the ring goes
    quiet, so one evicted rank is one incident even though the
    aggregator keeps re-reading the dead shard."""

    name = "stale_rank"
    severity = "critical"
    debounce_n = 1

    WATCHED = ("mesh_shrunk", "mesh_rank_stale", "mesh_rank_failed",
               "rank_death")

    def __init__(self):
        super().__init__()
        self._since = None

    def sample(self, ctx):
        from ..telemetry.events import latest_seq, recent_with_seq

        if self._since is None:
            # First sample anchors past history: pre-install events are
            # the installer's context, not a live anomaly.
            self._since = latest_seq()
            return False, {}
        hits = [e for _, e in recent_with_seq(since=self._since)
                if e.get("event") in self.WATCHED]
        self._since = latest_seq()
        if not hits:
            return False, {}
        last = hits[-1]
        return True, {"events": len(hits), "last_event": last.get("event"),
                      "rank": last.get("evicted", last.get("rank")),
                      "reason": last.get("reason", "")}


class BubbleRegression(Rule):
    """Pipeline ``bubble_fraction`` regression vs the in-run baseline.

    Reads ``pipeline_report`` over the profiler's recent records —
    interval math over a bounded tail, so the rule self-throttles to at
    most one real computation per ``min_interval_s`` (throttled samples
    cost one clock read, the same discipline as
    ``meshprof.memory.sample_memory``). Absolute bubble is backend
    weather (a cpu world is all bubble); only a REGRESSION against this
    run's own warmup baseline fires."""

    name = "bubble_regression"
    severity = "warn"
    debounce_n = 3

    TAIL = 128

    def __init__(self):
        super().__init__()
        self.warmup_n = env_number("MPIBT_CHAINWATCH_BUBBLE_WARMUP", 6,
                                   cast=int, minimum=2)
        self.margin = env_number("MPIBT_CHAINWATCH_BUBBLE_MARGIN", 0.3,
                                 cast=float, minimum=0)
        self.min_interval_s = env_number(
            "MPIBT_CHAINWATCH_BUBBLE_INTERVAL", 0.5, cast=float, minimum=0)
        self._last_eval = 0.0
        self._baseline = None
        self._samples = 0
        self._breach_hold = False

    def sample(self, ctx):
        now = ctx.get("now", time.monotonic())
        if now - self._last_eval < self.min_interval_s:
            # Throttled: hold the last verdict so debounce streaks are
            # counted in real samples, not in call frequency.
            return self._breach_hold, {}
        self._last_eval = now
        from ..meshwatch.pipeline import pipeline_report, profiler

        rep = pipeline_report(profiler().records(tail=self.TAIL))
        bubble = rep.get("bubble_fraction")
        if bubble is None:
            self._breach_hold = False
            return False, {}
        self._samples += 1
        if self._samples <= self.warmup_n or self._baseline is None:
            self._baseline = bubble if self._baseline is None else \
                0.5 * bubble + 0.5 * self._baseline
            self._breach_hold = False
            return False, {}
        # bubble_fraction <= 1.0, so a baseline within `margin` of full
        # idle can never breach — regression detection, not an absolute
        # bound (a cpu world's natural bubble is weather, not an SLO).
        breach = bubble > self._baseline + self.margin
        if not breach:
            self._baseline = 0.1 * bubble + 0.9 * self._baseline
        self._breach_hold = breach
        return breach, {"bubble_fraction": bubble,
                        "baseline": round(self._baseline, 4),
                        "margin": self.margin}


class EventStorm(Rule):
    """Burst of damage-absorption events (``STORM_EVENTS``) over the
    ring: ``storm_n`` or more inside ``window_s`` breaches. A healthy
    run emits none of these; a run riding its retry budget hard is
    degrading even when every retry succeeds."""

    name = "event_storm"
    severity = "warn"
    debounce_n = 1

    def __init__(self):
        super().__init__()
        self.storm_n = env_number("MPIBT_CHAINWATCH_STORM_N", 10,
                                  cast=int, minimum=1)
        self.window_s = env_number("MPIBT_CHAINWATCH_STORM_WINDOW", 10.0,
                                   cast=float, minimum=0.1)
        self._since = None
        self._times: collections.deque = collections.deque(maxlen=4096)

    def sample(self, ctx):
        from ..telemetry.events import latest_seq, recent_with_seq

        now = ctx.get("now", time.monotonic())
        if self._since is None:
            self._since = latest_seq()
            return False, {}
        hits = [e for _, e in recent_with_seq(since=self._since)
                if e.get("event") in STORM_EVENTS]
        self._since = latest_seq()
        for e in hits:
            self._times.append((now, e.get("event")))
        while self._times and now - self._times[0][0] > self.window_s:
            self._times.popleft()
        if len(self._times) < self.storm_n:
            return False, {}
        kinds = collections.Counter(k for _, k in self._times)
        return True, {"events": len(self._times),
                      "window_s": self.window_s,
                      "kinds": dict(sorted(kinds.items()))}


class RecompileStorm(Rule):
    """Trace-cache churn off the dispatchwatch census: total observed
    XLA backend compiles *growing* after the warmup samples breaches.
    A healthy steady-state run compiles each sweep callable exactly
    once during warmup and never again — post-warmup growth means some
    dispatch seam is re-tracing (shape drift, a donated-buffer layout
    flip, per-template retraces), the runtime twin of the SHD003
    divergent-trace hang class. The first ``warmup_n`` samples absorb
    legitimate startup compilation; ``allowed`` compiles per sample are
    tolerated after that (default 0 — any growth is churn). Processes
    that never observed a compile sample ``{}`` and never fire; the
    incident detail carries the per-site census so the bundle names
    the guilty seam."""

    name = "recompile_storm"
    severity = "warn"

    def __init__(self):
        super().__init__()
        self.warmup_n = env_number("MPIBT_CHAINWATCH_RECOMPILE_WARMUP", 4,
                                   cast=int, minimum=1)
        self.allowed = env_number("MPIBT_CHAINWATCH_RECOMPILE_ALLOWED", 0,
                                  cast=int, minimum=0)
        self._prev_total = None
        self._samples = 0

    def sample(self, ctx):
        from ..dispatchwatch import compile_census

        census = compile_census()
        if not census:
            return False, {}
        total = sum(int(st.get("compiles", 0)) for st in census.values())
        prev, self._prev_total = self._prev_total, total
        if prev is None:
            return False, {}
        self._samples += 1
        grown = total - prev
        if self._samples <= self.warmup_n or grown <= self.allowed:
            return False, {}
        return True, {"compiles_total": total, "grown": grown,
                      "allowed": self.allowed,
                      "sites": {site: int(st.get("compiles", 0))
                                for site, st in census.items()}}


class MempoolSaturation(Rule):
    """The blockserve admission surface under sustained overload: the
    door shedding faster than ``shed_n`` requests between samples, or
    the pool camping at/above ``full_frac`` of its capacity bound.
    Transient spikes ride the standard debounce; the episode clears by
    hysteresis once the pool drains and sheds stop. Serviceless
    processes sample ``{}`` and never fire (the clean-mine
    false-positive contract), and an idle door (no sheds, shallow pool)
    never breaches. The incident detail carries the shed breakdown and
    depth so the bundle's ``service`` snapshot has its headline."""

    name = "mempool_saturation"
    severity = "warn"

    def __init__(self):
        super().__init__()
        self.shed_n = env_number("MPIBT_CHAINWATCH_MEMPOOL_SHED_N", 5,
                                 cast=int, minimum=1)
        self.full_frac = env_number("MPIBT_CHAINWATCH_MEMPOOL_FRAC", 0.95,
                                    cast=float, minimum=0)
        self._prev_shed = None

    def sample(self, ctx):
        from ..service import service_stats

        stats = service_stats()
        if not stats:
            return False, {}
        shed_total = sum((stats.get("shed_total") or {}).values())
        prev, self._prev_shed = self._prev_shed, shed_total
        pool = stats.get("mempool") or {}
        depth, cap = int(pool.get("depth", 0)), int(pool.get("cap", 0))
        full = cap > 0 and depth >= self.full_frac * cap
        shed_delta = 0 if prev is None else shed_total - prev
        if shed_delta < self.shed_n and not full:
            return False, {}
        return True, {"depth": depth, "cap": cap,
                      "shed_delta": shed_delta,
                      "shed_total": dict(stats.get("shed_total") or {}),
                      "accept_gate": stats.get("accept_gate") or {},
                      "full_frac": self.full_frac}


def default_rules() -> list[Rule]:
    """Fresh instances of the full catalogue, evaluation order fixed
    (docs/observability.md §chainwatch documents each row)."""
    return [HashrateCollapse(), CollectiveSkewSpike(),
            HbmWatermarkGrowth(), StaleRank(), BubbleRegression(),
            EventStorm(), RecompileStorm(), MempoolSaturation()]
