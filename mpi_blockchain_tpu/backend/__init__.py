"""The miner_backend plugin boundary (BASELINE.json north-star).

Every backend implements the same deterministic contract: return the LOWEST
nonce in [start_nonce, start_nonce + count) whose double-SHA256 header hash
has >= difficulty_bits leading zero bits. Lowest-nonce (not first-found
wall-clock) is what makes CPU, single-chip TPU, and 8-chip mesh runs produce
identical block hashes (SURVEY.md §7 hard part #3).
"""
from __future__ import annotations

import abc
import concurrent.futures
import dataclasses

from ..config import ConfigError


@dataclasses.dataclass(frozen=True)
class SearchResult:
    nonce: int | None        # lowest qualifying nonce, or None
    hash: bytes | None       # 32-byte sha256d digest of the winning header
    hashes_tried: int        # total nonces evaluated (for hashes/sec metrics)


def sync_search_future(search_fn, header80: bytes, difficulty_bits: int,
                       start_nonce: int = 0,
                       max_count: int = 1 << 32
                       ) -> "concurrent.futures.Future":
    """The degenerate (synchronous) form of the async dispatch seam:
    runs ``search_fn`` inline and returns an already-completed future,
    so a driver written against ``search_async`` degrades to the exact
    sequential one-deep pipeline on backends without a real async
    dispatch path. Exceptions travel through the future, like a real
    dispatch's would."""
    f: concurrent.futures.Future = concurrent.futures.Future()
    try:
        f.set_result(search_fn(header80, difficulty_bits,
                               start_nonce=start_nonce,
                               max_count=max_count))
    except BaseException as e:   # delivered to the consumer, not lost
        f.set_exception(e)
    return f


class MinerBackend(abc.ABC):
    """Abstract nonce-search engine behind the plugin boundary."""

    name: str = "abstract"

    @abc.abstractmethod
    def search(self, header80: bytes, difficulty_bits: int,
               start_nonce: int = 0,
               max_count: int = 1 << 32) -> SearchResult:
        """Finds the lowest qualifying nonce in the given range."""

    def search_async(self, header80: bytes, difficulty_bits: int,
                     start_nonce: int = 0,
                     max_count: int = 1 << 32
                     ) -> "concurrent.futures.Future":
        """Future-returning dispatch: the seam the double-buffered miner
        pipeline (models/miner.py) drives, letting the host validate /
        append / checkpoint block N while sweep N+1 runs. The contract
        on top of ``search``'s:

        * same determinism — the future resolves to exactly what
          ``search`` with the same arguments would return;
        * FIFO completion — two dispatches issued back-to-back resolve
          in issue order (the driver additionally consumes strictly in
          issue order, so the lowest-nonce rule survives even a backend
          whose futures complete out of order);
        * errors arrive through the future, never at submission.

        Default implementation: the degenerate synchronous one-deep
        pipeline (``sync_search_future``). ``ResilientBackend``
        overrides it with a real single-flight dispatch worker.
        """
        return sync_search_future(self.search, header80, difficulty_bits,
                                  start_nonce=start_nonce,
                                  max_count=max_count)


_REGISTRY: dict[str, type[MinerBackend]] = {}


def register(name: str):
    def deco(cls: type[MinerBackend]):
        cls.name = name
        _REGISTRY[name] = cls
        return cls
    return deco


def get_backend(name: str, **kwargs) -> MinerBackend:
    """Instantiates a registered backend: get_backend("cpu"|"tpu", ...)."""
    # Import lazily so the cpu path never drags in jax.
    if name not in _REGISTRY:
        if name == "cpu":
            from . import cpu  # noqa: F401
        elif name == "tpu":
            from . import tpu  # noqa: F401
    try:
        return _REGISTRY[name](**kwargs)
    except KeyError:
        raise ConfigError(f"unknown miner_backend {name!r}; "
                         f"known: {sorted(_REGISTRY)}") from None


def backend_from_config(config, cpu_ranks: int | None = None,
                        mesh=None, resilient: bool = True) -> MinerBackend:
    """The one place a MinerConfig becomes a backend instance (shared by
    Miner, FusedMiner's rollover path, and SimNode). cpu_ranks overrides
    the CPU thread-rank count (SimNode runs each group as one rank);
    mesh passes an explicit device mesh through to the TPU backend.

    By default the instance is wrapped in the resilience layer's
    ``ResilientBackend``: retry-with-backoff around every dispatch,
    host-side re-validation of every winner, and the degradation ladder
    (device kernel → jnp → native CPU) on repeated failure — see
    docs/resilience.md. ``resilient=False`` returns the raw rung
    (equivalence tests and benchmarks that must measure one backend).
    """
    if not resilient:
        if config.backend == "cpu":
            return get_backend("cpu",
                               n_ranks=(config.n_miners if cpu_ranks is None
                                        else cpu_ranks),
                               batch_size=config.batch_size)
        return get_backend("tpu", batch_pow2=config.effective_batch_pow2,
                           n_miners=config.n_miners, kernel=config.kernel,
                           mesh=mesh)
    from ..resilience.dispatch import ResilientBackend, ladder_from_config
    return ResilientBackend(ladder_from_config(config, cpu_ranks=cpu_ranks,
                                               mesh=mesh),
                            seed=config.seed)


def _faulted_result(fault, res: SearchResult,
                    start_nonce: int) -> SearchResult:
    """Applies a dispatch-site ``corrupt``/``partial`` fault to a search
    result (shared by the cpu and tpu hooks, docs/resilience.md):

    * ``corrupt`` — the result LIES: a found winner keeps its nonce but
      reports a damaged digest; an empty sweep fabricates a bogus
      winner. Either way host-side re-validation (ResilientBackend)
      must catch it — corruption is injected *detectably wrong*.
    * ``partial`` — the result is TRUNCATED: any winner is suppressed
      and only half the sweep is credited, the lost-result fault.
    """
    if fault.kind == "partial":
        return SearchResult(None, None, max(0, res.hashes_tried // 2))
    if fault.kind == "corrupt":
        if res.nonce is not None:
            bad = bytes(b ^ 0xFF for b in res.hash) if res.hash else b"\xff" * 32
            return dataclasses.replace(res, hash=bad)
        return SearchResult(start_nonce & 0xFFFFFFFF, b"\x00" * 32,
                            res.hashes_tried)
    return res


def available() -> list[str]:
    from . import cpu  # noqa: F401
    try:
        from . import tpu  # noqa: F401
    except Exception as e:   # jax missing/broken — cpu still works
        # Loud, not swallowed (chainlint RES001): the probe failure is
        # an event a post-mortem can see, not a silent capability hole.
        from ..telemetry.events import emit_event
        emit_event({"event": "backend_probe_failed", "backend": "tpu",
                    "error": f"{type(e).__name__}: {e}"})
    return sorted(_REGISTRY)
