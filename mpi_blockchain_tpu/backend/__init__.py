"""The miner_backend plugin boundary (BASELINE.json north-star).

Every backend implements the same deterministic contract: return the LOWEST
nonce in [start_nonce, start_nonce + count) whose double-SHA256 header hash
has >= difficulty_bits leading zero bits. Lowest-nonce (not first-found
wall-clock) is what makes CPU, single-chip TPU, and 8-chip mesh runs produce
identical block hashes (SURVEY.md §7 hard part #3).
"""
from __future__ import annotations

import abc
import dataclasses

from ..config import ConfigError


@dataclasses.dataclass(frozen=True)
class SearchResult:
    nonce: int | None        # lowest qualifying nonce, or None
    hash: bytes | None       # 32-byte sha256d digest of the winning header
    hashes_tried: int        # total nonces evaluated (for hashes/sec metrics)


class MinerBackend(abc.ABC):
    """Abstract nonce-search engine behind the plugin boundary."""

    name: str = "abstract"

    @abc.abstractmethod
    def search(self, header80: bytes, difficulty_bits: int,
               start_nonce: int = 0,
               max_count: int = 1 << 32) -> SearchResult:
        """Finds the lowest qualifying nonce in the given range."""


_REGISTRY: dict[str, type[MinerBackend]] = {}


def register(name: str):
    def deco(cls: type[MinerBackend]):
        cls.name = name
        _REGISTRY[name] = cls
        return cls
    return deco


def get_backend(name: str, **kwargs) -> MinerBackend:
    """Instantiates a registered backend: get_backend("cpu"|"tpu", ...)."""
    # Import lazily so the cpu path never drags in jax.
    if name not in _REGISTRY:
        if name == "cpu":
            from . import cpu  # noqa: F401
        elif name == "tpu":
            from . import tpu  # noqa: F401
    try:
        return _REGISTRY[name](**kwargs)
    except KeyError:
        raise ConfigError(f"unknown miner_backend {name!r}; "
                         f"known: {sorted(_REGISTRY)}") from None


def backend_from_config(config, cpu_ranks: int | None = None,
                        mesh=None) -> MinerBackend:
    """The one place a MinerConfig becomes a backend instance (shared by
    Miner, FusedMiner's rollover path, and SimNode). cpu_ranks overrides
    the CPU thread-rank count (SimNode runs each group as one rank);
    mesh passes an explicit device mesh through to the TPU backend."""
    if config.backend == "cpu":
        return get_backend("cpu",
                           n_ranks=(config.n_miners if cpu_ranks is None
                                    else cpu_ranks),
                           batch_size=config.batch_size)
    return get_backend("tpu", batch_pow2=config.effective_batch_pow2,
                       n_miners=config.n_miners, kernel=config.kernel,
                       mesh=mesh)


def available() -> list[str]:
    from . import cpu  # noqa: F401
    try:
        from . import tpu  # noqa: F401
    except Exception:   # jax missing/broken — cpu still works
        pass
    return sorted(_REGISTRY)
