"""CPU miner_backend: the C++ scalar sweep (the correctness oracle).

Maps to the reference's per-rank nonce loop (SURVEY.md §2.1 "Miner"); with
n_ranks > 1 it reproduces the mpirun-style search-space split using
interleaved contiguous rounds, which preserves the lowest-nonce winner rule
exactly (see parallel/mesh.py for the same scheme on the device mesh).
"""
from __future__ import annotations

import concurrent.futures

from .. import core
from ..resilience import injection
from ..telemetry.spans import span
from . import MinerBackend, SearchResult, _faulted_result, register


@register("cpu")
class CpuBackend(MinerBackend):
    def __init__(self, n_ranks: int = 1, batch_size: int = 1 << 20):
        self.n_ranks = n_ranks
        self.batch_size = batch_size
        self._pool = (concurrent.futures.ThreadPoolExecutor(n_ranks)
                      if n_ranks > 1 else None)

    def search(self, header80: bytes, difficulty_bits: int,
               start_nonce: int = 0, max_count: int = 1 << 32) -> SearchResult:
        # Fault-injection hook: raise/hang fire here; corrupt/partial
        # damage the result below (docs/resilience.md).
        fault = injection.check("backend.cpu.search",
                                difficulty=difficulty_bits)
        with span("backend.cpu.search", n_ranks=self.n_ranks):
            if self.n_ranks == 1:
                nonce, tried = core.cpu_search(header80, start_nonce,
                                               max_count, difficulty_bits)
                digest = (core.header_hash(core.set_nonce(header80, nonce))
                          if nonce is not None else None)
                res = SearchResult(nonce, digest, tried)
            else:
                res = self._search_ranks(header80, difficulty_bits,
                                         start_nonce, max_count)
        if fault is not None:
            res = _faulted_result(fault, res, start_nonce)
        return res

    def _search_ranks(self, header80: bytes, difficulty_bits: int,
                      start_nonce: int, max_count: int) -> SearchResult:
        # Round r covers the contiguous range [base, base + n_ranks*B); rank i
        # sweeps its B-sized slice. The first round with any qualifier yields
        # the exact global lowest nonce — every smaller nonce was already
        # swept — which is the deterministic analogue of the reference's
        # first-finder MPI_Bcast (the C++ side releases the GIL during
        # cc_search, so ranks genuinely run in parallel).
        B = self.batch_size
        end = min(start_nonce + max_count, 1 << 32)
        base = start_nonce
        total_tried = 0
        while base < end:
            spans = []
            for i in range(self.n_ranks):
                lo = base + i * B
                hi = min(lo + B, end)
                if lo < hi:
                    spans.append((lo, hi - lo))
            results = list(self._pool.map(
                lambda s: core.cpu_search(header80, s[0], s[1],
                                          difficulty_bits), spans))
            total_tried += sum(t for _, t in results)
            found = [n for n, _ in results if n is not None]
            if found:
                nonce = min(found)
                digest = core.header_hash(core.set_nonce(header80, nonce))
                return SearchResult(nonce, digest, total_tried)
            base += self.n_ranks * B
        return SearchResult(None, None, total_tried)
