"""TPU miner_backend: device-resident multi-round nonce search.

Replaces the reference's per-rank scalar loop + MPI collectives with ONE
jit'd XLA program per search (SURVEY.md §3.4 taken to the per-block limit):
a ``lax.while_loop`` over ascending sweep rounds runs on-device until a
round contains a qualifier, and with n_miners > 1 each round is shard_map'd
over the 'miners' mesh with psum/pmin winner-select riding the ICI — the
TPU-native form of first-finder MPI_Bcast + height allreduce.

Round-4 redesign: the previous per-ROUND host loop paid one host<->device
round trip (~90 ms under the axon tunnel) per round, so at the config-3
literal batch (2^20) the chip idled ~97% of the time (2.83 MH/s measured
vs 971.8 at dispatch-amortized batches). Moving the round loop into the
program makes a block cost ~one dispatch regardless of how many rounds the
search needs; determinism is unchanged because rounds still ascend and the
winner is still the lowest qualifying nonce in the requested range.

Early exit under jit: rounds cover contiguous ranges from start_nonce
upward, so the first round containing any qualifier yields the exact global
lowest nonce — deterministic and backend-independent. The device cannot
break mid-round, but a full round is exact-count work the host accounting
mirrors (models/miner.py hashes_tried).
"""
from __future__ import annotations

import numpy as np

from .. import core
from ..dispatchwatch import compile_scope, note_cache
from ..resilience import injection
from ..telemetry import counter
from ..telemetry.spans import span
from . import MinerBackend, SearchResult, _faulted_result, register

NONCE_SPACE = 1 << 32


def make_multiround_search_fn(batch_size: int, difficulty_bits: int,
                              n_miners: int = 1, mesh=None,
                              kernel: str = "auto"):
    """Builds the jit'd multi-round searcher.

    Returns (fn, effective_kernel) where
    fn(ext (EXT_WORDS,)u32, start u32, n_rounds u32)
      -> (rounds_done u32, count i32, min_nonce u32)
    sweeps rounds r = 0.. covering [start + r*round_size, +round_size)
    until count > 0 or r == n_rounds (n_rounds is a traced scalar — no
    recompile per call). ``ext`` is the extended-midstate payload
    (``ops.sha256_sched.extend_midstate`` — the caller precomputes it on
    the host once per template, so the nonce-invariant rounds/schedule
    prefix never ride a dispatch). count/min_nonce are the LAST executed
    round's result; min_nonce is 0xFFFFFFFF when count == 0.
    """
    from ..ops import select_kernel
    from ..parallel.mesh import make_round_search, maybe_shard_over_miners

    sweep, effective = select_kernel(kernel, batch_size, difficulty_bits,
                                     shard=True)
    run = make_round_search(sweep, batch_size, batch_size * n_miners)
    return maybe_shard_over_miners(run, n_miners, mesh, n_out=3), effective


@register("tpu")
class TpuBackend(MinerBackend):
    def __init__(self, batch_pow2: int = 20, n_miners: int = 1,
                 kernel: str = "auto", mesh=None):
        import jax  # deferred so cpu-only users never import jax

        self.batch_size = 1 << batch_pow2
        self.n_miners = n_miners
        self.kernel = kernel
        if n_miners > 1 and mesh is None:
            from ..parallel.mesh import make_miner_mesh
            mesh = make_miner_mesh(n_miners)
        self.mesh = mesh
        self._searchers: dict[int, object] = {}  # difficulty -> compiled fn
        self._jax = jax

    def _searcher(self, difficulty_bits: int):
        fn = self._searchers.get(difficulty_bits)
        if fn is None:
            fn, self.effective_kernel = make_multiround_search_fn(
                self.batch_size, difficulty_bits, n_miners=self.n_miners,
                mesh=self.mesh, kernel=self.kernel)
            self._searchers[difficulty_bits] = fn
            note_cache(site="backend.tpu", entries=len(self._searchers))
        return fn

    # ---- the plugin contract ---------------------------------------------

    def search(self, header80: bytes, difficulty_bits: int,
               start_nonce: int = 0, max_count: int = NONCE_SPACE
               ) -> SearchResult:
        # Fault-injection hook: raise/hang fire before any device work
        # (a dead dispatch costs no compile); corrupt/partial damage the
        # completed result (docs/resilience.md).
        fault = injection.check("backend.tpu.dispatch",
                                difficulty=difficulty_bits)
        res = self._search_device(header80, difficulty_bits, start_nonce,
                                  max_count)
        if fault is not None:
            res = _faulted_result(fault, res, start_nonce)
        return res

    def _search_device(self, header80: bytes, difficulty_bits: int,
                       start_nonce: int, max_count: int) -> SearchResult:
        from ..ops.sha256_sched import extend_midstate
        from ..parallel.mesh import replicated_host_values

        # Host-side per-template precompute (numpy, no device work): the
        # chunk-1 midstate plus the nonce-invariant chunk-2 rounds and
        # schedule prefix, packed for the kernels' scalar-prefetch path.
        midstate, tail = core.header_midstate(header80)
        ext = extend_midstate(midstate, tail)
        end = min(start_nonce + max_count, NONCE_SPACE)
        round_size = self.batch_size * self.n_miners
        tried = 0
        base = start_nonce
        # The device sweeps full rounds (static shapes). Rounds are capped
        # to those fully inside the uint32 nonce space: a round wrapping
        # past 2^32 could surface a wrapped low nonce from *unswept* space
        # and shadow a genuine in-range winner, so any partial tail
        # (< round_size nonces) runs on the CPU oracle after the device
        # rounds.
        n_rounds = 0
        if base < end and base + round_size <= NONCE_SPACE:
            # The 0xFFFFFFFF clamp keeps np.uint32(n_rounds) in range at
            # round_size == 1 (n_rounds would be 2^32); the one elided
            # round falls through to the CPU tail below.
            n_rounds = min(-(-(end - base) // round_size),
                           (NONCE_SPACE - base) // round_size, 0xFFFFFFFF)
        if n_rounds > 0:
            # The span covers dispatch AND the value materialization below
            # — the device-side share of the search (vs the CPU tail's
            # host share), the split docs/observability.md documents.
            with span("backend.tpu.dispatch",
                      difficulty=difficulty_bits, n_rounds=n_rounds), \
                    compile_scope(site="backend.tpu"):
                out = self._searcher(difficulty_bits)(
                    ext, np.uint32(base), np.uint32(n_rounds))
                rounds, count, min_nonce = (
                    int(v) for v in replicated_host_values(out))
            counter("device_dispatches_total",
                    help="jit'd multi-round search programs dispatched",
                    backend="tpu").inc()
            counter("device_rounds_total",
                    help="sweep rounds executed on-device",
                    backend="tpu").inc(rounds)
            if rounds > 0:
                # Same accounting as one host-checked round at a time:
                # every executed round counts in full, except the final
                # round's overshoot past the requested end.
                last_base = base + (rounds - 1) * round_size
                tried += (rounds - 1) * round_size \
                    + min(round_size, end - last_base)
            # min_nonce >= end can only be an overshoot past the requested
            # range (never a wrap: wrapping rounds were excluded above) —
            # and then no later round could hold an in-range winner either.
            if count > 0 and base <= min_nonce < end:
                winner = core.set_nonce(header80, min_nonce)
                return SearchResult(min_nonce, core.header_hash(winner),
                                    tried)
            base += rounds * round_size
        if base < end:
            with span("backend.tpu.host_tail"):
                nonce, t = core.cpu_search(header80, base, end - base,
                                           difficulty_bits)
            tried += t
            if nonce is not None:
                winner = core.set_nonce(header80, nonce)
                return SearchResult(nonce, core.header_hash(winner), tried)
        return SearchResult(None, None, tried)
