"""TPU miner_backend: jit'd batched nonce sweeps on one or more chips.

Replaces the reference's per-rank scalar loop + MPI collectives with one jit'd
XLA program per sweep round (SURVEY.md §3.4): the host sees only
(count, min_nonce) per round; with n_miners > 1 the sweep runs under
shard_map over the 'miners' mesh axis and the winner-select pmin/psum ride
the ICI (parallel/mesh.py) — the TPU-native form of first-finder MPI_Bcast +
height allreduce.

Early exit under jit: rounds cover contiguous ranges [base, base + R) from
start_nonce upward, so the first round containing any qualifier yields the
exact global lowest nonce — deterministic and backend-independent.
"""
from __future__ import annotations

import numpy as np

from .. import core
from . import MinerBackend, SearchResult, register

NONCE_SPACE = 1 << 32


@register("tpu")
class TpuBackend(MinerBackend):
    def __init__(self, batch_pow2: int = 20, n_miners: int = 1,
                 kernel: str = "auto", mesh=None):
        import jax  # deferred so cpu-only users never import jax

        self.batch_size = 1 << batch_pow2
        self.n_miners = n_miners
        self.kernel = kernel
        self._sweeps: dict[int, object] = {}  # difficulty -> compiled fn
        if n_miners > 1:
            from ..parallel.mesh import MeshSweeper
            self._mesh_sweeper = MeshSweeper(n_miners=n_miners,
                                             batch_size=self.batch_size,
                                             kernel=kernel, mesh=mesh)
        else:
            self._mesh_sweeper = None
        self._jax = jax

    # ---- kernel selection -------------------------------------------------

    def _single_sweep(self, difficulty_bits: int):
        fn = self._sweeps.get(difficulty_bits)
        if fn is None:
            from ..ops import select_kernel
            fn, self.effective_kernel = select_kernel(
                self.kernel, self.batch_size, difficulty_bits)
            self._sweeps[difficulty_bits] = fn
        return fn

    # ---- the plugin contract ---------------------------------------------

    def search(self, header80: bytes, difficulty_bits: int,
               start_nonce: int = 0, max_count: int = NONCE_SPACE
               ) -> SearchResult:
        midstate, tail = core.header_midstate(header80)
        end = min(start_nonce + max_count, NONCE_SPACE)
        round_size = self.batch_size * self.n_miners
        tried = 0
        base = start_nonce
        while base < end:
            # The device sweeps full batches (static shapes). A final round
            # that would wrap past 2^32 could surface a wrapped low nonce
            # from *unswept* space and shadow a genuine in-range winner, so
            # that partial tail (< round_size nonces) runs on the CPU oracle
            # instead.
            if base + round_size > NONCE_SPACE:
                nonce, t = core.cpu_search(header80, base, end - base,
                                           difficulty_bits)
                tried += t
                if nonce is not None:
                    winner = core.set_nonce(header80, nonce)
                    return SearchResult(nonce, core.header_hash(winner),
                                        tried)
                break
            if self._mesh_sweeper is not None:
                count, min_nonce = self._mesh_sweeper.sweep(
                    midstate, tail, base, difficulty_bits)
            else:
                fn = self._single_sweep(difficulty_bits)
                count, min_nonce = fn(midstate, tail,
                                      np.uint32(base))
            count = int(count)
            min_nonce = int(min_nonce)
            tried += min(round_size, end - base)
            # min_nonce >= end can only be an overshoot past the requested
            # range (never a wrap: wrapping rounds were handled above).
            if count > 0 and base <= min_nonce < end:
                winner = core.set_nonce(header80, min_nonce)
                return SearchResult(min_nonce, core.header_hash(winner), tried)
            base += round_size
        return SearchResult(None, None, tried)


