"""Multi-node network simulation + adversarial harness (BASELINE config 5).

Rebuilds the reference's multi-rank world — N nodes that mine concurrently,
announce found blocks, and resolve forks by longest-chain — as an in-process
simulation: C++ Nodes connected by a message bus with injectable delay,
drop, and partition faults (SURVEY.md §5 "failure detection": harness-level
fault injection on block announcements).

Determinism: the simulation advances in discrete steps. Each step, every
live group mines with a bounded nonce budget; found blocks are enqueued on
the bus with a configurable delivery delay (in steps). Within a step,
deliveries happen before mining, in (send_step, sender_id) order. Given the
same faults schedule, a run is exactly reproducible — the adversarial reorg
tests assert on this.
"""
from __future__ import annotations

import dataclasses
from typing import Callable

from . import core
from .backend import MinerBackend, backend_from_config
from .config import ConfigError, MinerConfig, extend_payload
from .resilience import injection
from .telemetry import (CausalLog, counter, dump_causal_logs, gauge,
                        heartbeat, histogram)

# Byzantine-sync length budget: the longest adopt suffix a node accepts
# from a peer in one sync. An honest same-difficulty peer can never be
# this far ahead inside one simulation (the bus delivers every step);
# a response past it is a resource-exhaustion attack, not a fork heal.
MAX_SYNC_SUFFIX = 4096

# RecvResult codes as stable event vocabulary for the causal logs.
_RESULT_NAMES = {
    core.RecvResult.APPENDED: "appended",
    core.RecvResult.DUPLICATE: "duplicate",
    core.RecvResult.STALE_OR_FORK: "stale_or_fork",
    core.RecvResult.INVALID: "invalid",
    core.RecvResult.REORGED: "reorged",
    core.RecvResult.IGNORED_SHORTER: "ignored_shorter",
}


def _hdr_info(header80: bytes) -> dict:
    """Block identity fields every causal event carries: short hash,
    short prev hash, and height (timestamps are structural: ts == height)."""
    f = core.HeaderFields.unpack(header80)
    return {"hash": core.header_hash(header80).hex()[:12],
            "prev": f.prev_hash.hex()[:12],
            "height": f.timestamp}


@dataclasses.dataclass
class _Message:
    send_step: int
    deliver_step: int
    sender: int
    header80: bytes
    lamport: int = 0   # the sender's Lamport stamp at broadcast time


@dataclasses.dataclass
class GroupStats:
    blocks_mined: int = 0
    blocks_accepted_from_peers: int = 0   # via direct tip extension (receive)
    blocks_adopted: int = 0               # gained via suffix/chain adoption
    reorgs: int = 0
    reorged_away_blocks: int = 0   # blocks actually rolled back by adoptions
    headers_fetched: int = 0       # sync-protocol transfer accounting

    def conserved_height(self) -> int:
        """Every chain mutation is accounted, so a node's height is exactly
        mined + accepted + adopted - reorged_away (the fuzz invariant)."""
        return (self.blocks_mined + self.blocks_accepted_from_peers
                + self.blocks_adopted - self.reorged_away_blocks)


def locator_heights(tip: int) -> list[int]:
    """Bitcoin-style block locator: the last 10 heights step 1, then
    exponentially widening gaps, always ending at genesis. O(log height)
    entries; the first entry a peer recognizes bounds the common ancestor
    from below, making fork-heal transfer O(suffix), not O(height)."""
    heights, step, h = [], 1, tip
    while h > 0:
        heights.append(h)
        if len(heights) >= 10:
            step *= 2
        h -= step
    heights.append(0)
    return heights


class SimNode:
    """One miner group in the simulation: a C++ Node + backend + progress.

    ``retarget`` (a ``sim.retarget.RetargetRule``) arms the C++ chain's
    height-scheduled difficulty rule: candidates carry the scheduled
    bits (the search targets whatever the candidate demands), and the
    C++ ``valid_child`` enforces the schedule on every adoption path —
    local submits AND synced suffixes — so a peer serving wrong-bits
    headers is rejected exactly like one serving bad PoW.
    """

    def __init__(self, node_id: int, config: MinerConfig,
                 backend: MinerBackend | None = None, retarget=None):
        self.id = node_id
        self.config = config
        self.retarget = retarget
        self.node = core.Node(config.difficulty_bits, node_id)
        if retarget is not None:
            retarget.apply(self.node)
        if backend is None:  # honor the config's plugin choice (cli `sim
            # --backend tpu` runs the device sweep inside each group);
            # each group is ONE rank, so the cpu pool stays unthreaded
            backend = backend_from_config(config, cpu_ranks=1)
        self.backend = backend
        self.stats = GroupStats()
        # Causal observability: every bus interaction this node takes part
        # in is stamped into its bounded Lamport-clock log (telemetry/
        # causal.py) — the forensics CLI merges these across nodes.
        self.causal = CausalLog(node_id)
        # The bus's current step, mirrored in by Network.step() so events
        # recorded inside node methods carry the simulation time too.
        self.sim_step = 0
        # Per-height search position, so a group resumes its sweep across
        # steps instead of restarting at nonce 0 (restarting would let a
        # slower group never finish a block at higher difficulty).
        self._next_nonce = 0
        # Bumped when the 2^32 nonce space is exhausted without a winner:
        # it varies the candidate payload (hence data_hash), opening a
        # fresh search space instead of re-sweeping dead nonces forever.
        self._extra_nonce = 0
        self._tip_at_start = self.node.tip_hash

    def _candidate(self) -> bytes:
        data = f"{self.config.data_prefix}:g{self.id}:" \
               f"{self.node.height + 1}".encode()
        return self.node.make_candidate(
            extend_payload(data, self._extra_nonce))

    def mine_step(self, nonce_budget: int) -> bytes | None:
        """Searches up to nonce_budget nonces; returns a mined header or None.

        The tip moving (own block or peer block adopted) resets the sweep —
        the reference's preemption point (SURVEY.md §3.2): a stale candidate
        would fail prev-hash validation anyway.
        """
        tip = self.node.tip_hash
        if tip != self._tip_at_start:
            self._next_nonce = 0
            self._extra_nonce = 0
            self._tip_at_start = tip
        cand = self._candidate()
        # The candidate's own bits field IS the target: under a retarget
        # rule the C++ make_candidate stamps the scheduled bits for the
        # next height; without one it equals config.difficulty_bits.
        res = self.backend.search(cand, core.HeaderFields.unpack(cand).bits,
                                  start_nonce=self._next_nonce,
                                  max_count=nonce_budget)
        if res.nonce is None:
            self._next_nonce += nonce_budget
            if self._next_nonce >= 1 << 32:
                # Nonce space exhausted at this height: bump the extra
                # nonce so the next candidate carries different payload
                # data (new data_hash => a genuinely fresh search space).
                self._extra_nonce += 1
                self._next_nonce = 0
            return None
        winner = core.set_nonce(cand, res.nonce)
        assert self.node.submit(winner), "own block failed validation"
        self.causal.record("mine", step=self.sim_step, **_hdr_info(winner))
        self.stats.blocks_mined += 1
        self._next_nonce = 0
        self._extra_nonce = 0
        self._tip_at_start = self.node.tip_hash
        return winner

    # ---- sync protocol (SURVEY.md §3.3: "request chain (suffix)") -------

    def find_anchor(self, locator: list[tuple[int, bytes]]) -> int:
        """Serve side: highest locator entry present on OUR chain (O(1)
        each via the C++ hash index). Heights are structural (timestamp ==
        height), so a common block sits at the same height on both chains;
        genesis is always common, so this never fails for same-difficulty
        peers."""
        for height, digest in locator:          # descending heights
            if self.node.find(digest) == height:
                return height
        return 0

    def receive(self, header80: bytes, peer: "SimNode",
                lamport: int | None = None) -> None:
        """Consensus on a peer announcement (SURVEY.md §3.3).

        ``lamport`` is the announcement's causal stamp (from the bus
        message); receipt merges it into this node's clock. Direct calls
        without a stamp (tests, ad-hoc wiring) record a plain local event.
        """
        r = self.node.receive(header80)
        self.causal.record("deliver", merge=lamport, step=self.sim_step,
                           sender=peer.id,
                           result=_RESULT_NAMES.get(r, str(r)),
                           **_hdr_info(header80))
        if r == core.RecvResult.APPENDED:
            self.stats.blocks_accepted_from_peers += 1
        elif r == core.RecvResult.STALE_OR_FORK:
            # Height gate on the peer's LIVE height (one O(1) query — the
            # reference's height-allreduce shape): a peer whose chain is
            # not longer than ours cannot win adoption, so syncing on its
            # stale announcement could only return IGNORED_SHORTER. Old
            # losing-branch announcements flushed at a partition heal
            # would otherwise each trigger a redundant O(suffix) fetch.
            # The ANNOUNCED height must not be the gate: under delivery
            # delay the announcement is stale while the peer's chain has
            # grown, and gating on it can suppress sync forever when the
            # delay exceeds the peer's lead (equal-rate fork livelock).
            if peer.node.height > self.node.height:
                self._sync_from(peer)

    def _sync_from(self, peer: "SimNode") -> None:
        """O(suffix) longest-chain sync: send a block locator, fetch only
        the peer's headers above the common ancestor, adopt the suffix.
        Falls back to a genesis-anchored (full-chain) fetch if the suffix
        unexpectedly fails to validate — the locator guarantees the anchor
        is common, so the fallback is pure defense in depth.

        The peer's response is NOT trusted wholesale: before adoption it
        must pass the byzantine bounds (``_validate_suffix`` — header
        size, header-chain linkage from the anchor, and the
        ``MAX_SYNC_SUFFIX`` length budget), or the sync is rejected with
        a ``sync_rejected`` causal event and the chain stays untouched.
        """
        own_height = self.node.height
        locator = [(h, self.node.block_hash(h))
                   for h in locator_heights(own_height)]
        anchor = peer.find_anchor(locator)
        suffix = peer.node.headers_from(anchor)
        # The sync is a request/response exchange with TWO causal edges:
        # our request reaches the peer (its serve event merges OUR clock),
        # and its response reaches us (our sync event merges the serve
        # stamp) — so a suffix adoption is always causally after the
        # serve, and the serve always after the deliver that triggered it.
        serve = peer.causal.record("serve_headers",
                                   merge=self.causal.clock.time,
                                   step=peer.sim_step,
                                   requester=self.id, anchor=anchor,
                                   count=len(suffix))
        self.causal.record("sync", merge=serve["lamport"],
                           step=self.sim_step, peer=peer.id, anchor=anchor,
                           fetched=len(suffix))
        self.stats.headers_fetched += len(suffix)
        reason = self._validate_suffix(anchor, suffix)
        if reason is not None:
            self._reject_sync(peer, anchor, len(suffix), reason)
            return
        res = self._adopt(anchor, suffix, own_height, peer=peer.id)
        if res == core.RecvResult.INVALID and anchor > 0:
            full = peer.node.all_headers()
            serve = peer.causal.record("serve_headers",
                                       merge=self.causal.clock.time,
                                       step=peer.sim_step,
                                       requester=self.id, anchor=0,
                                       count=len(full))
            self.causal.record("sync", merge=serve["lamport"],
                               step=self.sim_step, peer=peer.id, anchor=0,
                               fetched=len(full))
            self.stats.headers_fetched += len(full)
            reason = self._validate_suffix(0, full)
            if reason is not None:
                self._reject_sync(peer, 0, len(full), reason)
                return
            self._adopt(0, full, own_height, peer=peer.id)

    def _validate_suffix(self, anchor: int,
                         suffix: list[bytes]) -> str | None:
        """Byzantine bounds on a sync response; None when acceptable.

        Linkage is checked Python-side before any C++ adoption work:
        header i's prev_hash must equal the hash of header i-1 (the
        anchor block for i == 0), every header must be exactly 80
        bytes, and the whole response must fit the length budget. A
        forged response therefore costs O(len) hashing to reject and
        can never roll back a single block.
        """
        if len(suffix) > MAX_SYNC_SUFFIX:
            return (f"suffix length {len(suffix)} exceeds the "
                    f"{MAX_SYNC_SUFFIX}-header sync budget")
        prev = self.node.block_hash(anchor)
        for i, header in enumerate(suffix):
            if len(header) != core.HEADER_SIZE:
                return (f"header {i} is {len(header)} bytes, "
                        f"not {core.HEADER_SIZE}")
            fields = core.HeaderFields.unpack(header)
            if fields.prev_hash != prev:
                return f"header-chain linkage broken at offset {i}"
            if self.retarget is not None:
                expected = self.retarget.expected_bits(
                    self.config.difficulty_bits, anchor + 1 + i)
                if fields.bits != expected:
                    # The C++ valid_child would reject this too, but
                    # only after the anchor walk; pre-checking here
                    # gives the rejection a distinct causal reason the
                    # forensics attack audit can count.
                    return (f"retarget bits mismatch at offset {i}: "
                            f"got {fields.bits}, schedule demands "
                            f"{expected}")
            prev = core.header_hash(header)
        return None

    def _reject_sync(self, peer: "SimNode", anchor: int, count: int,
                     reason: str) -> None:
        self.causal.record("sync_rejected", step=self.sim_step,
                           peer=peer.id, anchor=anchor, count=count,
                           reason=reason)
        counter("sim_sync_rejected_total",
                help="peer sync responses rejected by the byzantine "
                     "bounds before adoption").inc()

    def _adopt(self, anchor: int, suffix: list[bytes],
               own_height: int, peer=None) -> int:
        old = [self.node.block_hash(i)
               for i in range(anchor + 1, own_height + 1)]
        old_tip = self.node.tip_hash.hex()[:12]
        res = self.node.adopt_suffix(anchor, suffix)
        if res == core.RecvResult.REORGED:
            rolled_hashes = [d.hex()[:12] for d in old
                             if self.node.find(d) < 0]
            rolled_back = len(rolled_hashes)
            adopted = self.node.height - own_height + rolled_back
            # ``peer`` (who served the adopted suffix) lets the
            # forensics flood audit prove chains-untouched non-vacuously.
            self.causal.record("adopt", step=self.sim_step,
                               peer=peer,
                               old_tip=old_tip,
                               new_tip=self.node.tip_hash.hex()[:12],
                               height=self.node.height, anchor=anchor,
                               adopted=adopted, rolled_back=rolled_back,
                               rolled_back_hashes=rolled_hashes)
            self.stats.blocks_adopted += adopted
            if rolled_back:
                self.stats.reorgs += 1
                self.stats.reorged_away_blocks += rolled_back
                counter("sim_reorgs_total",
                        help="chain reorganizations across all groups"
                        ).inc()
                histogram("sim_reorg_depth",
                          help="blocks rolled back per reorg"
                          ).observe(rolled_back)
        return res


class Network:
    """Message bus with fault injection between SimNodes."""

    def __init__(self, nodes: list[SimNode], delay_steps: int = 0,
                 drop_fn: Callable[[int, int, int], bool] | None = None,
                 partitioned_until: int | None = None):
        """drop_fn(step, sender, receiver) -> True to drop the delivery.

        partitioned_until: until that step, announcements do not cross
        between nodes at all (two isolated miner groups building competing
        chains — the BASELINE config-5 adversary).
        """
        self.nodes = nodes
        self.delay_steps = delay_steps
        self.drop_fn = drop_fn
        self.partitioned_until = partitioned_until
        self.queue: list[_Message] = []
        self.step_count = 0
        # The bus's own causal log: drops and partition-deferrals happen
        # IN the network, not on any node, so they are recorded by a
        # pseudo-node "bus" whose clock merges each message's send stamp.
        self.causal = CausalLog("bus")

    def _blocked(self, step: int, sender: int, receiver: int) -> bool:
        if self.partitioned_until is not None and step < self.partitioned_until:
            return True
        if self.drop_fn is not None and self.drop_fn(step, sender, receiver):
            return True
        return False

    def broadcast(self, sender: int, header80: bytes) -> None:
        counter("sim_messages_sent_total",
                help="block announcements enqueued on the bus").inc()
        deliver_step = self.step_count + self.delay_steps
        rec = self.nodes[sender].causal.record(
            "send", step=self.step_count, deliver_step=deliver_step,
            **_hdr_info(header80))
        self.queue.append(_Message(self.step_count, deliver_step,
                                   sender, header80,
                                   lamport=rec["lamport"]))

    def deliver_due(self, horizon: int = 0) -> None:
        """Delivers messages with deliver_step <= step_count + horizon.

        horizon > 0 is the post-target flush: in-flight announcements may
        be due up to delay_steps in the future, and no further mining steps
        will advance the clock to meet them.
        """
        # Mirror the bus clock into every node so node-side events
        # (deliver/sync/adopt) carry the SAME step as the bus-side
        # drop/defer events of this delivery round — including the
        # post-target flush, which runs after step() incremented the
        # clock past the nodes' last mirrored value.
        for node in self.nodes:
            node.sim_step = self.step_count
        cutoff = self.step_count + horizon
        due = [m for m in self.queue if m.deliver_step <= cutoff]
        self.queue = [m for m in self.queue if m.deliver_step > cutoff]
        due.sort(key=lambda m: (m.send_step, m.sender))
        for m in due:
            sender_node = self.nodes[m.sender]
            for node in self.nodes:
                if node.id == m.sender:
                    continue
                if self._blocked(self.step_count, m.sender, node.id):
                    # Re-queue across a partition: real networks retransmit;
                    # the reference's collective world never loses the
                    # broadcast, so the partition delays rather than
                    # destroys it.
                    if (self.partitioned_until is not None
                            and self.step_count < self.partitioned_until):
                        counter("sim_messages_partition_deferred_total",
                                help="deliveries deferred to the "
                                     "partition heal").inc()
                        self.causal.record(
                            "defer", merge=m.lamport, step=self.step_count,
                            sender=m.sender, receiver=node.id,
                            until_step=self.partitioned_until,
                            **_hdr_info(m.header80))
                        self.queue.append(dataclasses.replace(
                            m, deliver_step=self.partitioned_until))
                    else:
                        counter("sim_messages_dropped_total",
                                help="deliveries lost to the drop "
                                     "schedule").inc()
                        self.causal.record(
                            "drop", merge=m.lamport, step=self.step_count,
                            sender=m.sender, receiver=node.id,
                            **_hdr_info(m.header80))
                    continue
                # Fault-injection hook, per delivery attempt: raise/hang
                # crash the sim step (the flight recorder's home turf);
                # partial loses THIS delivery; corrupt damages the header
                # in flight so consensus must reject it (both recorded on
                # the bus's causal log for the forensics merge).
                header80 = m.header80
                fault = injection.check("sim.deliver", sender=m.sender,
                                        receiver=node.id)
                if fault is not None:
                    self.causal.record(
                        "fault", merge=m.lamport, step=self.step_count,
                        site="sim.deliver", fault=fault.kind,
                        sender=m.sender, receiver=node.id,
                        **_hdr_info(m.header80))
                    if fault.kind == "partial":
                        counter("sim_messages_fault_lost_total",
                                help="deliveries lost to an injected "
                                     "partial fault").inc()
                        continue
                    # corrupt: flip a data_hash byte — same length, so
                    # consensus sees a VALID-shaped but PoW-broken header.
                    header80 = (header80[:40] +
                                bytes([header80[40] ^ 0xFF]) +
                                header80[41:])
                node.receive(header80, sender_node, lamport=m.lamport)
                counter("sim_messages_delivered_total",
                        help="announcements delivered to a peer").inc()

    def step(self, nonce_budget: int = 1 << 16) -> None:
        """One simulation step: deliver, then every group mines a slice."""
        self.deliver_due()   # also mirrors step_count into node.sim_step
        for node in self.nodes:
            mined = node.mine_step(nonce_budget)
            if mined is not None:
                self.broadcast(node.id, mined)
        self.step_count += 1
        self.mirror_stats()
        # Progress heartbeat: /healthz watches the last_set age, so a
        # stalled sim (wedged backend, runaway step) flips unhealthy.
        heartbeat("sim_heartbeat").set(self.step_count)

    def mirror_stats(self) -> None:
        """Mirrors every group's GroupStats (+ height) as labeled gauges
        — the bus's counters see traffic; these see consensus state."""
        for node in self.nodes:
            g = str(node.id)
            for name, value in dataclasses.asdict(node.stats).items():
                gauge(f"sim_group_{name}", group=g).set(value)
            gauge("sim_group_height",
                  help="current chain height per group",
                  group=g).set(node.node.height)

    def run(self, target_height: int, max_steps: int = 10_000,
            nonce_budget: int = 1 << 16) -> int:
        """Steps until every node reaches target_height on ONE chain.

        Mining continues past target_height while tips disagree: an
        equal-height fork (both groups found a block at the same height) can
        only be broken by the next block — the keep-first rule means neither
        side adopts at equal length, exactly like the reference's
        longest-chain world.
        """
        while self.step_count < max_steps:
            self.step(nonce_budget)
            if all(n.node.height >= target_height for n in self.nodes):
                # Flush in-flight announcements (due up to delay_steps
                # ahead of the clock), then check for one chain.
                self.deliver_due(horizon=self.delay_steps)
                # The flush can adopt/reorg after the last step's mirror.
                self.mirror_stats()
                if self.converged():
                    return self.step_count
        err = RuntimeError(f"no convergence in {max_steps} steps")
        # The failed network IS the post-mortem: callers (sim CLI, flight
        # recorder) read .network off the exception to dump causal logs.
        err.network = self
        raise err

    def converged(self) -> bool:
        tips = {n.node.tip_hash for n in self.nodes}
        return len(tips) == 1

    # ---- causal observability export ------------------------------------

    def causal_logs(self) -> list:
        """Every per-node causal log plus the bus's own (drop/defer) log."""
        return [n.causal for n in self.nodes] + [self.causal]

    def dump_causal(self, path, meta: dict | None = None):
        """Write all causal logs as one forensics-ready JSON artifact
        (CLI: ``sim --events-dump PATH``; reader:
        ``python -m mpi_blockchain_tpu.forensics --events PATH``)."""
        base = {"steps": self.step_count, "converged": self.converged(),
                "n_nodes": len(self.nodes),
                "heights": [n.node.height for n in self.nodes],
                "delay_steps": self.delay_steps,
                "partitioned_until": self.partitioned_until}
        base.update(meta or {})
        return dump_causal_logs(self.causal_logs(), path, meta=base)


def seeded_drop(drop_rate_pct: int, seed: int = 0
                ) -> Callable[[int, int, int], bool]:
    """Deterministic pseudo-random drop_fn: ~drop_rate_pct% of deliveries.

    Keyed by (step, sender, receiver, seed) through crc32, so a run with
    the same faults schedule is exactly reproducible (the simulation's
    determinism contract) with no global RNG state.
    """
    import struct
    import zlib

    def drop(step: int, sender: int, receiver: int) -> bool:
        key = struct.pack("<IIII", step, sender, receiver, seed)
        return zlib.crc32(key) % 100 < drop_rate_pct

    return drop


def run_adversarial(config: MinerConfig | None = None,
                    partition_steps: int = 30, target_height: int = 8,
                    nonce_budget: int = 1 << 8, delay_steps: int = 1,
                    drop_rate_pct: int = 0, seed: int = 0,
                    n_groups: int = 2, retarget=None,
                    on_network: Callable[["Network"], None] | None = None
                    ) -> Network:
    """BASELINE config 5: competing miner groups, then reconciliation.

    n_groups groups mine in a partition (building competing chains with
    different payloads), the partition heals, and longest-chain reorg
    resolution must converge every node onto one chain — optionally under
    delivery delay and seeded random message loss on top of the partition.
    ``retarget`` (a ``sim.retarget.RetargetRule``) arms every group's
    chain with the height-scheduled difficulty rule.
    """
    if n_groups < 2:
        raise ConfigError(f"n_groups must be >= 2, got {n_groups}")
    cfg = config if config is not None else MinerConfig(
        difficulty_bits=8, n_blocks=target_height, backend="cpu")
    nodes = [SimNode(i, cfg, retarget=retarget) for i in range(n_groups)]
    net = Network(nodes, delay_steps=delay_steps,
                  drop_fn=(seeded_drop(drop_rate_pct, seed)
                           if drop_rate_pct else None),
                  partitioned_until=partition_steps)
    if on_network is not None:
        # Hand the network out BEFORE the run: a non-converging run raises
        # out of net.run, and the caller (sim CLI / flight recorder) still
        # needs the causal logs of the failed run.
        on_network(net)
    net.run(target_height, nonce_budget=nonce_budget)
    return net
