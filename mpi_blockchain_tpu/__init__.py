"""mpi_blockchain_tpu — TPU-native rebuild of CatOfTheCannals/MPI_blockchain.

A proof-of-work blockchain framework where the per-rank MPI nonce search of
the reference becomes a vmapped/Pallas SHA-256 sweep on TPU, and the MPI
broadcast/allreduce collectives become XLA ICI collectives over a
``jax.sharding.Mesh`` (BASELINE.json north-star; SURVEY.md §7).

Layout:
  core/      C++ chain kernel (sha256, Block, Chain, Node) via ctypes
  backend/   miner_backend plugin boundary: {cpu, tpu}
  ops/       device sha256d sweep kernels (pure-jnp and Pallas)
  parallel/  mesh construction + winner-select collectives
  models/    the Miner driver (flagship jittable mine step)
  utils/     logging, profiling, serialization helpers
"""

__version__ = "0.1.0"

from .config import MinerConfig, PRESETS  # noqa: F401
