"""Sharded nonce sweep over a 'miners' device mesh.

Search-space data parallelism (SURVEY.md §2.3): round r covers the contiguous
global range [base, base + n_miners*B); device i sweeps its B-sized slice
(offset by jax.lax.axis_index). The collective epilogue —
psum(local count) and pmin(local min qualifying nonce) — is the TPU-native
replacement for the reference's first-finder MPI_Bcast + height allreduce:
the pmin result is replicated to every device over the ICI, which *is* the
broadcast. Deterministic winner = lowest qualifying nonce; ties are
impossible (nonce ranges are disjoint), so no device-id tiebreak is needed.

Multi-host scaling: the same shard_map program runs unchanged over a
multi-host mesh (jax.distributed.initialize + all hosts executing the same
program); XLA then routes the pmin/psum over ICI within a slice and DCN
across slices. See parallel/distributed.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config import ConfigError
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

_U32 = jnp.uint32


def replicated_host_value(x) -> np.ndarray:
    """Host numpy value of a replicated (out_specs=P()) sharded output.

    Single-process arrays convert directly; on a multi-process (multi-host)
    mesh the global array is not fully addressable, but every process's
    local shard of a replicated output is the full value.
    """
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    return np.asarray(x.addressable_data(0))


def make_miner_mesh(n_miners: int) -> Mesh:
    """A 1-D ('miners',) mesh over the first n_miners local devices."""
    devices = jax.devices()
    if len(devices) < n_miners:
        raise ConfigError(
            f"need {n_miners} devices for the miners mesh, have "
            f"{len(devices)} (tests: XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n_miners})")
    return jax.make_mesh((n_miners,), ("miners",),
                         devices=devices[:n_miners])


def make_mesh_sweep_fn(mesh: Mesh, batch_size: int, difficulty_bits: int,
                       kernel: str = "auto"):
    """Builds the jit'd sharded sweep: (midstate, tail, base) -> (count, min).

    All inputs are replicated; outputs are replicated scalars (the collective
    epilogue reduces across 'miners'). One XLA program per round — the entire
    mine-round including the "MPI" step is a single device computation.
    """
    from ..ops import select_kernel

    sweep, _ = select_kernel(kernel, batch_size, difficulty_bits, shard=True)

    def per_device(midstate, tail_w, base):
        i = jax.lax.axis_index("miners").astype(_U32)
        local_base = jnp.asarray(base).astype(_U32) + i * np.uint32(batch_size)
        count, min_nonce = sweep(midstate, tail_w, local_base)
        # Winner-select: the reference's MPI_Bcast/allreduce, as ICI
        # collectives. min_nonce is 0xFFFFFFFF where count==0, so pmin
        # directly yields the global lowest qualifying nonce.
        total = jax.lax.psum(count, "miners")
        gmin = jax.lax.pmin(min_nonce, "miners")
        return total, gmin

    sharded = jax.shard_map(per_device, mesh=mesh,
                            in_specs=(P(), P(), P()), out_specs=(P(), P()))
    return jax.jit(sharded)


class MeshSweeper:
    """Per-difficulty cache of jit'd sharded sweeps over one miners mesh."""

    def __init__(self, n_miners: int, batch_size: int, kernel: str = "auto",
                 mesh: Mesh | None = None):
        self.mesh = mesh if mesh is not None else make_miner_mesh(n_miners)
        self.n_miners = n_miners
        self.batch_size = batch_size
        self.kernel = kernel
        self._fns: dict[int, object] = {}

    def sweep(self, midstate, tail_w, base: int, difficulty_bits: int):
        fn = self._fns.get(difficulty_bits)
        if fn is None:
            fn = make_mesh_sweep_fn(self.mesh, self.batch_size,
                                    difficulty_bits, self.kernel)
            self._fns[difficulty_bits] = fn
        count, gmin = fn(jnp.asarray(midstate), jnp.asarray(tail_w),
                         np.uint32(base))
        return (int(replicated_host_value(count)),
                int(replicated_host_value(gmin)))
