"""Sharded nonce sweep over a 'miners' device mesh.

Search-space data parallelism (SURVEY.md §2.3): round r covers the contiguous
global range [base, base + n_miners*B); device i sweeps its B-sized slice
(offset by jax.lax.axis_index). The collective epilogue —
psum(local count) and pmin(local min qualifying nonce) — is the TPU-native
replacement for the reference's first-finder MPI_Bcast + height allreduce:
the pmin result is replicated to every device over the ICI, which *is* the
broadcast. Deterministic winner = lowest qualifying nonce; ties are
impossible (nonce ranges are disjoint), so no device-id tiebreak is needed.

Multi-host scaling: the same shard_map program runs unchanged over a
multi-host mesh (jax.distributed.initialize + all hosts executing the same
program); XLA then routes the pmin/psum over ICI within a slice and DCN
across slices. See parallel/distributed.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config import ConfigError
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

# The ONE shard_map compat seam: every shard_map in the repo (and in
# tests) goes through this name with the modern check_vma spelling.
# Keyed on the actual kwarg, not the export location: some versions
# export top-level jax.shard_map that still spells the replication
# check check_rep.
def _resolve_shard_map():
    import inspect
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        params = {}
    if "check_vma" in params:
        return fn

    def compat(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        # check_rep-era shard_map has no replication rule for while_loop
        # (the multi-round searcher), so it defaults off here; an
        # explicit check_vma choice is still honored.
        kw["check_rep"] = bool(check_vma) if check_vma is not None else False
        return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
    return compat


shard_map = _resolve_shard_map()

_U32 = jnp.uint32


def replicated_host_value(x) -> np.ndarray:
    """Host numpy value of a replicated (out_specs=P()) sharded output.

    Single-process arrays convert directly; on a multi-process (multi-host)
    mesh the global array is not fully addressable, but every process's
    local shard of a replicated output is the full value.
    """
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    return np.asarray(x.addressable_data(0))


def replicated_host_values(xs) -> tuple:
    """Batched replicated_host_value: starts every D2H copy before blocking
    on any — one tunnel round trip for all outputs instead of one each
    (the axon tunnel bills ~90 ms per blocking transfer)."""
    xs = tuple(xs)
    for x in xs:
        try:
            (x if getattr(x, "is_fully_addressable", True)
             else x.addressable_data(0)).copy_to_host_async()
        except AttributeError:
            pass
    return tuple(replicated_host_value(x) for x in xs)


def record_mesh_topology(mesh: Mesh, local_devices: int | None = None
                         ) -> None:
    """Host-side topology gauges, stamped whenever a miners mesh is
    built: the mesh-wide device count (replicated, unlabeled) and this
    process's share under its ``rank`` label — the meshwatch aggregator
    reads the latter per-rank, so an 8-rank merge shows exactly which
    rank brought how many chips (docs/observability.md §Mesh shards)."""
    from ..telemetry import gauge, rank_gauge

    gauge("mesh_devices", help="devices in the ('miners',) mesh").set(
        mesh.size)
    rank_gauge("mesh_rank_local_devices",
               help="devices this rank contributes to the mesh").set(
        local_devices if local_devices is not None else mesh.size)


def make_miner_mesh(n_miners: int) -> Mesh:
    """A 1-D ('miners',) mesh over the first n_miners local devices."""
    devices = jax.devices()
    if len(devices) < n_miners:
        raise ConfigError(
            f"need {n_miners} devices for the miners mesh, have "
            f"{len(devices)} (tests: XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n_miners})")
    mesh = jax.make_mesh((n_miners,), ("miners",),
                         devices=devices[:n_miners])
    record_mesh_topology(mesh)
    return mesh


def maybe_shard_over_miners(fn, n_miners: int, mesh: Mesh | None,
                            n_out: int, donate_argnames: tuple = ()):
    """jit-wraps a device program, shard_map'd over ('miners',) when
    n_miners > 1 OR an explicit mesh is passed — 1-element-axis collectives
    compile the same program, which is how the production sharded path gets
    hardware-proven on a single chip (bench.py sharded_pallas section).
    fn must accept an `axis_name` parameter (None = unsharded); its other
    parameters are the device inputs — in_specs arity is derived from the
    signature so callers cannot hand-miscount it. All inputs and the n_out
    outputs are replicated.

    ``donate_argnames`` names parameters of ``fn`` whose buffers are
    DONATED to the dispatch (the double-buffer pipeline handoff: the
    fused miner threads its tip words output -> input across pipelined
    calls). Names are resolved against ``fn``'s own signature and passed
    to ``jax.jit`` as positions — the shard_map wrapper's signature is
    opaque to jit's own name resolution."""
    import functools
    import inspect
    params = [p.name for p in inspect.signature(fn).parameters.values()]
    if "axis_name" not in params:
        raise ConfigError(
            f"shardable device fn {getattr(fn, '__name__', fn)!r} must "
            f"take an axis_name parameter; has {params}")
    n_in = len(params) - 1
    unknown = [n for n in donate_argnames if n not in params[:n_in]]
    if unknown:
        raise ConfigError(
            f"donate_argnames {unknown} not among the device inputs "
            f"{params[:n_in]} of {getattr(fn, '__name__', fn)!r}")
    donate = tuple(params.index(n) for n in donate_argnames)
    if n_miners > 1 or mesh is not None:
        if mesh is None:
            mesh = make_miner_mesh(n_miners)
        elif mesh.size != max(n_miners, 1):
            # A mismatch would leave per-round slices [n_devices*batch,
            # n_miners*batch) silently unswept — breaking the lowest-nonce
            # determinism contract. Fail at build time instead.
            raise ConfigError(
                f"mesh has {mesh.size} devices but n_miners={n_miners}; "
                f"the 'miners' axis must match the round split exactly")
        sharded = shard_map(functools.partial(fn, axis_name="miners"),
                            mesh=mesh, in_specs=(P(),) * n_in,
                            out_specs=(P(),) * n_out)
        return jax.jit(sharded, donate_argnums=donate)
    return jax.jit(functools.partial(fn, axis_name=None),
                   donate_argnums=donate)


def make_round_search(sweep, batch_size: int, round_size: int):
    """The multi-round device search loop, shared by the per-block searcher
    (backend/tpu.py) and the fused miner (models/fused.py).

    Returns run(ext (EXT_WORDS,)u32, start u32, n_rounds u32,
    axis_name=None) -> (rounds_done u32, count i32, min_nonce u32): a
    lax.while_loop over ascending rounds r covering [start + r*round_size,
    +round_size) that exits at the first round containing a qualifier.
    ``ext`` is the per-template extended-midstate payload
    (``ops.sha256_sched.extend_midstate``) — hoisted OUTSIDE the round
    loop by construction, so the nonce-invariant precompute is paid once
    per template, never per round. count/min_nonce are the LAST executed
    round's result (min_nonce == 0xFFFFFFFF when count == 0); rounds
    ascend, so the winner is the exact global lowest qualifying nonce —
    the determinism contract. n_rounds is a traced scalar: one compile
    serves any round budget.
    """
    # round_size == 2^32 (one round = the whole nonce space) is a legal
    # config whose multiplier overflows uint32; masked it becomes 0, which
    # stays correct because the only executable round is then r == 0.
    round_size_u32 = np.uint32(round_size & 0xFFFFFFFF)

    def run(ext, start, n_rounds, axis_name=None):
        def cond(s):
            r, c, _ = s
            return (c == 0) & (r < n_rounds)

        def body(s):
            r, _, _ = s
            base = (jnp.asarray(start).astype(_U32) + r * round_size_u32)
            if axis_name is not None:
                c, mn = sweep(ext,
                              sharded_local_base(base, batch_size,
                                                 axis_name))
                c, mn = winner_select(c, mn, axis_name)
            else:
                c, mn = sweep(ext, base)
            return r + np.uint32(1), c, mn

        from ..ops.sha256_jnp import NOT_FOUND_U32
        return jax.lax.while_loop(
            cond, body, (np.uint32(0), jnp.zeros((), jnp.int32),
                         jnp.asarray(NOT_FOUND_U32)))

    return run


def sharded_local_base(base, batch_size: int, axis_name: str = "miners"):
    """This device's slice offset of a round's contiguous global range:
    round r covers [base, base + n_miners*batch_size); device i sweeps
    [base + i*batch_size, +batch_size)."""
    i = jax.lax.axis_index(axis_name).astype(_U32)
    return jnp.asarray(base).astype(_U32) + i * np.uint32(batch_size)


#: The full uint32 nonce space every striping scheme must tile exactly.
NONCE_SPACE = 1 << 32


def stripe_windows(index: int, n_live: int, batch_size: int,
                   space: int = NONCE_SPACE):
    """The nonce windows the dense-``index``-th of ``n_live`` live ranks
    sweeps, ascending — the HOST-side twin of ``sharded_local_base``:
    round r covers the contiguous range [r*n_live*B, +n_live*B) and the
    index-th rank owns its B-sized slice of every round, so the union of
    all live ranks' windows is EXACTLY [0, space) with no gap and no
    overlap (the elastic re-stripe invariant; property-tested in
    tests/test_elastic.py for every world_size <= 8 x dead-subset pair).

    Yields ``(start, end)`` pairs. n_live == 1 yields one full-space
    window (no reason to chop a lone rank's sweep into round slices).
    Keeping this next to ``sharded_local_base`` is deliberate: they
    encode the same striping rule and must change together.
    """
    if not 0 <= index < n_live:
        raise ConfigError(f"stripe index {index} out of range for "
                          f"{n_live} live rank(s)")
    if batch_size < 1 or space < 1:
        raise ConfigError(f"stripe batch_size/space must be >= 1, got "
                          f"{batch_size}/{space}")
    if n_live == 1:
        yield (0, space)
        return
    round_size = n_live * batch_size
    for base in range(index * batch_size, space, round_size):
        yield (base, min(base + batch_size, space))


def winner_select(count, min_nonce, axis_name: str = "miners"):
    """The reference's MPI_Bcast/allreduce as ICI collectives: psum the
    qualifier count, pmin the per-device min qualifying nonce (0xFFFFFFFF
    where none), replicated to every device — the pmin result arriving on
    all devices *is* the first-finder broadcast. Deterministic winner =
    lowest nonce; ties impossible (disjoint ranges), so no device-id
    tiebreak is needed. The ONE copy of the winner-select epilogue, shared
    by the per-round mesh sweep, the multi-round searcher (backend/tpu.py),
    and the fused miner (models/fused.py)."""
    return (jax.lax.psum(count, axis_name),
            jax.lax.pmin(min_nonce, axis_name))


def make_mesh_sweep_fn(mesh: Mesh, batch_size: int, difficulty_bits: int,
                       kernel: str = "auto"):
    """Builds the jit'd sharded sweep: (midstate, tail, base) -> (count, min).

    All inputs are replicated; outputs are replicated scalars (the collective
    epilogue reduces across 'miners'). One XLA program per round — the entire
    mine-round including the "MPI" step is a single device computation. The
    extended-midstate precompute (``ops.sha256_sched.extend_midstate``)
    runs once per call on replicated scalars, outside the shard_map.
    """
    from ..dispatchwatch import note_cache
    from ..ops import extend_midstate, select_kernel

    sweep, _ = select_kernel(kernel, batch_size, difficulty_bits, shard=True)

    def per_device(ext, base):
        count, min_nonce = sweep(ext, sharded_local_base(base, batch_size))
        return winner_select(count, min_nonce)

    sharded = shard_map(per_device, mesh=mesh,
                        in_specs=(P(), P()), out_specs=(P(), P()))

    def fn(midstate, tail_w, base):
        return sharded(extend_midstate(jnp.asarray(midstate, _U32),
                                       jnp.asarray(tail_w, _U32)), base)

    jfn = jax.jit(fn)
    note_cache(site="mesh.sweep", entries=1)

    def instrumented(midstate, tail_w, base):
        # Host-side skew span around the sharded dispatch (the call,
        # never the traced body — chainlint JAX006): its enter stamp is
        # this process's arrival at the round whose epilogue is the
        # winner-select rendezvous, joinable across hosts on a
        # multi-process mesh.
        from ..dispatchwatch import compile_scope
        from ..meshprof.spans import skew_span

        with skew_span(site="mesh.sweep"), \
                compile_scope(site="mesh.sweep"):
            return jfn(midstate, tail_w, base)

    return instrumented
