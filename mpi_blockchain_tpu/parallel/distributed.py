"""Multi-host scaling: the DCN analogue of the reference's multi-node MPI.

The reference scales past one machine by launching more MPI ranks
(`mpirun -np N` across hosts); this framework scales the same search by
widening the 'miners' mesh across TPU hosts. The sharded sweep program in
parallel/mesh.py is written against mesh axis names, not device counts, so
it runs unchanged on a multi-host mesh: XLA routes the psum/pmin winner
collectives over ICI within a slice and DCN across slices — no NCCL/MPI
translation, per the project's TPU-first mandate.

Single-host processes (this image has one host/chip) use init_local; a real
multi-host job calls init_distributed on every host with the same
coordinator address before any jax call, then make_global_miner_mesh.
"""
from __future__ import annotations

import jax



def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> None:
    """Joins the jax.distributed world (call once per host, before jax use).

    With no arguments, jax.distributed.initialize auto-discovers the TPU pod
    topology from the environment (the standard v5e multi-host launch).
    Callers that want the wedged-coordinator case survivable wrap this in
    ``resilience.call_with_retry(site="distributed.init")`` — the CLI's
    ``_init_world`` does (docs/resilience.md).
    """
    from ..resilience import injection
    injection.check("distributed.init",
                    coordinator=str(coordinator_address))
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    # The authoritative rank stamp: every rank-labeled metric and every
    # meshwatch shard this process writes from here on carries the real
    # process index, not a launcher-guessed one — including the shard
    # FILE identity (an auto-detected launch armed the writer as rank 0
    # on every host; rebind moves each to its real rank_NNNN.json).
    from ..meshwatch.shard import rebind_installed
    from ..telemetry import set_mesh_rank
    set_mesh_rank(jax.process_index())
    rebind_installed(jax.process_index(), jax.process_count())


def make_global_miner_mesh() -> jax.sharding.Mesh:
    """1-D ('miners',) mesh over every device in the (multi-host) world.

    jax.devices() is global after init_distributed, so the mesh spans hosts;
    each host runs the same sharded sweep and XLA keeps the winner-select
    collective consistent across DCN.
    """
    from .mesh import record_mesh_topology

    mesh = jax.make_mesh((len(jax.devices()),), ("miners",))
    record_mesh_topology(mesh, local_devices=len(jax.local_devices()))
    return mesh


def world_info() -> dict:
    """Process/topology info (the reference's rank/size introspection)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
