"""Multi-chip parallelism: mesh construction + winner-select collectives.

The TPU-native replacement for the reference's OpenMPI backend
(SURVEY.md §5 "Distributed comm backend"): first-finder MPI_Bcast becomes a
pmin winner-select inside the sharded sweep, height allreduce becomes a psum
— both ride the ICI, with no cross-process boundary on a single host.
"""
from .mesh import (make_mesh_sweep_fn, make_miner_mesh,  # noqa: F401
                   sharded_local_base, winner_select)
