"""Live-bus attackers for the REAL ``simulation.Network`` / ``SimNode``.

The vectorized engine (``sim.vecnet``) scales attacks to 1000 nodes over
a lightweight chain model; this module aims the same strategies at the
real thing — C++ chains, 80-byte headers, the genuine ``_sync_from``
byzantine bounds — so the PR 5 sync budget and linkage checks are
exercised by a live attacker on a live bus instead of hand-built
fixtures (ISSUE 6 satellite: byzantine-bounds regression tests).

* ``FloodingSimNode`` joins a ``Network`` as a normal (non-mining)
  node whose ``node`` facade lies about its height and serves forged
  deep suffixes: every peer that hears its stale-tip announcement runs
  the full receive -> live-height gate -> ``_sync_from`` ->
  ``_validate_suffix`` path and must reject with ``sync_rejected``
  (budget or linkage, by mode), chain untouched.
* ``eclipse_drop_fn`` expresses an eclipse window as a composed drop
  schedule for the legacy bus: during [start, until) the victim hears
  only the attacker (and speaks only to it); afterwards the normal
  longest-chain sync must pull the victim back onto the honest chain.

Determinism: forged bytes come from sha256 over (seed, counter) — no
``os.urandom``, no wall clock (chainlint RES002 covers this module).
"""
from __future__ import annotations

import hashlib

from ..simulation import MAX_SYNC_SUFFIX, Network, SimNode

#: Height the lying facade claims: any honest gate "is the peer ahead of
#: me?" must pass, no matter the victim's real height.
CLAIMED_HEIGHT = 1 << 30


def _forged_header(seed: int, i: int) -> bytes:
    """80 deterministic garbage bytes — VALID length, so the size gate
    passes and the linkage/budget gates do the rejecting."""
    d = hashlib.sha256(f"flood|{seed}|{i}".encode()).digest()
    return (d * 3)[:80]


class _LyingNode:
    """Facade over a real ``core.Node``: honest for the flooder's own
    consensus bookkeeping, byzantine on the serve side — inflated
    ``height`` plus forged ``headers_from``/``all_headers``."""

    def __init__(self, real, mode: str, seed: int):
        if mode not in ("budget", "linkage"):
            raise ValueError(f"flood mode must be budget|linkage, "
                             f"got {mode!r}")
        self._real = real
        self.mode = mode
        self.seed = seed
        # The lie is for the SERVE side (peers probing us). The owning
        # FloodingSimNode switches it off around its own consumption so
        # the inherited receive/sync logic sees the real chain.
        self.lying = True

    def __getattr__(self, name):
        return getattr(self._real, name)

    @property
    def height(self) -> int:
        return CLAIMED_HEIGHT if self.lying else self._real.height

    def _forged(self) -> list[bytes]:
        if self.mode == "budget":
            # One header past the sync budget: the length gate must fire
            # before any linkage hashing happens.
            return [_forged_header(self.seed, i)
                    for i in range(MAX_SYNC_SUFFIX + 1)]
        # Unlinked garbage inside the budget: the linkage gate's turf.
        return [_forged_header(self.seed, i) for i in range(3)]

    def headers_from(self, from_height: int) -> list[bytes]:
        return self._forged()

    def all_headers(self) -> list[bytes]:
        return self._forged()


class FloodingSimNode(SimNode):
    """A stale-tip flooder on the live bus. It never mines; it follows
    the honest chain through normal deliveries; and on ``flood()`` it
    broadcasts a forged stale announcement that drags every peer through
    the byzantine sync bounds."""

    def __init__(self, node_id: int, config, mode: str = "budget",
                 seed: int = 0):
        super().__init__(node_id, config)
        self.node = _LyingNode(self.node, mode, seed)
        self.seed = seed
        self.floods = 0

    def mine_step(self, nonce_budget: int):
        return None                     # all malice, no work

    def receive(self, header80: bytes, peer, lamport=None) -> None:
        """Honest consumption despite the lying serve facade: with the
        lie left on, the inherited sync gate would compare the peer's
        height against OUR inflated claim and never sync, wedging the
        flooder on any losing fork. An attacker must track the live tip
        to keep forging stale announcements against it, so the lie is
        switched off for the duration of our own receive."""
        self.node.lying = False
        try:
            super().receive(header80, peer, lamport=lamport)
        finally:
            self.node.lying = True

    def forged_announcement(self) -> bytes:
        # A fresh unknown header each flood: peers must see
        # STALE_OR_FORK (not DUPLICATE) and re-run the gate.
        self.floods += 1
        return _forged_header(self.seed + 7919, self.floods)

    def flood(self, net: Network) -> bytes:
        """Broadcasts one forged stale-tip announcement on the bus.
        Delivery (next ``deliver_due``) makes every honest peer sync
        from us and reject."""
        hdr = self.forged_announcement()
        self.causal.record("attack_flood", step=self.sim_step,
                           mode=self.node.mode, flood=self.floods)
        net.broadcast(self.id, hdr)
        return hdr


def eclipse_drop_fn(victim: int, attacker: int, start: int, until: int,
                    inner=None):
    """An eclipse window as a legacy-bus drop schedule: during
    [start, until) the victim's peer set is monopolized by the attacker
    — deliveries to the victim from anyone else, and from the victim to
    anyone else, are dropped. Outside the window, ``inner`` (e.g.
    ``seeded_drop`` or a ``Scenario.drop_fn()``) decides; composition
    precedence stays churn > partition > drop because the legacy bus
    consults ``partitioned_until`` before any drop_fn."""
    def drop(step: int, sender: int, receiver: int) -> bool:
        if start <= step < until:
            if receiver == victim and sender != attacker:
                return True
            if sender == victim and receiver != attacker:
                return True
        return inner(step, sender, receiver) if inner else False
    return drop
