"""Network-scale adversarial simulation (ISSUE 6 / ROADMAP item 5).

The scenario engine on top of ``simulation.py``:

* ``scenario`` — scenarios as pure values: seeded latency
  distributions, first-class partition windows, node churn
  (join/leave/crash-restart), adversary specs, and the documented
  churn > partition > drop fault-composition precedence.
* ``retarget`` — the height-scheduled difficulty-retarget rule shared
  with the C++ core (``Chain::expected_bits``), enforced on every
  adoption path.
* ``vecnet`` — the vectorized engine: ~1000 nodes x 10k steps via
  batched delivery masks and a Philox mining lottery, with the SAME
  consensus shape (keep-first, live-height sync gate, byzantine
  suffix bounds) and the same causal-event vocabulary as the real bus,
  so the forensics CLI audits both.
* ``strategies`` — pluggable adversaries: selfish mining
  (withhold-and-release), eclipse (peer-set monopolization), stale-tip
  flooding (forged deep suffixes vs the sync budget/linkage/retarget
  gates). Seeded-RNG-only by chainlint rule RES002.
* ``real_attackers`` — the same attacks aimed at the REAL
  ``Network``/``SimNode`` stack (C++ chains, 80-byte headers) for the
  byzantine-bounds regression tests and ``make adversary-smoke``.

CLI: ``python -m mpi_blockchain_tpu sim --preset adversarial-1k``
(scenario presets live in ``scenario.SCENARIO_PRESETS``; strategy /
churn / retarget flags compose ad-hoc scenarios). Every run is
byte-reproducible from its scenario value — see docs/resilience.md
§Adversaries.
"""
from __future__ import annotations

from .retarget import RetargetRule  # noqa: F401
from .scenario import (SCENARIO_PRESETS, AdversarySpec,  # noqa: F401
                       ChurnEvent, ChurnSchedule, LatencySpec,
                       PartitionWindow, Scenario, ScenarioRng)
from .vecnet import VecNetwork, run_scenario  # noqa: F401
