"""Pluggable adversary strategies for the vectorized scenario engine.

Three real attackers beyond the legacy drop/defer faults, each a small
state machine driven by the engine's step loop:

* **SelfishMiner** — withhold-and-release: mines privately on its own
  tip (its announcements are suppressed, counted in
  ``sim_selfish_blocks_withheld_total``), keeps the lead secret while it
  is >= 2, and releases the whole private chain the moment honest miners
  close the gap to one block — forcing the network to reorg onto the
  attacker's chain and orphan honest work. Falling behind abandons the
  private fork (the engine's normal sync adopts the public chain).
* **Eclipse** — monopolizes a victim's peer set for a window: every
  delivery to the victim not sent by the attacker is blocked (and the
  victim's own announcements reach only the attacker), so the victim
  extends an isolated fork; when the window closes, the first honest
  announcement triggers the standard live-height sync and the victim
  reorgs back — the recovery the byzantine regression tests assert.
* **StaleTipFlood** — spams forged deep suffixes at honest nodes,
  cycling through the three byzantine rejection paths (the
  ``max_sync_suffix`` length budget, broken header linkage, and a
  retarget-schedule bits mismatch). Every attempt must die in
  ``validate_suffix`` with a ``sync_rejected`` causal event and an
  untouched chain; the strategy asserts that — a flood that ever
  *succeeds* is a consensus bug, not an attack outcome.

Determinism contract (chainlint RES002): strategies draw randomness ONLY
from the engine's seeded ``ScenarioRng`` — no ``random``, no wall clock
— so every attack replays byte-identically under a fixed scenario.

Causal vocabulary added for the forensics attack audit
(``forensics/attack_audit.py``): ``attack_withhold`` / ``attack_release``
/ ``attack_abandon`` on the selfish miner's log, ``attack_eclipse_start``
/ ``attack_eclipse_end`` on the bus log, ``attack_flood`` on the
flooder's log (each flood's rejection lands as the victim's
``sync_rejected``).
"""
from __future__ import annotations

import numpy as np

from ..telemetry import counter
from .scenario import AdversarySpec


class AdversaryStrategy:
    """Hook surface the engine drives. Subclasses override what they
    need; every hook is a no-op by default."""

    name = "adversary"

    def __init__(self, eng, spec: AdversarySpec):
        self.eng = eng
        self.spec = spec
        self.node = spec.node
        eng.hashrate[spec.node] = float(spec.hashrate)

    def on_step_begin(self, eng, step: int) -> None:
        pass

    def on_mined(self, eng, step: int, node: int, block) -> bool:
        """Return False to suppress the broadcast of ``block``."""
        return True

    def filter_delivery(self, eng, step: int, sender: int, block,
                        mask: np.ndarray) -> np.ndarray:
        return mask

    def on_step_end(self, eng, step: int) -> None:
        pass

    def on_horizon_end(self, eng, step: int) -> None:
        """The faulted horizon is over (the converge margin begins):
        wind the attack down so reconciliation can complete."""

    def eclipsing(self) -> int:
        """Victims this strategy currently monopolizes (the engine sums
        these into the ``sim_eclipse_victims`` gauge each step, so
        overlapping windows add up instead of clobbering)."""
        return 0

    def summary(self) -> dict:
        return {}


class SelfishMiner(AdversaryStrategy):
    name = "selfish"

    def __init__(self, eng, spec: AdversarySpec):
        super().__init__(eng, spec)
        self.withheld: list[int] = []     # private block idxs, oldest first
        self.withheld_total = 0
        self.released_total = 0
        self.releases = 0
        self.abandoned_total = 0

    def _public_height(self, eng) -> int:
        mask = eng.alive.copy()
        mask[self.node] = False
        return int(eng.heights[mask].max()) if mask.any() else 0

    def on_mined(self, eng, step: int, node: int, block) -> bool:
        if node != self.node or not eng.fault_phase:
            return True              # margin steps mine honestly
        if self.withheld and block.prev_idx != self.withheld[-1]:
            # The engine adopted the public chain between our last
            # withhold and this find (deliver runs before mine in a
            # step): the old private fork is orphaned. Without this
            # check, tips == the NEW block would mask the abandonment
            # in on_step_end and a later release would re-broadcast
            # dead-fork blocks as if they were a private lead.
            self.abandoned_total += len(self.withheld)
            eng.log(self.node).record("attack_abandon", step=step,
                                      count=len(self.withheld))
            self.withheld = []
        self.withheld.append(block.idx)
        self.withheld_total += 1
        counter("sim_selfish_blocks_withheld_total",
                help="blocks mined and withheld by the selfish miner"
                ).inc()
        eng.log(self.node).record(
            "attack_withhold", step=step, hash=block.key,
            height=block.height,
            lead=int(eng.heights[self.node]) - self._public_height(eng))
        return False

    def on_step_end(self, eng, step: int) -> None:
        if not self.withheld:
            return
        # The engine's normal sync may have adopted the public chain over
        # our private tip (we fell behind): the withheld blocks are
        # orphaned — record the abandonment and reset.
        if int(eng.tips[self.node]) != self.withheld[-1]:
            self.abandoned_total += len(self.withheld)
            eng.log(self.node).record("attack_abandon", step=step,
                                      count=len(self.withheld))
            self.withheld = []
            return
        lead = int(eng.heights[self.node]) - self._public_height(eng)
        if lead > 1:
            return                     # keep the lead secret
        if lead < 1:
            # Public passed us between syncs; dump the fork.
            self.abandoned_total += len(self.withheld)
            eng.log(self.node).record("attack_abandon", step=step,
                                      count=len(self.withheld))
            self.withheld = []
            return
        # lead == 1: honest miners closed the gap — release everything;
        # our chain is strictly longer, so the network must reorg onto it.
        count = len(self.withheld)
        tip = eng.blocks[self.withheld[-1]]
        eng.log(self.node).record("attack_release", step=step, count=count,
                                  tip=tip.key, height=tip.height,
                                  lead=lead)
        counter("sim_selfish_blocks_released_total",
                help="withheld blocks released to force a reorg"
                ).inc(count)
        for idx in self.withheld:
            eng.broadcast(self.node, idx)
        self.released_total += count
        self.releases += 1
        self.withheld = []

    def on_horizon_end(self, eng, step: int) -> None:
        """End of the faulted horizon: a still-secret private fork must
        be played or folded — release it if it is (weakly) ahead, else
        abandon — so the fault-free margin can reconcile one chain."""
        if not self.withheld:
            return
        if int(eng.tips[self.node]) == self.withheld[-1] and \
                int(eng.heights[self.node]) >= self._public_height(eng):
            count = len(self.withheld)
            tip = eng.blocks[self.withheld[-1]]
            eng.log(self.node).record("attack_release", step=step,
                                      count=count, tip=tip.key,
                                      height=tip.height, lead=0)
            counter("sim_selfish_blocks_released_total").inc(count)
            for idx in self.withheld:
                eng.broadcast(self.node, idx)
            self.released_total += count
            self.releases += 1
        else:
            self.abandoned_total += len(self.withheld)
            eng.log(self.node).record("attack_abandon", step=step,
                                      count=len(self.withheld))
        self.withheld = []

    def summary(self) -> dict:
        eng = self.eng
        canonical = eng.chain_miners()
        revenue = canonical.get(self.node, 0)
        total = sum(canonical.values())
        return {
            "node": self.node,
            "hashrate_share": round(
                float(eng.hashrate[self.node])
                / float(eng.hashrate[eng.alive].sum()), 4)
            if eng.alive.any() else 0.0,
            "withheld_total": self.withheld_total,
            "released_total": self.released_total,
            "releases": self.releases,
            "abandoned_total": self.abandoned_total,
            "revenue_blocks": revenue,
            "revenue_share": round(revenue / total, 4) if total else 0.0,
        }


class Eclipse(AdversaryStrategy):
    name = "eclipse"

    def __init__(self, eng, spec: AdversarySpec):
        super().__init__(eng, spec)
        self.victim = spec.victim
        self.blocked_total = 0
        self._started = False
        self._ended = False

    def active(self, step: int) -> bool:
        # The faulted horizon bounds every window: an open-ended
        # (until=0) eclipse still lifts when the converge margin starts.
        return (self.eng.fault_phase and self.spec.start <= step
                and (self.spec.until == 0 or step < self.spec.until))

    def _end(self, eng, step: int) -> None:
        if self._ended or not self._started:
            return
        self._ended = True
        eng.bus_log.record("attack_eclipse_end", step=step,
                           attacker=self.node, victim=self.victim,
                           victim_height=int(eng.heights[self.victim]))

    def on_step_begin(self, eng, step: int) -> None:
        if step == self.spec.start:
            self._started = True
            eng.bus_log.record("attack_eclipse_start", step=step,
                               attacker=self.node, victim=self.victim,
                               until_step=self.spec.until,
                               victim_height=int(eng.heights[self.victim]))
        if self.spec.until and step == self.spec.until:
            self._end(eng, step)

    def eclipsing(self) -> int:
        return 1 if self._started and not self._ended else 0

    def on_horizon_end(self, eng, step: int) -> None:
        # An open-ended window (until=0), or one reaching past the
        # horizon, really ends when the fault phase does — the gauge
        # and the audit's end event must say so.
        self._end(eng, step)

    def filter_delivery(self, eng, step: int, sender: int, block,
                        mask: np.ndarray) -> np.ndarray:
        if not self.active(step):
            return mask
        if sender == self.victim:
            # The victim's announcements reach only the attacker.
            kept = mask.copy()
            kept[:] = False
            kept[self.node] = mask[self.node]
            n_blocked = int(mask.sum()) - int(kept.sum())
            if n_blocked:
                self.blocked_total += n_blocked
                counter("sim_eclipse_blocked_total",
                        help="deliveries blocked by an eclipse "
                             "attacker monopolizing a victim's peers"
                        ).inc(n_blocked)
            return kept
        if sender != self.node and mask[self.victim]:
            mask = mask.copy()
            mask[self.victim] = False
            self.blocked_total += 1
            counter("sim_eclipse_blocked_total",
                    help="deliveries blocked by an eclipse attacker "
                         "monopolizing a victim's peers").inc()
        return mask

    def summary(self) -> dict:
        eng = self.eng
        return {
            "node": self.node,
            "victim": self.victim,
            "window": [self.spec.start, self.spec.until],
            "blocked_total": self.blocked_total,
            "victim_converged": bool(
                eng.tips[self.victim] == eng.canonical_tip().idx),
        }


class _ForgedBlock:
    """A stand-in header the flooder serves: quacks like a LightBlock
    for ``validate_suffix`` but never enters the store."""
    __slots__ = ("key", "prev_key", "height", "bits")

    def __init__(self, key, prev_key, height, bits):
        self.key = key
        self.prev_key = prev_key
        self.height = height
        self.bits = bits


class StaleTipFlood(AdversaryStrategy):
    name = "flood"

    #: rejection paths exercised, in rotation.
    MODES = ("budget", "linkage", "bits")

    def __init__(self, eng, spec: AdversarySpec):
        super().__init__(eng, spec)
        self.attacks = 0
        self.rejected_by_mode = {m: 0 for m in self.MODES}

    def _forged_suffix(self, eng, victim: int, mode: str):
        tip = eng.blocks[int(eng.tips[victim])]
        base_bits = eng.scenario.difficulty_bits
        if mode == "budget":
            # One deep stale suffix past the sync budget: the length
            # gate must fire before any per-header work.
            filler = _ForgedBlock("flood-fill", "flood-fill",
                                  tip.height + 1, base_bits)
            return tip.key, [filler] * (eng.scenario.max_sync_suffix + 1)
        chain, prev = [], tip
        for i in range(3):
            height = tip.height + 1 + i
            bits = eng.rule.expected_bits(base_bits, height)
            if mode == "bits":
                bits = base_bits - 1 if base_bits > 1 else base_bits + 7
            prev_key = prev.key if (mode != "linkage" or i != 1) \
                else "forged-gap"
            blk = _ForgedBlock(f"flood-{self.attacks}-{i}", prev_key,
                               height, bits)
            chain.append(blk)
            prev = blk
        return tip.key, chain

    def on_step_begin(self, eng, step: int) -> None:
        spec = self.spec
        if not eng.fault_phase:
            return
        if step < max(1, spec.start) or (spec.until
                                         and step >= spec.until):
            return
        if (step - max(1, spec.start)) % spec.every != 0:
            return
        if not eng.alive[self.node]:
            return
        victim = spec.victim
        if victim < 0:
            victim = eng.rng.draw("adversary", self.node, step,
                                  mod=eng.n_nodes)
        if victim == self.node or not eng.alive[victim]:
            return                      # deterministic skip this round
        mode = self.MODES[self.attacks % len(self.MODES)]
        self.attacks += 1
        counter("sim_flood_attacks_total",
                help="forged deep-suffix sync attempts launched by the "
                     "stale-tip flooder").inc()
        eng.log(self.node).record("attack_flood", step=step,
                                  victim=victim, mode=mode)
        tip_before = int(eng.tips[victim])
        anchor_key, forged = self._forged_suffix(eng, victim, mode)
        reason = eng.validate_suffix(anchor_key, forged)
        # A forged suffix that VALIDATES would be a consensus hole, not
        # an attack outcome — fail the run loudly rather than absorb it.
        assert reason is not None, (
            f"forged {mode} suffix passed validation: consensus bug")
        eng.reject_sync(victim, self.node, len(forged), reason)
        self.rejected_by_mode[mode] += 1
        assert int(eng.tips[victim]) == tip_before, \
            "flood mutated the victim's chain"

    def summary(self) -> dict:
        return {
            "node": self.node,
            "attacks": self.attacks,
            "rejected_by_mode": dict(self.rejected_by_mode),
        }


_STRATEGIES = {
    "selfish": SelfishMiner,
    "eclipse": Eclipse,
    "flood": StaleTipFlood,
}


def build_strategies(eng) -> tuple[AdversaryStrategy, ...]:
    return tuple(_STRATEGIES[spec.kind](eng, spec)
                 for spec in eng.scenario.adversaries)
