"""Height-scheduled difficulty retargeting — the ONE rule, shared by the
C++ core and the vectorized simulation.

Timestamps in the frozen 80-byte header are structural (``timestamp ==
height``), so the only retarget rule every validator can agree on from
header bytes alone is a pure function of height:

    expected_bits(h) = min(base_bits + step_bits * (h // interval),
                           max_bits)                    for h >= 1
    expected_bits(0) = base_bits                        (genesis)

``interval == 0`` disables retargeting. The same closed form lives in
``chaincore::Chain::expected_bits`` (core/src/chain.cpp) — the C++ side
enforces it in ``valid_child`` on EVERY adoption path (submit, receive,
adopt_suffix), and this Python mirror lets the vectorized engine and the
SimNode pre-checks compute the schedule without a chain handle. The
equivalence is pinned by a test (tests/test_sim_adversarial.py).

Why a schedule and not a solve-rate feedback loop: with deterministic
structural timestamps there is no per-block time signal in the header, so
a rate-responsive rule could not be re-validated by a peer from the chain
bytes alone — it would break the "retarget rule validated on sync
adoption, not just locally" requirement (ISSUE 6). The schedule still
makes long-horizon scenarios meaningful: difficulty ramps as the chain
grows, so the block-production rate falls over a 10k-step run exactly as
a hardening network's would.
"""
from __future__ import annotations

import dataclasses

from ..config import ConfigError


@dataclasses.dataclass(frozen=True)
class RetargetRule:
    """The height schedule: +``step_bits`` difficulty every ``interval``
    blocks, clamped to ``max_bits`` (0 = uncapped at 255)."""
    interval: int
    step_bits: int = 1
    max_bits: int = 0

    def __post_init__(self):
        if self.interval < 0:
            raise ConfigError(f"retarget interval must be >= 0, "
                              f"got {self.interval}")
        if self.step_bits < 0:
            raise ConfigError(f"retarget step_bits must be >= 0, "
                              f"got {self.step_bits}")
        if self.max_bits < 0:
            raise ConfigError(f"retarget max_bits must be >= 0, "
                              f"got {self.max_bits}")

    def expected_bits(self, base_bits: int, height: int) -> int:
        """Bits a block at ``height`` must carry on a ``base_bits`` chain
        — the Python mirror of ``Chain::expected_bits``."""
        if self.interval == 0 or height == 0:
            return base_bits
        bits = base_bits + self.step_bits * (height // self.interval)
        cap = self.max_bits if self.max_bits else 255
        return min(bits, max(cap, base_bits))

    def apply(self, node) -> None:
        """Arms a ``core.Node`` with this rule (must still be at genesis)."""
        if self.interval and not node.set_retarget(
                self.interval, self.step_bits, self.max_bits):
            raise ConfigError(
                "cannot arm retargeting on a chain that already has "
                f"blocks (height {node.height})")

    @classmethod
    def parse(cls, spec: str) -> "RetargetRule":
        """CLI form ``INTERVAL[:STEP[:MAX]]`` (e.g. ``2000:1:20``)."""
        parts = spec.split(":")
        if not 1 <= len(parts) <= 3:
            raise ConfigError(f"--retarget wants INTERVAL[:STEP[:MAX]], "
                              f"got {spec!r}")
        try:
            nums = [int(p) for p in parts]
        except ValueError:
            raise ConfigError(f"--retarget wants integers, "
                              f"got {spec!r}") from None
        return cls(interval=nums[0],
                   step_bits=nums[1] if len(nums) > 1 else 1,
                   max_bits=nums[2] if len(nums) > 2 else 0)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)
