"""Scenario objects for the network-scale adversarial simulation.

Everything stochastic in a scenario — message latency, seeded drops,
partition membership, node churn, and every adversary decision — draws
from ONE seed through counter-based generators (crc32 for scalar
decisions, numpy Philox for per-step vectors), never from global RNG
state or the wall clock (chainlint rule RES002 enforces this statically
for the whole ``sim`` package). A scenario value therefore IS the run:
two executions of the same ``Scenario`` produce byte-identical causal
dumps, churn and attacks included.

Fault-composition precedence (the ``seeded_drop``/``drop_fn``
composition contract, asserted by tests/test_sim_adversarial.py):

1. **churn** — a delivery to (or from) a node that is down at the
   delivery step is LOST: the node is not there to retransmit to, and
   real gossip does not queue for dead peers. Checked first.
2. **partition** — a delivery crossing an active partition boundary is
   DEFERRED to the partition's heal step (real networks retransmit;
   the reference's collective world never loses a broadcast), exactly
   like the legacy ``Network.partitioned_until`` semantics.
3. **drop** — only a delivery that survived churn and partition is
   subject to the seeded random drop schedule, and a dropped delivery
   is LOST.

All three are evaluated at the DELIVERY step (matching the legacy bus,
whose ``_blocked`` runs when a message comes due), keyed by the single
scenario seed — so adding churn or a partition never perturbs the drop
schedule's draws for unrelated (step, sender, receiver) triples.
"""
from __future__ import annotations

import dataclasses
import struct
import zlib

import numpy as np

from ..config import ConfigError
from .retarget import RetargetRule

#: blocked() verdicts, in precedence order (index = priority).
LOST_CHURN = "churn"        # receiver (or sender) down: delivery lost
DEFER_PARTITION = "partition"   # deferred to the partition heal step
LOST_DROP = "drop"          # seeded random loss


class ScenarioRng:
    """Counter-based randomness for one scenario seed.

    ``draw(tag, *key, mod)`` is a stateless crc32 draw (the
    ``seeded_drop`` idiom): the same (seed, tag, key) always yields the
    same value, regardless of call order — churn cannot shift the drop
    schedule. ``vector(tag, *key, n)`` is a Philox-keyed uniform [0,1)
    vector for per-step batched draws (mining lottery, latency), equally
    order-independent because the Philox counter is derived from the
    key, not from stream position.
    """

    _TAGS = ("drop", "latency", "mine", "churn", "adversary", "partition")

    def __init__(self, seed: int):
        self.seed = int(seed)

    def draw(self, tag: str, *key: int, mod: int) -> int:
        tag_id = zlib.crc32(tag.encode())
        packed = struct.pack(f"<qI{len(key)}q", self.seed, tag_id, *key)
        return zlib.crc32(packed) % mod

    def uniform(self, tag: str, *key: int) -> float:
        """One crc32 draw scaled to [0, 1)."""
        return self.draw(tag, *key, mod=1 << 30) / float(1 << 30)

    def vector(self, tag: str, a: int, b: int, n: int) -> np.ndarray:
        """Uniform [0,1) vector of length n, keyed by (seed, tag, a, b).

        (seed, tag) and (a, b) go into the Philox KEY, not its counter:
        the counter is the intra-stream block index that advances as
        values are drawn, so two streams whose start counters differ by
        one would be the same sequence shifted by one block — distinct
        keys are what Philox guarantees independence for.
        """
        tag_id = zlib.crc32(tag.encode())
        key = np.array([
            (self.seed & 0xFFFFFFFF) << 32 | tag_id,
            (a & 0xFFFFFFFF) << 32 | (b & 0xFFFFFFFF),
        ], dtype=np.uint64)
        return np.random.Generator(np.random.Philox(key=key)).random(n)


@dataclasses.dataclass(frozen=True)
class LatencySpec:
    """Per-(announcement, receiver) delivery delay distribution, in sim
    steps. ``fixed`` always takes ``min_steps``; ``uniform`` draws from
    [min_steps, max_steps] inclusive."""
    kind: str = "fixed"           # "fixed" | "uniform"
    min_steps: int = 1
    max_steps: int = 1

    def __post_init__(self):
        if self.kind not in ("fixed", "uniform"):
            raise ConfigError(f"latency kind must be fixed|uniform, "
                              f"got {self.kind!r}")
        if self.min_steps < 0 or self.max_steps < self.min_steps:
            raise ConfigError(f"latency wants 0 <= min <= max, got "
                              f"[{self.min_steps}, {self.max_steps}]")

    def delays(self, rng: ScenarioRng, step: int, announce_seq: int,
               n: int) -> np.ndarray:
        """Integer delay per receiver index (vectorized, seeded)."""
        if self.kind == "fixed" or self.min_steps == self.max_steps:
            return np.full(n, self.min_steps, dtype=np.int64)
        u = rng.vector("latency", step, announce_seq, n)
        span = self.max_steps - self.min_steps + 1
        return self.min_steps + (u * span).astype(np.int64)

    @classmethod
    def parse(cls, spec: str) -> "LatencySpec":
        """CLI form ``N`` (fixed) or ``LO-HI`` (uniform)."""
        if "-" in spec:
            lo, _, hi = spec.partition("-")
            try:
                return cls("uniform", int(lo), int(hi))
            except ValueError:
                raise ConfigError(f"--latency wants N or LO-HI, "
                                  f"got {spec!r}") from None
        try:
            n = int(spec)
        except ValueError:
            raise ConfigError(f"--latency wants N or LO-HI, "
                              f"got {spec!r}") from None
        return cls("fixed", n, n)


@dataclasses.dataclass(frozen=True)
class PartitionWindow:
    """A first-class partition: from ``start`` (inclusive) to ``until``
    (exclusive) the node set splits into ``groups`` contiguous groups
    (node i in group ``i * groups // n_nodes``) and announcements do not
    cross group boundaries — they defer to the heal step ``until``."""
    start: int
    until: int
    groups: int = 2

    def __post_init__(self):
        if self.until <= self.start:
            raise ConfigError(f"partition window wants start < until, "
                              f"got [{self.start}, {self.until})")
        if self.groups < 2:
            raise ConfigError(f"partition wants >= 2 groups, "
                              f"got {self.groups}")

    def active(self, step: int) -> bool:
        return self.start <= step < self.until

    def group_of(self, node: int, n_nodes: int) -> int:
        return node * self.groups // n_nodes

    def groups_vec(self, n_nodes: int) -> np.ndarray:
        return (np.arange(n_nodes, dtype=np.int64)
                * self.groups) // n_nodes


@dataclasses.dataclass(frozen=True)
class ChurnEvent:
    """One membership change. Kinds: ``crash`` (down for ``down_steps``,
    then restart with chain intact — the crash-restart/checkpoint-recovery
    shape from PR 5), ``leave`` (down until a later ``join``), ``join``
    (restart a down node, chain intact, syncs via the normal protocol)."""
    step: int
    node: int
    kind: str
    down_steps: int = 0

    def __post_init__(self):
        if self.kind not in ("crash", "leave", "join"):
            raise ConfigError(f"churn kind must be crash|leave|join, "
                              f"got {self.kind!r}")
        if self.kind == "crash" and self.down_steps <= 0:
            raise ConfigError("churn crash wants down_steps >= 1")


@dataclasses.dataclass(frozen=True)
class ChurnSchedule:
    """The scenario's membership timeline, as a fixed event list — the
    same shape as a ``FaultPlan``: a pure value, derivable from a seed
    via crc32 with no global RNG (``from_seed`` mirrors
    ``FaultPlan.from_seed``), so a churned run replays byte-identically."""
    events: tuple[ChurnEvent, ...] = ()

    @classmethod
    def from_seed(cls, seed: int, n_nodes: int, steps: int,
                  n_events: int) -> "ChurnSchedule":
        rng = ScenarioRng(seed)
        events = []
        for i in range(n_events):
            step = 1 + rng.draw("churn", i, 0, mod=max(1, steps - 1))
            node = rng.draw("churn", i, 1, mod=n_nodes)
            down = 5 + rng.draw("churn", i, 2, mod=max(1, steps // 10))
            events.append(ChurnEvent(step=step, node=node, kind="crash",
                                     down_steps=down))
        return cls(events=tuple(events))

    def by_step(self, steps: int) -> dict[int, list[ChurnEvent]]:
        """Events indexed by step, crash restarts expanded into joins."""
        out: dict[int, list[ChurnEvent]] = {}
        for e in self.events:
            out.setdefault(e.step, []).append(e)
            if e.kind == "crash":
                up = e.step + e.down_steps
                if up < steps:
                    out.setdefault(up, []).append(
                        ChurnEvent(step=up, node=e.node, kind="join"))
        return out


@dataclasses.dataclass(frozen=True)
class AdversarySpec:
    """One adversary strategy instance: ``kind`` selects the class in
    ``sim.strategies``, ``node`` is the attacker's id. ``victim`` is
    eclipse's target; ``start``/``until`` bound windowed attacks;
    ``hashrate`` multiplies the attacker's per-step mining power
    (selfish mining is only interesting with a non-trivial share);
    ``every`` paces repeated attacks (flood)."""
    kind: str                     # "selfish" | "eclipse" | "flood"
    node: int
    victim: int = -1
    start: int = 0
    until: int = 0                # 0 = open-ended
    hashrate: int = 1
    every: int = 25

    def __post_init__(self):
        if self.kind not in ("selfish", "eclipse", "flood"):
            raise ConfigError(f"adversary kind must be selfish|eclipse|"
                              f"flood, got {self.kind!r}")
        if self.node < 0:
            # A negative id would numpy-wrap onto a DIFFERENT node.
            raise ConfigError(f"adversary node id must be >= 0, "
                              f"got {self.node}")
        if self.victim < -1:
            raise ConfigError(f"adversary victim must be a node id or "
                              f"-1 (none/seeded), got {self.victim}")
        if self.kind == "eclipse":
            if self.victim < 0:
                raise ConfigError("eclipse wants a victim node id")
            if self.victim == self.node:
                raise ConfigError("eclipse victim must differ from the "
                                  "attacker")
        if self.until and self.until <= self.start:
            raise ConfigError(f"adversary window wants start < until "
                              f"(or until=0 for open-ended), got "
                              f"[{self.start}, {self.until})")
        if self.start < 0:
            raise ConfigError("adversary start must be >= 0")
        if self.hashrate < 1:
            raise ConfigError("adversary hashrate multiplier must be >= 1")
        if self.every < 1:
            raise ConfigError("adversary every must be >= 1")

    @classmethod
    def parse(cls, spec: str) -> "AdversarySpec":
        """CLI form ``kind:key=value[,key=value...]``, e.g.
        ``selfish:node=1,hashrate=8`` or ``eclipse:node=2,victim=5,
        start=50,until=120`` or ``flood:node=3,every=20``."""
        kind, _, rest = spec.partition(":")
        kwargs: dict = {}
        if rest:
            for pair in rest.split(","):
                key, eq, value = pair.partition("=")
                if not eq:
                    raise ConfigError(f"--strategy wants key=value pairs, "
                                      f"got {pair!r} in {spec!r}")
                try:
                    kwargs[key.strip()] = int(value)
                except ValueError:
                    raise ConfigError(f"--strategy {key} wants an integer, "
                                      f"got {value!r}") from None
        kwargs.setdefault("node", 0)
        try:
            return cls(kind=kind.strip(), **kwargs)
        except TypeError as e:
            raise ConfigError(f"bad --strategy {spec!r}: {e}") from None


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One complete adversarial run, as a pure value (JSON-able via
    ``to_dict``). See the module docstring for the churn > partition >
    drop composition precedence ``blocked()`` implements."""
    n_nodes: int
    steps: int
    seed: int = 0
    difficulty_bits: int = 16
    # Expected hashes a node tries per step: P(block) per node per step
    # = hashes_per_step / 2^bits — the vectorized engine's abstract
    # stand-in for a backend sweep.
    hashes_per_step: int = 32
    retarget: RetargetRule | None = None
    latency: LatencySpec = LatencySpec()
    drop_rate_pct: int = 0
    partitions: tuple[PartitionWindow, ...] = ()
    churn: ChurnSchedule = ChurnSchedule()
    adversaries: tuple[AdversarySpec, ...] = ()
    # Per-delivery causal events (deliver/drop/defer). None = auto:
    # recorded for small worlds, summarized into counters at scale
    # (a 1000-node dump would be ~1e6 deliver events otherwise).
    record_deliveries: bool | None = None
    max_sync_suffix: int = 4096   # mirrors simulation.MAX_SYNC_SUFFIX
    # Extra steps (mining included) granted past ``steps`` to reconcile
    # — the vectorized form of the legacy "partition heals, then the
    # network must converge" epilogue. Margin steps are FAULT-FREE: the
    # drop schedule and the adversaries end with the scenario horizon
    # (a selfish miner must release-or-abandon its private fork there),
    # because under per-receiver random loss at 1000 nodes EVERY
    # announcement misses ~drop_rate% of the network, so strict tip
    # agreement is unreachable while the fault schedule is live.
    # 0 = hard cutoff, converged() reports the instantaneous truth.
    converge_margin: int = 0

    def __post_init__(self):
        if self.n_nodes < 2:
            raise ConfigError(f"scenario wants >= 2 nodes, "
                              f"got {self.n_nodes}")
        if self.steps < 1:
            raise ConfigError("scenario wants >= 1 step")
        if not 0 <= self.drop_rate_pct <= 100:
            raise ConfigError(f"drop_rate_pct must be in [0, 100], "
                              f"got {self.drop_rate_pct}")
        for a in self.adversaries:
            for field in ("node", "victim"):
                v = getattr(a, field)
                if v >= self.n_nodes:
                    raise ConfigError(f"adversary {a.kind} {field}={v} "
                                      f"outside the {self.n_nodes}-node "
                                      f"world")

    def rng(self) -> ScenarioRng:
        return ScenarioRng(self.seed)

    def record_deliveries_effective(self) -> bool:
        if self.record_deliveries is not None:
            return self.record_deliveries
        return self.n_nodes <= 64

    # ---- fault composition (the ONE blocked-decision point) -------------

    def partition_between(self, step: int, sender: int,
                          receiver: int) -> PartitionWindow | None:
        for w in self.partitions:
            if w.active(step) and (w.group_of(sender, self.n_nodes)
                                   != w.group_of(receiver, self.n_nodes)):
                return w
        return None

    def dropped(self, step: int, sender: int, receiver: int) -> bool:
        if not self.drop_rate_pct:
            return False
        rng = self.rng()
        return rng.draw("drop", step, sender, receiver,
                        mod=100) < self.drop_rate_pct

    def blocked(self, step: int, sender: int, receiver: int,
                alive=None) -> str | None:
        """The composed fault decision for one delivery attempt, under
        the documented precedence:

        1. ``"churn"``     — sender or receiver down at ``step`` (lost);
        2. ``"partition"`` — an active window separates them (deferred
           to the window's ``until``);
        3. ``"drop"``      — the seeded drop schedule fires (lost);
        4. ``None``        — delivered.

        ``alive`` is the engine's live-node predicate (node -> bool);
        without one, churn is judged from the static schedule alone.
        """
        if alive is not None:
            if not alive(receiver) or not alive(sender):
                return LOST_CHURN
        if self.partition_between(step, sender, receiver) is not None:
            return DEFER_PARTITION
        if self.dropped(step, sender, receiver):
            return LOST_DROP
        return None

    def drop_fn(self):
        """Legacy ``Network(drop_fn=...)`` adapter: the composed churn +
        drop verdicts as a plain (step, sender, receiver) -> bool (the
        legacy bus realizes partition windows via ``partitioned_until``
        and has no churn, so both non-deferring verdicts read as drops).
        Precedence inside the legacy bus is preserved: its ``_blocked``
        consults ``partitioned_until`` BEFORE this drop_fn, matching
        churn > partition > drop only when churn is empty — pass real
        churn through the vectorized engine instead."""
        def drop(step: int, sender: int, receiver: int) -> bool:
            verdict = self.blocked(step, sender, receiver)
            return verdict in (LOST_CHURN, LOST_DROP)
        return drop

    # ---- serialization ---------------------------------------------------

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["retarget"] = self.retarget.to_dict() if self.retarget else None
        return d


# ---- named scenario presets (cli: sim --preset <name>) --------------------

SCENARIO_PRESETS: dict[str, Scenario] = {
    # The ISSUE 6 headline: 1000 nodes, 10k steps, churn, retargeting,
    # a partition, and all three adversary strategies live at once.
    "adversarial-1k": Scenario(
        n_nodes=1000, steps=10_000, seed=0, difficulty_bits=16,
        hashes_per_step=32,
        # interval 600 => the canonical chain crosses ~3 retarget
        # boundaries inside the horizon, so the block rate measurably
        # decays and every post-boundary sync validates mixed-bits
        # suffixes (the "long-horizon scenarios are meaningful" point).
        retarget=RetargetRule(interval=600, step_bits=1, max_bits=20),
        latency=LatencySpec("uniform", 1, 3),
        drop_rate_pct=2,
        partitions=(PartitionWindow(start=2000, until=2400, groups=2),),
        churn=ChurnSchedule.from_seed(seed=0, n_nodes=1000, steps=10_000,
                                      n_events=40),
        adversaries=(
            AdversarySpec(kind="selfish", node=1, hashrate=120),
            AdversarySpec(kind="eclipse", node=2, victim=7,
                          start=4000, until=4500),
            AdversarySpec(kind="flood", node=3, every=50),
        ),
        converge_margin=2000,
    ),
    # The bench section's fixed workload (bench.py `sim_adversarial`):
    # mid-size so two reps cost ~2 s, all three strategies + churn +
    # retargeting live so the steps/sec number prices the full
    # adversarial machinery, not an idle bus.
    "adversarial-bench": Scenario(
        n_nodes=200, steps=1500, seed=11, difficulty_bits=14,
        hashes_per_step=32,
        retarget=RetargetRule(interval=120, step_bits=1, max_bits=17),
        latency=LatencySpec("uniform", 1, 3),
        drop_rate_pct=2,
        partitions=(PartitionWindow(start=300, until=420, groups=2),),
        churn=ChurnSchedule.from_seed(seed=11, n_nodes=200, steps=1500,
                                      n_events=10),
        adversaries=(
            AdversarySpec(kind="selfish", node=1, hashrate=24),
            AdversarySpec(kind="eclipse", node=2, victim=9,
                          start=600, until=750),
            AdversarySpec(kind="flood", node=3, every=40),
        ),
        converge_margin=600,
    ),
    # Small, fast variant with the same moving parts — the make
    # adversary-smoke / `make check` gate and the non-slow test surface.
    "adversarial-smoke": Scenario(
        n_nodes=24, steps=420, seed=7, difficulty_bits=10,
        hashes_per_step=16,
        retarget=RetargetRule(interval=50, step_bits=1, max_bits=12),
        latency=LatencySpec("uniform", 1, 2),
        drop_rate_pct=3,
        partitions=(PartitionWindow(start=80, until=140, groups=2),),
        churn=ChurnSchedule.from_seed(seed=7, n_nodes=24, steps=420,
                                      n_events=4),
        adversaries=(
            AdversarySpec(kind="selfish", node=1, hashrate=8),
            AdversarySpec(kind="eclipse", node=2, victim=5,
                          start=180, until=260),
            AdversarySpec(kind="flood", node=3, every=40),
        ),
        record_deliveries=True,
        converge_margin=400,
    ),
}
