"""The vectorized network engine: ~1000 nodes x 10k steps in minutes.

Where ``simulation.Network`` drives a handful of ``SimNode``s each owning
a real C++ chain and a real search backend, this engine scales the SAME
consensus protocol shape to network size by making both the mining and
the bus *batched*:

* **Mining** is an abstract lottery: node i finds a block in a step with
  probability ``hashes_per_step * hashrate_i / 2^bits`` — one seeded
  Philox vector draw per step for the whole world, not N backend sweeps.
  Blocks are lightweight records (prev/height/bits/miner/step) in one
  shared append-only store; a node's chain is its tip index plus the
  prev-pointer walk.
* **Delivery** is batched: announcements land in per-step buckets, each
  carrying a numpy receiver mask. Latency draws, drop draws, partition
  membership, and tip-extension appends are all vectorized over the
  receiver axis; only the rare consensus decisions (fork sync, reorg
  adoption) drop to per-group Python — and those are grouped by unique
  receiver tip, so 500 healing nodes cost one validation, not 500.
* **Consensus** mirrors ``SimNode`` exactly: extend-tip appends,
  keep-first at equal height, sync gated on the sender's LIVE height,
  suffix validation (length budget + linkage + retarget bits) BEFORE
  adoption, rejected syncs leave the chain untouched and emit
  ``sync_rejected`` causally + ``sim_sync_rejected_total``.

Fault composition follows ``Scenario.blocked()``'s documented precedence
— churn (lost) > partition (deferred to heal) > drop (lost) — evaluated
at the delivery step, vectorized. Every stochastic draw is keyed by the
scenario seed through counter-based generators (no global RNG, no wall
clock; chainlint RES002), so two runs of one scenario produce
byte-identical causal dumps, churn, retargeting and attacks included.
"""
from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from ..resilience import injection
from ..telemetry import (CausalLog, counter, dump_causal_logs, gauge,
                         heartbeat, histogram)
from .retarget import RetargetRule
from .scenario import (DEFER_PARTITION, LOST_CHURN, LOST_DROP,
                       ChurnEvent, Scenario, ScenarioRng)
from .strategies import build_strategies


class LightBlock:
    """One block in the shared store. ``key`` is the deterministic short
    hash the causal logs and forensics speak; ``idx`` its store index."""
    __slots__ = ("idx", "key", "prev_idx", "prev_key", "height", "bits",
                 "miner", "step")

    def __init__(self, idx, key, prev_idx, prev_key, height, bits, miner,
                 step):
        self.idx = idx
        self.key = key
        self.prev_idx = prev_idx
        self.prev_key = prev_key
        self.height = height
        self.bits = bits
        self.miner = miner
        self.step = step


@dataclasses.dataclass
class _Announce:
    """A broadcast in flight: ``mask`` is the receiver set still owed
    delivery at ``deliver_step`` (partition deferrals re-enqueue the
    blocked sub-mask at the heal step)."""
    seq: int
    send_step: int
    sender: int
    block_idx: int
    lamport: int
    mask: np.ndarray


class VecNetwork:
    """The scenario engine. ``run()`` executes the scenario's steps plus
    a drain phase and returns a JSON-able summary."""

    GENESIS_KEY = "genesis0"

    def __init__(self, scenario: Scenario):
        self.scenario = scenario
        self.rng: ScenarioRng = scenario.rng()
        n = scenario.n_nodes
        self.n_nodes = n
        self.step_count = 0
        self.record_deliveries = scenario.record_deliveries_effective()
        self.rule: RetargetRule = (scenario.retarget
                                   or RetargetRule(interval=0))
        # Block store: index 0 is genesis for every node.
        genesis = LightBlock(0, self.GENESIS_KEY, -1, "", 0,
                             scenario.difficulty_bits, -1, 0)
        self.blocks: list[LightBlock] = [genesis]
        self._block_index: dict[str, int] = {genesis.key: 0}
        # Vectorized node state.
        self.tips = np.zeros(n, dtype=np.int64)
        self.heights = np.zeros(n, dtype=np.int64)
        self.alive = np.ones(n, dtype=bool)
        self.hashrate = np.ones(n, dtype=np.float64)
        self.blocks_mined = np.zeros(n, dtype=np.int64)
        self.reorgs = np.zeros(n, dtype=np.int64)
        self.reorged_away = np.zeros(n, dtype=np.int64)
        # Delivery buckets: deliver_step -> announcements due then.
        self._buckets: dict[int, list[_Announce]] = {}
        self._announce_seq = 0
        self._churn_by_step = scenario.churn.by_step(scenario.steps)
        # Causal logs: per-node lazily (a 1000-node world where most
        # nodes never hit a consensus event should not allocate 1000
        # rings), plus the bus's own log for drop/defer/churn events.
        self._logs: dict[int, CausalLog] = {}
        self.bus_log = CausalLog("bus")
        self.strategies = build_strategies(self)
        self._sync_rejections = 0
        self._deliveries = 0
        self._drain_steps = 0
        # True during the scenario's faulted horizon; False in the
        # converge margin (fault-free reconciliation — see Scenario).
        self.fault_phase = True

    # ---- causal plumbing -------------------------------------------------

    def log(self, node: int) -> CausalLog:
        lg = self._logs.get(node)
        if lg is None:
            lg = self._logs[node] = CausalLog(node)
        return lg

    def _hdr_info(self, b: LightBlock) -> dict:
        return {"hash": b.key, "prev": b.prev_key, "height": b.height}

    # ---- block store -----------------------------------------------------

    def new_block(self, prev_idx: int, miner: int, step: int,
                  bits: int | None = None) -> LightBlock:
        prev = self.blocks[prev_idx]
        height = prev.height + 1
        if bits is None:
            bits = self.rule.expected_bits(self.scenario.difficulty_bits,
                                           height)
        key = hashlib.sha256(
            f"{prev.key}|{miner}|{height}|{step}|{self.scenario.seed}"
            .encode()).hexdigest()[:12]
        b = LightBlock(len(self.blocks), key, prev_idx, prev.key, height,
                       bits, miner, step)
        self.blocks.append(b)
        self._block_index[key] = b.idx
        return b

    def chain_suffix(self, tip_idx: int, above_height: int
                     ) -> list[LightBlock]:
        """Blocks on tip's chain with height > above_height, ascending."""
        out = []
        b = self.blocks[tip_idx]
        while b.height > above_height:
            out.append(b)
            b = self.blocks[b.prev_idx]
        out.reverse()
        return out

    def common_ancestor_height(self, a_idx: int, b_idx: int) -> int:
        a, b = self.blocks[a_idx], self.blocks[b_idx]
        while a.height > b.height:
            a = self.blocks[a.prev_idx]
        while b.height > a.height:
            b = self.blocks[b.prev_idx]
        while a.idx != b.idx:
            a = self.blocks[a.prev_idx]
            b = self.blocks[b.prev_idx]
        return a.height

    # ---- sync validation (the SimNode._validate_suffix mirror) -----------

    def validate_suffix(self, anchor_key: str, suffix) -> str | None:
        """Byzantine bounds on a sync response; None when acceptable.
        ``suffix`` is a list of LightBlocks (or forged stand-ins with the
        same attributes). Checks, in order: the ``max_sync_suffix``
        length budget, prev-key linkage from the anchor, and the
        retarget schedule on every header's bits — the same three gates
        ``SimNode`` applies to real 80-byte suffixes."""
        if len(suffix) > self.scenario.max_sync_suffix:
            return (f"suffix length {len(suffix)} exceeds the "
                    f"{self.scenario.max_sync_suffix}-header sync budget")
        prev = anchor_key
        for i, b in enumerate(suffix):
            if b.prev_key != prev:
                return f"header-chain linkage broken at offset {i}"
            expected = self.rule.expected_bits(
                self.scenario.difficulty_bits, b.height)
            if b.bits != expected:
                return (f"retarget bits mismatch at offset {i}: "
                        f"got {b.bits}, schedule demands {expected}")
            prev = b.key
        return None

    def reject_sync(self, node: int, peer: int, count: int,
                    reason: str) -> None:
        self.log(node).record("sync_rejected", step=self.step_count,
                              peer=peer, count=count, reason=reason)
        counter("sim_sync_rejected_total",
                help="peer sync responses rejected by the byzantine "
                     "bounds before adoption").inc()
        self._sync_rejections += 1

    # ---- delivery --------------------------------------------------------

    def broadcast(self, sender: int, block_idx: int,
                  mask: np.ndarray | None = None) -> None:
        """Enqueues one announcement; per-receiver latency buckets it."""
        b = self.blocks[block_idx]
        seq = self._announce_seq
        self._announce_seq += 1
        counter("sim_messages_sent_total",
                help="block announcements enqueued on the bus").inc()
        rec = self.log(sender).record("send", step=self.step_count,
                                      **self._hdr_info(b))
        base = np.ones(self.n_nodes, dtype=bool) if mask is None \
            else mask.copy()
        base[sender] = False
        delays = self.scenario.latency.delays(
            self.rng, self.step_count, seq, self.n_nodes)
        for d in np.unique(delays[base]):
            sub = base & (delays == d)
            # Clamped to >= 1: this step's bucket was already popped, so
            # a same-step key would strand the delivery (the legacy bus
            # likewise lands a delay-0 broadcast on the NEXT deliver).
            self._buckets.setdefault(
                self.step_count + max(int(d), 1), []).append(
                _Announce(seq, self.step_count, sender, block_idx,
                          rec["lamport"], sub))

    def _deliver_due(self) -> None:
        # Everything due AT OR BEFORE the clock (not just the exact key):
        # a stale bucket must never strand deliveries past its step.
        due_keys = sorted(k for k in self._buckets
                          if k <= self.step_count)
        if not due_keys:
            return
        due = [ann for k in due_keys for ann in self._buckets.pop(k)]
        due.sort(key=lambda a: (a.send_step, a.sender, a.seq))
        for ann in due:
            self._deliver_one(ann)

    def _deliver_one(self, ann: _Announce) -> None:
        step = self.step_count
        b = self.blocks[ann.block_idx]
        mask = ann.mask
        # Precedence 1 — churn: a receiver (or the sender) down at the
        # delivery step loses the delivery outright.
        if not self.alive[ann.sender]:
            lost = mask.copy()
        else:
            lost = mask & ~self.alive
        n_lost = int(lost.sum())
        if n_lost:
            counter("sim_messages_churn_lost_total",
                    help="deliveries lost to node churn (receiver or "
                         "sender down at the delivery step)").inc(n_lost)
            if self.record_deliveries:
                # Same "drop"/"defer" vocabulary as the legacy bus so the
                # forensics reorg audit explains vec forks too; ``cause``
                # carries which composed fault won.
                for r in np.nonzero(lost)[0]:
                    self.bus_log.record("drop", merge=ann.lamport,
                                        step=step, sender=ann.sender,
                                        receiver=int(r), cause=LOST_CHURN,
                                        **self._hdr_info(b))
            mask = mask & ~lost
        if not self.alive[ann.sender]:
            return
        # Precedence 2 — partition: cross-boundary deliveries defer to
        # the heal step (re-enqueued with the blocked sub-mask).
        for w in self.scenario.partitions:
            if not w.active(step):
                continue
            groups = w.groups_vec(self.n_nodes)
            blocked = mask & (groups != groups[ann.sender])
            n_block = int(blocked.sum())
            if n_block:
                counter("sim_messages_partition_deferred_total",
                        help="deliveries deferred to the partition "
                             "heal").inc(n_block)
                if self.record_deliveries:
                    for r in np.nonzero(blocked)[0]:
                        self.bus_log.record(
                            "defer", merge=ann.lamport, step=step,
                            sender=ann.sender, receiver=int(r),
                            cause=DEFER_PARTITION,
                            until_step=w.until, **self._hdr_info(b))
                self._buckets.setdefault(w.until, []).append(
                    dataclasses.replace(ann, mask=blocked))
                mask = mask & ~blocked
        # Precedence 3 — seeded drop (faulted horizon only; margin
        # steps reconcile fault-free).
        if self.scenario.drop_rate_pct and self.fault_phase:
            u = self.rng.vector("drop", step, ann.seq, self.n_nodes)
            dropped = mask & (u * 100 < self.scenario.drop_rate_pct)
            n_drop = int(dropped.sum())
            if n_drop:
                counter("sim_messages_dropped_total",
                        help="deliveries lost to the drop schedule"
                        ).inc(n_drop)
                if self.record_deliveries:
                    for r in np.nonzero(dropped)[0]:
                        self.bus_log.record("drop", merge=ann.lamport,
                                            step=step, sender=ann.sender,
                                            receiver=int(r),
                                            cause=LOST_DROP,
                                            **self._hdr_info(b))
                mask = mask & ~dropped
        # Adversary interception (eclipse monopolizes a victim's peers).
        for strat in self.strategies:
            mask = strat.filter_delivery(self, step, ann.sender, b, mask)
        if not mask.any():
            return
        self._consume(ann, b, mask)

    def _consume(self, ann: _Announce, b: LightBlock,
                 mask: np.ndarray) -> None:
        """Applies one announcement to its surviving receivers: batched
        tip-extension appends, then grouped fork syncs."""
        step = self.step_count
        n_recv = int(mask.sum())
        self._deliveries += n_recv
        counter("sim_messages_delivered_total",
                help="announcements delivered to a peer").inc(n_recv)
        append = mask & (self.tips == b.prev_idx)
        if append.any():
            idx = np.nonzero(append)[0]
            self.tips[idx] = b.idx
            self.heights[idx] = b.height
            if self.record_deliveries:
                for r in idx:
                    self.log(int(r)).record(
                        "deliver", merge=ann.lamport, step=step,
                        sender=ann.sender, result="appended",
                        **self._hdr_info(b))
        # Keep-first + the live-height sync gate: only receivers whose
        # chain is strictly shorter than the SENDER's current chain can
        # win an adoption (identical to SimNode.receive).
        sender_tip = int(self.tips[ann.sender])
        sender_h = int(self.heights[ann.sender])
        sync = mask & ~append & (self.heights < sender_h)
        if not sync.any():
            return
        # Group the syncing receivers by their current tip: one
        # validation + ancestor walk per distinct fork, applied to the
        # whole group vectorized.
        sync_idx = np.nonzero(sync)[0]
        for tip in np.unique(self.tips[sync_idx]):
            members = sync_idx[self.tips[sync_idx] == tip]
            self._sync_group(ann, [int(m) for m in members], int(tip),
                             sender_tip, sender_h)

    def _sync_group(self, ann: _Announce, members: list[int],
                    tip_idx: int, sender_tip: int, sender_h: int) -> None:
        """The O(suffix) sync for every member sharing ``tip_idx``."""
        step = self.step_count
        anchor_h = self.common_ancestor_height(tip_idx, sender_tip)
        suffix = self.chain_suffix(sender_tip, anchor_h)
        # The anchor block from the RECEIVER's side of the fork (the
        # locator guarantee): linkage is judged against what the
        # receiver already holds, never against the sender's claims.
        anchor = self.blocks[tip_idx]
        while anchor.height > anchor_h:
            anchor = self.blocks[anchor.prev_idx]
        reason = self.validate_suffix(anchor.key, suffix)
        if reason is not None:
            for m in members:
                self.reject_sync(m, ann.sender, len(suffix), reason)
            return
        old_h = int(self.blocks[tip_idx].height)
        rolled_back = old_h - anchor_h
        adopted = sender_h - anchor_h
        old_tip_key = self.blocks[tip_idx].key
        # The rolled-back hash list is O(depth) and duplicated per
        # member: priced into small-world dumps only (the forensics
        # audit degrades gracefully without it).
        extra = ({"rolled_back_hashes":
                  [blk.key for blk in self.chain_suffix(tip_idx,
                                                        anchor_h)]}
                 if self.record_deliveries else {})
        arr = np.array(members, dtype=np.int64)
        self.tips[arr] = sender_tip
        self.heights[arr] = sender_h
        for m in members:
            # ``peer`` (who we adopted from) is what lets the forensics
            # flood audit prove its chains-untouched invariant non-
            # vacuously: an adopt naming a flooder is a breach.
            self.log(m).record("adopt", merge=ann.lamport, step=step,
                               peer=ann.sender, old_tip=old_tip_key,
                               new_tip=self.blocks[sender_tip].key,
                               height=sender_h, anchor=anchor_h,
                               adopted=adopted, rolled_back=rolled_back,
                               **extra)
        if rolled_back:
            self.reorgs[arr] += 1
            self.reorged_away[arr] += rolled_back
            counter("sim_reorgs_total",
                    help="chain reorganizations across all groups"
                    ).inc(len(members))
            histogram("sim_reorg_depth",
                      help="blocks rolled back per reorg"
                      ).observe(rolled_back)

    # ---- churn -----------------------------------------------------------

    def _apply_churn(self) -> None:
        # PR 5 fault-plan integration: an armed plan's "sim.churn" site
        # is polled once per step (unarmed cost: one None check). A
        # fired fault crash-restarts a seeded-chosen live node — fault
        # plans compose with the scenario's own churn schedule, and the
        # crash is causally recorded like any scheduled one.
        fault = injection.check("sim.churn", step=self.step_count)
        if fault is not None:
            live = np.nonzero(self.alive)[0]
            if live.size:
                node = int(live[self.rng.draw(
                    "churn", self.step_count, 0xFA, mod=live.size)])
                down = 5 + self.rng.draw("churn", self.step_count, 0xFB,
                                         mod=max(2, self.scenario.steps
                                                 // 10))
                self.alive[node] = False
                up = self.step_count + down
                if up < self.scenario.steps:
                    self._churn_by_step.setdefault(up, []).append(
                        ChurnEvent(step=up, node=node, kind="join"))
                counter("sim_churn_events_total",
                        help="node membership changes "
                             "(crash/leave/join)", kind="crash").inc()
                self.bus_log.record("churn", step=self.step_count,
                                    node=node, action="crash",
                                    injected=True, fault=fault.kind,
                                    height=int(self.heights[node]))
        for e in self._churn_by_step.get(self.step_count, ()):
            was_alive = bool(self.alive[e.node])
            if e.kind in ("crash", "leave"):
                if not was_alive:
                    continue
                self.alive[e.node] = False
            else:                       # join / crash-restart
                if was_alive:
                    continue
                self.alive[e.node] = True
            counter("sim_churn_events_total",
                    help="node membership changes (crash/leave/join)",
                    kind=e.kind).inc()
            self.bus_log.record("churn", step=self.step_count,
                                node=e.node, action=e.kind,
                                height=int(self.heights[e.node]))

    # ---- mining ----------------------------------------------------------

    def _mine(self) -> None:
        # Per-node bits for the NEXT block under the retarget schedule,
        # then the lottery: P(find) = hashes * hashrate / 2^bits.
        next_h = self.heights + 1
        s = self.scenario
        if self.rule.interval:
            bits = (s.difficulty_bits
                    + self.rule.step_bits * (next_h // self.rule.interval))
            cap = max(self.rule.max_bits or 255, s.difficulty_bits)
            bits = np.minimum(bits, cap)
        else:
            bits = np.full(self.n_nodes, s.difficulty_bits, dtype=np.int64)
        p = (s.hashes_per_step * self.hashrate
             / np.exp2(bits.astype(np.float64)))
        u = self.rng.vector("mine", self.step_count, 0, self.n_nodes)
        winners = np.nonzero((u < p) & self.alive)[0]
        for w in winners:
            w = int(w)
            b = self.new_block(int(self.tips[w]), w, self.step_count)
            self.tips[w] = b.idx
            self.heights[w] = b.height
            self.blocks_mined[w] += 1
            counter("sim_vec_blocks_mined_total",
                    help="blocks found by the vectorized mining lottery"
                    ).inc()
            self.log(w).record("mine", step=self.step_count,
                               **self._hdr_info(b))
            publish = True
            for strat in self.strategies:
                publish = strat.on_mined(self, self.step_count, w, b) \
                    and publish
            if publish:
                self.broadcast(w, b.idx)

    # ---- the step loop ---------------------------------------------------

    def step(self) -> None:
        self._apply_churn()
        for strat in self.strategies:
            strat.on_step_begin(self, self.step_count)
        self._deliver_due()
        self._mine()
        for strat in self.strategies:
            strat.on_step_end(self, self.step_count)
        self.step_count += 1
        heartbeat("sim_heartbeat").set(self.step_count)
        self._mirror_gauges()

    def _mirror_gauges(self) -> None:
        live = self.alive.sum()
        gauge("sim_vec_live_nodes",
              help="nodes currently up in the vectorized sim"
              ).set(int(live))
        gauge("sim_vec_height_max",
              help="highest chain height across live nodes").set(
            int(self.heights[self.alive].max()) if live else 0)
        gauge("sim_vec_tips_distinct",
              help="distinct tips across live nodes (1 = converged)").set(
            int(np.unique(self.tips[self.alive]).size) if live else 0)
        gauge("sim_eclipse_victims",
              help="nodes whose peer set is currently monopolized by "
                   "an eclipse attacker").set(
            sum(s.eclipsing() for s in self.strategies))

    def run(self) -> dict:
        for _ in range(self.scenario.steps):
            self.step()
        # Converge margin: fault-free reconciliation (the legacy
        # "partition heals, then the network must converge" epilogue).
        # Mining continues — an equal-height fork at cutoff can only be
        # broken by the next block — but drops and attacks are over:
        # adversaries are told the horizon ended (a selfish miner must
        # release-or-abandon its private fork).
        self.fault_phase = False
        for strat in self.strategies:
            strat.on_horizon_end(self, self.step_count)
        for _ in range(self.scenario.converge_margin):
            if not self._buckets and self.converged():
                break
            self.step()
            self._drain_steps += 1
        # Final drain: deliver everything still in flight (latency
        # tails, partition deferrals), no further mining. Bounded:
        # every re-enqueue targets a finite step.
        while self._buckets:
            self._drain_steps += 1
            # Monotonic: the logical clock never rewinds — _deliver_due
            # pops every bucket at or before it.
            self.step_count = max(self.step_count, min(self._buckets))
            self._deliver_due()
        return self.summary()

    # ---- reporting -------------------------------------------------------

    def converged(self) -> bool:
        if not self.alive.any():
            return False
        return np.unique(self.tips[self.alive]).size == 1

    def canonical_tip(self) -> LightBlock:
        live = np.nonzero(self.alive)[0]
        if not live.size:
            # Everyone down at the end: judge from the last known tips
            # rather than crash the summary of an otherwise-clean run.
            live = np.arange(self.n_nodes)
        best = max(live, key=lambda i: (self.heights[i], -i))
        return self.blocks[int(self.tips[int(best)])]

    def chain_miners(self) -> dict[int, int]:
        """miner id -> blocks on the CANONICAL chain (revenue accounting
        for the selfish-mining audit)."""
        out: dict[int, int] = {}
        b = self.canonical_tip()
        while b.height > 0:
            out[b.miner] = out.get(b.miner, 0) + 1
            b = self.blocks[b.prev_idx]
        return out

    def summary(self) -> dict:
        live = self.alive
        tip = self.canonical_tip()
        return {
            "event": "sim_done",
            "engine": "vec",
            "converged": self.converged(),
            "steps": self.scenario.steps,
            "drain_steps": self._drain_steps,
            "n_nodes": self.n_nodes,
            "live_nodes": int(live.sum()),
            "blocks_total": len(self.blocks) - 1,
            "canonical_height": int(tip.height),
            "canonical_tip": tip.key,
            "final_bits": self.rule.expected_bits(
                self.scenario.difficulty_bits, int(tip.height) + 1),
            "height_min": int(self.heights[live].min()) if live.any()
            else 0,
            "height_max": int(self.heights[live].max()) if live.any()
            else 0,
            "deliveries": self._deliveries,
            "sync_rejections": self._sync_rejections,
            "reorgs": int(self.reorgs.sum()),
            "strategies": {s.name: s.summary() for s in self.strategies},
        }

    # ---- causal export ---------------------------------------------------

    def causal_logs(self) -> list:
        return ([self._logs[k] for k in sorted(self._logs)]
                + [self.bus_log])

    def dump_causal(self, path, meta: dict | None = None):
        base = {"engine": "vec", "steps": self.step_count,
                "converged": self.converged(),
                "n_nodes": self.n_nodes,
                "scenario": self.scenario.to_dict()}
        base.update(meta or {})
        return dump_causal_logs(self.causal_logs(), path, meta=base)


def run_scenario(scenario: Scenario,
                 on_network=None) -> tuple[VecNetwork, dict]:
    """Builds and runs the engine; ``on_network`` (like
    ``run_adversarial``'s hook) sees the engine before the run so a
    failing run's causal logs are still dumpable."""
    net = VecNetwork(scenario)
    if on_network is not None:
        on_network(net)
    return net, net.run()
