"""Configuration for mining runs.

One dataclass + the five BASELINE.json eval configs as named presets
(SURVEY.md §5 "Config/flag system").
"""
from __future__ import annotations

import dataclasses


class ConfigError(ValueError):
    """Invalid configuration or topology (bad kernel/batch, oversubscribed
    mesh, corrupt checkpoint, ...). The CLI converts exactly this class to
    a clean JSON error line; other exceptions keep their tracebacks.
    Subclasses ValueError so pre-existing `except ValueError` sites hold."""


def extend_payload(data: bytes, extra_nonce: int) -> bytes:
    """THE nonce-exhaustion rollover rule, shared by every mining driver.

    When the full 2^32 nonce space holds no qualifying hash for a candidate
    (SURVEY.md §0.2 #2 at difficulty ≳ 34), the search rolls over to a
    fresh space by deterministically varying the payload — new payload ⇒
    new data_hash ⇒ a genuinely independent search space. The rule is
    byte-level and backend-independent so CPU, single-chip TPU, and the
    fused mesh loop produce identical chains across a rollover:

        extra_nonce == 0  ->  data unchanged (the common path; existing
                              chains and pinned tips are unaffected)
        extra_nonce == k  ->  data + b":xk"

    Drivers try extra_nonce = 0, 1, 2, ... in order and accept the lowest
    qualifying nonce of the FIRST space that holds one, which keeps the
    winner a pure function of (tip, payload, difficulty).
    """
    if extra_nonce == 0:
        return data
    return data + b":x%d" % extra_nonce


# Rollover liveness bound: after this many consecutive empty 2^32 spaces the
# drivers raise instead of looping forever. Only an unsatisfiably high
# difficulty (≳ 48 bits: P(space empty) ≈ exp(-2^(32-d)), so ~2^(d-32)
# expected spaces) can hit it — that is a misconfiguration, and a loud error
# beats an infinite silent sweep.
MAX_EXTRA_NONCE = 1 << 16


@dataclasses.dataclass(frozen=True)
class MinerConfig:
    difficulty_bits: int = 16
    n_blocks: int = 10
    batch_pow2: int | str = 20    # log2(per-device nonces per sweep round),
    #                               or "auto" to track the difficulty
    n_miners: int = 1             # mesh axis size (devices or CPU ranks)
    backend: str = "tpu"          # miner_backend plugin: {"cpu", "tpu"}
    kernel: str = "auto"          # tpu sweep kernel: {"auto", "jnp", "pallas"}
    seed: int = 0                 # reserved (search is deterministic)
    data_prefix: str = "block"    # payload = f"{data_prefix}:{height}"

    def __post_init__(self):
        if self.batch_pow2 != "auto" and not (
                isinstance(self.batch_pow2, int)
                and 0 <= self.batch_pow2 <= 32):
            raise ConfigError(
                f"batch_pow2 must be an int in [0, 32] or 'auto', "
                f"got {self.batch_pow2!r}")

    @property
    def effective_batch_pow2(self) -> int:
        """batch_pow2 with "auto" resolved: ≈ one expected winner per
        round (batch ≈ 2^difficulty), clamped to [13, 24] — 2^13 is one
        Pallas tile (the smallest flagship-kernel batch), 2^24 bounds the
        early-exit overshoot. The difficulty-scaling curve (BASELINE.md)
        showed the fixed per-round cost dominating when a fixed 2^24
        batch vastly oversizes low difficulties (47.5 MH/s effective at
        diff 16 vs ~1000 at 24); tracking the difficulty right-sizes the
        round without changing any tip (round size never affects the
        lowest-qualifying-nonce winner)."""
        if self.batch_pow2 == "auto":
            return min(max(self.difficulty_bits, 13), 24)
        return self.batch_pow2

    @property
    def batch_size(self) -> int:
        return 1 << self.effective_batch_pow2

    def payload(self, height: int, extra_nonce: int = 0) -> bytes:
        return extend_payload(f"{self.data_prefix}:{height}".encode(),
                              extra_nonce)


# The five BASELINE.json eval configs (SURVEY.md §6 measurement matrix).
PRESETS: dict[str, MinerConfig] = {
    # 1: single-rank CPU mine: 10 blocks, difficulty=16, fixed genesis
    "cpu-single": MinerConfig(difficulty_bits=16, n_blocks=10, n_miners=1,
                              backend="cpu"),
    # 2: 4 CPU ranks, difficulty=20, first-finder broadcast
    "cpu-np4": MinerConfig(difficulty_bits=20, n_blocks=10, n_miners=4,
                           backend="cpu"),
    # 3: TPU single-chip Pallas SHA-256, nonce-batch=2^20, difficulty=20
    "tpu-single": MinerConfig(difficulty_bits=20, n_blocks=10, batch_pow2=20,
                              n_miners=1, backend="tpu", kernel="pallas"),
    # 4: v5e-8 data-parallel nonce-space split, difficulty=24
    "tpu-mesh8": MinerConfig(difficulty_bits=24, n_blocks=1000, batch_pow2=20,
                             n_miners=8, backend="tpu"),
    # 5: adversarial: 2 competing miner groups + longest-chain reorg
    "adversarial": MinerConfig(difficulty_bits=16, n_blocks=20, n_miners=2,
                               backend="tpu"),
}
