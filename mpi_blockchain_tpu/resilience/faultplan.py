"""FaultPlan: the seeded, byte-reproducible fault-injection spec.

A plan is a list of ``FaultSpec`` entries — which injection *site*,
which 0-based *call* index at that site, how many consecutive calls
(*times*; -1 = every call from there on), and which fault *kind*:

=========  ===========================================================
``raise``    the call raises ``FaultInjected`` before doing any work
``hang``     the call wedges for ``seconds`` (heartbeats go stale, the
             /healthz watchdog sees it), then raises ``FaultTimeout``
             — the deterministic stand-in for a watchdogged hang
``corrupt``  the call completes but its RESULT is damaged (a flipped
             header byte on the bus, a wrong search digest, a bitrot
             byte in a written checkpoint)
``partial``  the call completes but its result is truncated or lost
             (a torn checkpoint write, a suppressed search winner, a
             vanished bus delivery)
=========  ===========================================================

Determinism contract: a plan is a pure value (JSON round-trippable),
``FaultPlan.from_seed`` derives one from a seed via crc32 with no
global RNG, and the injection counters reset at arm time — so a
fixed-seed faulted run produces byte-identical causal dumps across
runs (the chaos-smoke gate asserts this).
"""
from __future__ import annotations

import dataclasses
import json
import pathlib
import struct
import zlib

from . import FaultPlanError

#: Every hook site threaded through the stack (docs/resilience.md).
SITES = (
    "backend.tpu.dispatch",   # TpuBackend.search, before device dispatch
    "backend.cpu.search",     # CpuBackend.search, before the C++ sweep
    "sim.deliver",            # Network.deliver_due, per delivery attempt
    "native.load",            # core/build.py, before make/ctypes load
    "checkpoint.write",       # utils/checkpoint.save_chain
    "checkpoint.read",        # utils/checkpoint.load_chain
    "distributed.init",       # parallel/distributed.init_distributed
    "sim.churn",              # sim/vecnet.VecNetwork, once per step:
    #                           a fired fault crash-restarts a
    #                           seeded-chosen live node (corrupt/partial
    #                           damage kinds; raise/hang crash the step)
    "parallel.collective",    # resilience/elastic.guarded_collective,
    #                           per guarded rendezvous: raise/hang both
    #                           surface as RankLossSuspected — the
    #                           deterministic stand-in for a peer dying
    #                           mid-psum/pmin (corrupt/partial behave
    #                           like raise: a damaged collective result
    #                           is indistinguishable from a lost peer)
    "mesh.rank_death",        # resilience/elastic.ElasticWorld.step,
    #                           once per block: a fired damage fault
    #                           hard-exits the seeded-chosen victim rank
    #                           (os._exit — no final shard, like
    #                           SIGKILL) while every survivor evicts it
    #                           at the same step (raise/hang crash the
    #                           step as usual)
    "service.submit",         # service/frontdoor.ServiceState.submit,
    #                           per admission attempt: raise/hang are
    #                           retried under the service budget and
    #                           shed TYPED on exhaustion (hang is a
    #                           real-seconds wedge bounded by
    #                           FaultTimeout — the door answers late,
    #                           never never); corrupt rejects the tx as
    #                           integrity-damaged before it can enter
    #                           the mempool; partial admits the tx but
    #                           loses the receipt (client recovers via
    #                           tx_status — the accepted-then-lost
    #                           conservation check)
    "service.rebuild",        # service/frontdoor.TemplateFeed.rebuild,
    #                           per template rebuild: raise/hang are
    #                           retried and on exhaustion the PREVIOUS
    #                           template keeps serving (degrade, never
    #                           drop); corrupt damages the rebuilt
    #                           template so the block-boundary
    #                           re-validation discards it like a stale
    #                           speculation; partial rebuilds from only
    #                           a prefix of the eligible txs (the rest
    #                           stay pending — delayed, never lost)
)

KINDS = ("raise", "hang", "corrupt", "partial")

VERSION = 1


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One injected fault: site + call window + kind."""
    site: str
    kind: str
    call: int = 0          # first 0-based call index at the site that faults
    times: int = 1         # consecutive faulted calls; -1 = forever
    seconds: float = 0.05  # hang: simulated wedge before FaultTimeout
    message: str = ""

    def __post_init__(self):
        if self.site not in SITES:
            raise FaultPlanError(f"unknown fault site {self.site!r}; "
                                 f"known: {list(SITES)}")
        if self.kind not in KINDS:
            raise FaultPlanError(f"unknown fault kind {self.kind!r}; "
                                 f"known: {list(KINDS)}")
        if self.call < 0:
            raise FaultPlanError(f"fault call index must be >= 0, "
                                 f"got {self.call}")
        if self.times < -1 or self.times == 0:
            raise FaultPlanError(f"fault times must be >= 1 or -1 "
                                 f"(forever), got {self.times}")
        if not self.seconds >= 0:   # also rejects NaN
            raise FaultPlanError(f"fault seconds must be >= 0, "
                                 f"got {self.seconds}")

    def matches(self, index: int) -> bool:
        """Does this fault fire on the index-th call at its site?"""
        if index < self.call:
            return False
        return self.times < 0 or index < self.call + self.times


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """An ordered set of FaultSpecs + the seed that labels the scenario.

    ``strict`` plans additionally demand every fault actually fires:
    a run that ends with unfired faults is a fault-plan exhaustion
    failure (CLI rc 3) — the injected scenario was not exercised.
    """
    faults: tuple[FaultSpec, ...] = ()
    seed: int = 0
    strict: bool = False

    # ---- construction ----------------------------------------------------

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        if not isinstance(d, dict):
            raise FaultPlanError(f"fault plan must be a JSON object, "
                                 f"got {type(d).__name__}")
        version = d.get("version", VERSION)
        if version != VERSION:
            raise FaultPlanError(f"unsupported fault-plan version "
                                 f"{version!r} (have {VERSION})")
        raw = d.get("faults", [])
        if not isinstance(raw, list):
            raise FaultPlanError("fault plan 'faults' must be a list")
        faults = []
        known = {f.name for f in dataclasses.fields(FaultSpec)}
        for i, entry in enumerate(raw):
            if not isinstance(entry, dict):
                raise FaultPlanError(f"fault #{i} must be an object")
            unknown = sorted(set(entry) - known)
            if unknown:
                raise FaultPlanError(f"fault #{i} has unknown field(s) "
                                     f"{unknown}; known: {sorted(known)}")
            try:
                faults.append(FaultSpec(**entry))
            except TypeError as e:
                raise FaultPlanError(f"fault #{i}: {e}") from e
        return cls(faults=tuple(faults), seed=int(d.get("seed", 0)),
                   strict=bool(d.get("strict", False)))

    @classmethod
    def load(cls, path: str | pathlib.Path) -> "FaultPlan":
        path = pathlib.Path(path)
        try:
            payload = json.loads(path.read_text())
        except OSError as e:
            raise FaultPlanError(f"cannot read fault plan {path}: "
                                 f"{e}") from e
        except json.JSONDecodeError as e:
            raise FaultPlanError(f"fault plan {path} is not valid JSON: "
                                 f"{e}") from e
        return cls.from_dict(payload)

    @classmethod
    def from_seed(cls, seed: int, n_faults: int = 3,
                  sites: tuple[str, ...] = SITES,
                  strict: bool = False) -> "FaultPlan":
        """Derives a pseudo-random plan from a seed — crc32-keyed like
        ``simulation.seeded_drop``, so the same seed always yields the
        same plan with no global RNG state (the fuzz-harness input)."""
        if not sites:
            raise FaultPlanError("from_seed needs at least one site")
        bad = [s for s in sites if s not in SITES]
        if bad:
            raise FaultPlanError(f"unknown fault site(s) {bad}; "
                                 f"known: {list(SITES)}")

        def draw(i: int, tag: int, mod: int) -> int:
            key = struct.pack("<IIi", tag, i, seed)
            return zlib.crc32(key) % mod

        faults = []
        for i in range(max(1, n_faults)):
            kind = KINDS[draw(i, 1, len(KINDS))]
            faults.append(FaultSpec(
                site=sites[draw(i, 0, len(sites))],
                kind=kind,
                call=draw(i, 2, 8),
                times=1 + draw(i, 3, 3),
                # Hangs stay short: the fuzz harness's liveness bound is
                # "no hang outlasts its watchdog", not wall-clock realism.
                seconds=0.01 + draw(i, 4, 5) / 100.0))
        return cls(faults=tuple(faults), seed=seed, strict=strict)

    @classmethod
    def parse_arg(cls, value: str) -> "FaultPlan":
        """The CLI form: ``seed:N`` derives from a seed, anything else
        is a JSON plan path."""
        if value.startswith("seed:"):
            raw = value[len("seed:"):]
            try:
                return cls.from_seed(int(raw))
            except ValueError:
                raise FaultPlanError(
                    f"--fault-plan seed:N needs an integer seed, "
                    f"got {raw!r}") from None
        return cls.load(value)

    # ---- queries ---------------------------------------------------------

    def to_dict(self) -> dict:
        return {"version": VERSION, "seed": self.seed,
                "strict": self.strict,
                "faults": [dataclasses.asdict(f) for f in self.faults]}

    def match(self, site: str, index: int) -> FaultSpec | None:
        """The first fault that fires on the index-th call at ``site``."""
        for f in self.faults:
            if f.site == site and f.matches(index):
                return f
        return None

    def match_all(self, site: str, index: int
                  ) -> list[tuple[int, FaultSpec]]:
        """EVERY (plan index, fault) whose window covers this call. The
        injector applies the first but credits all as fired — a spec
        shadowed by an earlier overlapping window (e.g. a times=-1
        fault at the same site) must not make a strict plan
        unexhaustible."""
        return [(i, f) for i, f in enumerate(self.faults)
                if f.site == site and f.matches(index)]
