"""CLI: ``python -m mpi_blockchain_tpu.resilience {smoke,plan}``.

``smoke`` is the ``make chaos-smoke`` gate — the acceptance proof of
ISSUE 5, three phases, all against the REAL CLI surface:

1. **Determinism** — one fixed fault plan drives two identical faulted
   sims; their causal event dumps must be byte-identical.
2. **Kill + resume** — a real subprocess miner checkpointing every
   block is SIGKILL'd mid-run; resume must verify, extend, and (after
   an additional deliberate tear) truncate to the last valid block.
3. **Degradation** — a fault plan kills every TPU dispatch; the ladder
   must walk device → jnp → native CPU and still converge with rc 0 on
   the byte-identical chain the CPU oracle mines.

``plan --seed N`` prints the seed-derived plan ``--fault-plan seed:N``
would arm (the fuzz harness's input, docs/resilience.md).
"""
from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import pathlib
import signal
import subprocess
import sys
import tempfile


def _run_cli(argv: list[str]) -> tuple[int, dict]:
    """Runs the real CLI in-process; returns (rc, last JSON line)."""
    from ..cli import main as cli_main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(argv)
    lines = [ln for ln in buf.getvalue().splitlines() if ln.strip()]
    return rc, (json.loads(lines[-1]) if lines else {})


def smoke_determinism(tmp: pathlib.Path) -> str:
    plan = {"version": 1, "seed": 5, "faults": [
        {"site": "sim.deliver", "kind": "corrupt", "call": 2, "times": 2},
        {"site": "sim.deliver", "kind": "partial", "call": 7, "times": 3},
        {"site": "backend.cpu.search", "kind": "partial", "call": 5,
         "times": 2},
    ]}
    plan_path = tmp / "plan.json"
    plan_path.write_text(json.dumps(plan))
    for i in range(2):
        rc, out = _run_cli(["sim", "--blocks", "4", "--partition-steps",
                            "10", "--drop-rate", "10", "--seed", "3",
                            "--fault-plan", str(plan_path),
                            "--events-dump", str(tmp / f"dump{i}.json")])
        assert rc == 0, f"faulted sim run {i} rc={rc}: {out}"
        assert out.get("converged") is True, out
    b0 = (tmp / "dump0.json").read_bytes()
    b1 = (tmp / "dump1.json").read_bytes()
    assert b0 == b1, (f"fixed-seed fault plan produced DIVERGING causal "
                      f"dumps ({len(b0)} vs {len(b1)} bytes)")
    return (f"determinism ok ({len(plan['faults'])} faults, "
            f"{len(b0)}-byte causal dump byte-identical across 2 runs)")


def smoke_kill_resume(tmp: pathlib.Path) -> str:
    ck = tmp / "ck.bin"
    repo_root = pathlib.Path(__file__).resolve().parents[2]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in (str(repo_root),
                               os.environ.get("PYTHONPATH")) if p))
    # --checkpoint-every 1 fsyncs per block: plenty of runway to SIGKILL
    # long before the 4000-block target.
    proc = subprocess.Popen(
        [sys.executable, "-m", "mpi_blockchain_tpu", "mine",
         "--difficulty", "10", "--blocks", "4000", "--backend", "cpu",
         "--checkpoint", str(ck), "--checkpoint-every", "1", "--verbose"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=str(tmp))
    mined = 0
    for line in proc.stdout:
        if '"block_mined"' in line:
            mined += 1
            if mined >= 3:
                break
    os.kill(proc.pid, signal.SIGKILL)
    proc.stdout.close()
    proc.wait()
    assert mined >= 3, "miner died before mining 3 blocks"
    sidecar = json.loads(ck.with_suffix(".bin.json").read_text())
    height = sidecar["height"]
    # The SIGKILL can land between a block's log line and its save:
    # --checkpoint-every 1 guarantees at most ONE block of loss.
    assert height >= mined - 1 >= 2, (mined, sidecar)
    # (a) Straight resume: the atomic writer guarantees the published
    # checkpoint is whole despite the SIGKILL; mine 2 more and verify.
    out_path = tmp / "resumed.bin"
    rc, out = _run_cli(["mine", "--difficulty", "10", "--blocks",
                        str(height + 2), "--backend", "cpu",
                        "--resume", str(ck), "--out", str(out_path)])
    assert rc == 0 and out["height"] == height + 2, (rc, out)
    rc, verdict = _run_cli(["verify", "--chain", str(out_path),
                            "--difficulty", "10"])
    assert rc == 0 and verdict["valid"] is True, (rc, verdict)
    # (b) Torn tail: rip the trailer + most of the last header off, as a
    # non-atomic writer's crash would; resume must truncate to the last
    # valid block and still reach the target.
    blob = ck.read_bytes()
    ck.write_bytes(blob[:-120])
    rc, out = _run_cli(["mine", "--difficulty", "10", "--blocks",
                        str(height + 1), "--backend", "cpu",
                        "--resume", str(ck)])
    assert rc == 0 and out["height"] == height + 1, (rc, out)
    from ..telemetry.events import recent_events
    truncs = recent_events(event="checkpoint_truncated")
    assert truncs and truncs[-1]["height"] == height - 1, truncs
    return (f"kill+resume ok (SIGKILL at >= 3 blocks, checkpoint height "
            f"{height}, resumed to {height + 2} and verified; torn tail "
            f"truncated to {height - 1} and re-mined)")


def smoke_degradation(tmp: pathlib.Path) -> str:
    plan_path = tmp / "kill_tpu.json"
    plan_path.write_text(json.dumps({"version": 1, "faults": [
        {"site": "backend.tpu.dispatch", "kind": "raise", "call": 0,
         "times": -1}]}))
    rc, out = _run_cli(["mine", "--difficulty", "8", "--blocks", "2",
                        "--backend", "tpu", "--kernel", "auto",
                        "--batch-pow2", "11",
                        "--fault-plan", str(plan_path)])
    assert rc == 0, f"degraded mine must still converge rc 0, got {rc}"
    assert out.get("degraded") is True and out["degraded_to"] == "cpu", out
    assert out["backend"] == "cpu", out
    rc, oracle = _run_cli(["mine", "--difficulty", "8", "--blocks", "2",
                           "--backend", "cpu"])
    assert rc == 0, oracle
    assert out["tip_hash"] == oracle["tip_hash"], (
        "degraded chain diverged from the cpu oracle chain")
    return ("degradation ok (dead TPU dispatch walked the ladder to cpu, "
            "rc 0, chain byte-identical to the cpu oracle)")


def cmd_smoke(args) -> int:
    with tempfile.TemporaryDirectory() as td:
        tmp = pathlib.Path(td)
        for phase in (smoke_determinism, smoke_kill_resume,
                      smoke_degradation):
            print(f"chaos-smoke: {phase(tmp)}", flush=True)
    return 0


def cmd_plan(args) -> int:
    from .faultplan import FaultPlan
    print(json.dumps(FaultPlan.from_seed(args.seed,
                                         n_faults=args.faults).to_dict(),
                     indent=2, sort_keys=True))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_blockchain_tpu.resilience",
        description="chaos gate: deterministic fault injection, "
                    "kill+resume recovery, degradation ladder")
    sub = parser.add_subparsers(dest="command", required=True)
    p_smoke = sub.add_parser("smoke", help="run the chaos-smoke gate "
                                           "(make chaos-smoke)")
    p_smoke.set_defaults(fn=cmd_smoke)
    p_plan = sub.add_parser("plan", help="print the plan --fault-plan "
                                         "seed:N would arm")
    p_plan.add_argument("--seed", type=int, default=0)
    p_plan.add_argument("--faults", type=int, default=3)
    p_plan.set_defaults(fn=cmd_plan)
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
