"""CLI: ``python -m mpi_blockchain_tpu.resilience {smoke,plan}``.

``smoke`` is the ``make chaos-smoke`` gate — the acceptance proof of
ISSUE 5, three phases, all against the REAL CLI surface:

1. **Determinism** — one fixed fault plan drives two identical faulted
   sims; their causal event dumps must be byte-identical.
2. **Kill + resume** — a real subprocess miner checkpointing every
   block is SIGKILL'd mid-run; resume must verify, extend, and (after
   an additional deliberate tear) truncate to the last valid block.
3. **Degradation** — a fault plan kills every TPU dispatch; the ladder
   must walk device → jnp → native CPU and still converge with rc 0 on
   the byte-identical chain the CPU oracle mines.

``plan --seed N`` prints the seed-derived plan ``--fault-plan seed:N``
would arm (the fuzz harness's input, docs/resilience.md).
"""
from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import pathlib
import signal
import subprocess
import sys
import tempfile


def _run_cli(argv: list[str]) -> tuple[int, dict]:
    """Runs the real CLI in-process; returns (rc, last JSON line)."""
    from ..cli import main as cli_main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(argv)
    lines = [ln for ln in buf.getvalue().splitlines() if ln.strip()]
    return rc, (json.loads(lines[-1]) if lines else {})


def smoke_determinism(tmp: pathlib.Path) -> str:
    plan = {"version": 1, "seed": 5, "faults": [
        {"site": "sim.deliver", "kind": "corrupt", "call": 2, "times": 2},
        {"site": "sim.deliver", "kind": "partial", "call": 7, "times": 3},
        {"site": "backend.cpu.search", "kind": "partial", "call": 5,
         "times": 2},
    ]}
    plan_path = tmp / "plan.json"
    plan_path.write_text(json.dumps(plan))
    for i in range(2):
        rc, out = _run_cli(["sim", "--blocks", "4", "--partition-steps",
                            "10", "--drop-rate", "10", "--seed", "3",
                            "--fault-plan", str(plan_path),
                            "--events-dump", str(tmp / f"dump{i}.json")])
        assert rc == 0, f"faulted sim run {i} rc={rc}: {out}"
        assert out.get("converged") is True, out
    b0 = (tmp / "dump0.json").read_bytes()
    b1 = (tmp / "dump1.json").read_bytes()
    assert b0 == b1, (f"fixed-seed fault plan produced DIVERGING causal "
                      f"dumps ({len(b0)} vs {len(b1)} bytes)")
    return (f"determinism ok ({len(plan['faults'])} faults, "
            f"{len(b0)}-byte causal dump byte-identical across 2 runs)")


def smoke_kill_resume(tmp: pathlib.Path) -> str:
    ck = tmp / "ck.bin"
    repo_root = pathlib.Path(__file__).resolve().parents[2]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in (str(repo_root),
                               os.environ.get("PYTHONPATH")) if p))
    # --checkpoint-every 1 fsyncs per block: plenty of runway to SIGKILL
    # long before the 4000-block target.
    proc = subprocess.Popen(
        [sys.executable, "-m", "mpi_blockchain_tpu", "mine",
         "--difficulty", "10", "--blocks", "4000", "--backend", "cpu",
         "--checkpoint", str(ck), "--checkpoint-every", "1", "--verbose"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=str(tmp))
    mined = 0
    for line in proc.stdout:
        if '"block_mined"' in line:
            mined += 1
            if mined >= 3:
                break
    os.kill(proc.pid, signal.SIGKILL)
    proc.stdout.close()
    proc.wait()
    assert mined >= 3, "miner died before mining 3 blocks"
    sidecar = json.loads(ck.with_suffix(".bin.json").read_text())
    height = sidecar["height"]
    # The SIGKILL can land between a block's log line and its save:
    # --checkpoint-every 1 guarantees at most ONE block of loss.
    assert height >= mined - 1 >= 2, (mined, sidecar)
    # (a) Straight resume: the atomic writer guarantees the published
    # checkpoint is whole despite the SIGKILL; mine 2 more and verify.
    out_path = tmp / "resumed.bin"
    rc, out = _run_cli(["mine", "--difficulty", "10", "--blocks",
                        str(height + 2), "--backend", "cpu",
                        "--resume", str(ck), "--out", str(out_path)])
    assert rc == 0 and out["height"] == height + 2, (rc, out)
    rc, verdict = _run_cli(["verify", "--chain", str(out_path),
                            "--difficulty", "10"])
    assert rc == 0 and verdict["valid"] is True, (rc, verdict)
    # (b) Torn tail: rip the trailer + most of the last header off, as a
    # non-atomic writer's crash would; resume must truncate to the last
    # valid block and still reach the target.
    blob = ck.read_bytes()
    ck.write_bytes(blob[:-120])
    rc, out = _run_cli(["mine", "--difficulty", "10", "--blocks",
                        str(height + 1), "--backend", "cpu",
                        "--resume", str(ck)])
    assert rc == 0 and out["height"] == height + 1, (rc, out)
    from ..telemetry.events import recent_events
    truncs = recent_events(event="checkpoint_truncated")
    assert truncs and truncs[-1]["height"] == height - 1, truncs
    return (f"kill+resume ok (SIGKILL at >= 3 blocks, checkpoint height "
            f"{height}, resumed to {height + 2} and verified; torn tail "
            f"truncated to {height - 1} and re-mined)")


def smoke_degradation(tmp: pathlib.Path) -> str:
    plan_path = tmp / "kill_tpu.json"
    plan_path.write_text(json.dumps({"version": 1, "faults": [
        {"site": "backend.tpu.dispatch", "kind": "raise", "call": 0,
         "times": -1}]}))
    rc, out = _run_cli(["mine", "--difficulty", "8", "--blocks", "2",
                        "--backend", "tpu", "--kernel", "auto",
                        "--batch-pow2", "11",
                        "--fault-plan", str(plan_path)])
    assert rc == 0, f"degraded mine must still converge rc 0, got {rc}"
    assert out.get("degraded") is True and out["degraded_to"] == "cpu", out
    assert out["backend"] == "cpu", out
    rc, oracle = _run_cli(["mine", "--difficulty", "8", "--blocks", "2",
                           "--backend", "cpu"])
    assert rc == 0, oracle
    assert out["tip_hash"] == oracle["tip_hash"], (
        "degraded chain diverged from the cpu oracle chain")
    return ("degradation ok (dead TPU dispatch walked the ladder to cpu, "
            "rc 0, chain byte-identical to the cpu oracle)")


def cmd_smoke(args) -> int:
    with tempfile.TemporaryDirectory() as td:
        tmp = pathlib.Path(td)
        for phase in (smoke_determinism, smoke_kill_resume,
                      smoke_degradation):
            print(f"chaos-smoke: {phase(tmp)}", flush=True)
    return 0


# ---- the elastic-smoke gate (make elastic-smoke) ---------------------------


def _spawn_elastic_rank(rank: int, world: int, tmp: pathlib.Path,
                        argv_extra: list[str],
                        env_extra: dict | None = None):
    repo_root = pathlib.Path(__file__).resolve().parents[2]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in (str(repo_root),
                               os.environ.get("PYTHONPATH")) if p),
               **(env_extra or {}))
    argv = [sys.executable, "-m", "mpi_blockchain_tpu", "mine",
            "--backend", "cpu", "--elastic",
            "--process-id", str(rank), "--num-processes", str(world)]
    return subprocess.Popen(argv + argv_extra, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, env=env,
                            cwd=str(tmp))


def _last_json(out: str) -> dict:
    lines = [ln for ln in out.splitlines() if ln.strip()]
    return json.loads(lines[-1]) if lines else {}


def smoke_elastic_sigkill(tmp: pathlib.Path) -> str:
    """Phase 1: a 4-rank striped world; rank 2 is SIGKILL'd once its
    shard PROVES a miner heartbeat in flight. The survivors must evict
    it via meshwatch shard staleness (dead-shard — not a timeout
    guess), re-stripe over [0, 1, 3], finish rc 0, and rank 0's chain
    must pass the cpu oracle's full C++ PoW+linkage validation."""
    import signal
    import time

    from ..meshwatch.aggregate import read_shards
    from ..meshwatch.shard import shard_path
    from .. import core

    world, victim = 4, 2
    obs = tmp / "mesh_sigkill"
    chain = tmp / "elastic_chain.bin"
    # Self-calibrate the survivor workload to ~12 s of mining on THIS
    # machine, so the staleness eviction (a few seconds in) always lands
    # while survivors are still mining — CI hosts span >10x in hash
    # rate, and rank processes additionally share cores.
    t0 = time.perf_counter()
    _, probed = core.cpu_search(bytes(range(80)), 0, 1 << 20, 40)
    rate = probed / max(time.perf_counter() - t0, 1e-9)
    n_blocks = max(12, min(600, int(12.0 * rate / (1 << 18))))
    # Stall budget 2 s against a 0.2 s flush cadence: wide enough that a
    # LIVE survivor's flusher starved by CPU oversubscription (4 ranks
    # on a 2-core CI box) is never mistaken for the corpse — only the
    # SIGKILL'd rank, whose shard stops forever, ages past it. The
    # missing-rank grace is parked far beyond the run: every rank writes
    # a shard here, so a missing-eviction could only ever be a misfire.
    env = {"MPIBT_MESH_OBS_INTERVAL": "0.2", "MPIBT_MESH_STALL": "2.0",
           "MPIBT_ELASTIC_GRACE": "600"}
    survivors = {
        r: _spawn_elastic_rank(
            r, world, tmp,
            ["--difficulty", "18", "--blocks", str(n_blocks),
             "--mesh-obs", str(obs)]
            + (["--out", str(chain)] if r == 0 else []), env)
        for r in range(world) if r != victim}
    # The victim mines a much harder chain, so it is mid-sweep (stamping
    # a heartbeat per stripe window) when the signal lands.
    victim_proc = _spawn_elastic_rank(
        victim, world, tmp,
        ["--difficulty", "24", "--blocks", "1000",
         "--mesh-obs", str(obs)], env)
    try:
        deadline = time.monotonic() + 120
        vpath = shard_path(obs, victim)
        while time.monotonic() < deadline:
            shards = {s["rank"]: s for s in read_shards(obs)}
            beats = shards.get(victim, {}).get("heartbeats", {})
            if vpath.exists() and any("miner_heartbeat" in k
                                      for k in beats):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("victim never heartbeat")
        victim_proc.send_signal(signal.SIGKILL)
        victim_proc.wait(timeout=30)
        summaries = {}
        for r, p in survivors.items():
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, \
                f"survivor rank {r} rc={p.returncode}: {err[-800:]}"
            summaries[r] = _last_json(out)
    finally:
        for p in list(survivors.values()) + [victim_proc]:
            if p.poll() is None:
                p.kill()
                p.wait()
    for r, summary in summaries.items():
        mesh = summary.get("mesh") or {}
        assert mesh.get("live") == [0, 1, 3], (r, mesh)
        ev = {e["rank"]: e["reason"] for e in mesh.get("evicted", [])}
        assert ev.get(victim) == "dead-shard", (r, mesh)
    # The final chain verifies against the cpu oracle (full C++
    # re-validation of every block: PoW + linkage).
    assert core.Node(18, 0).load(chain.read_bytes()), \
        "survivor chain failed oracle validation"
    return (f"elastic sigkill ok (victim {victim} evicted via "
            f"dead-shard staleness by all survivors; {n_blocks} blocks "
            f"each; rank-0 chain oracle-valid)")


def smoke_elastic_determinism(tmp: pathlib.Path) -> str:
    """Phase 2: the seeded ``mesh.rank_death`` fault plan — the victim
    hard-exits (rc 137, no final shard, like SIGKILL) at a plan-chosen
    block step while every survivor evicts it at the SAME step; two
    same-seed runs must produce byte-identical causal dumps."""
    world = 4
    plan_path = tmp / "rank_death.json"
    plan_path.write_text(json.dumps({"version": 1, "seed": 9, "faults": [
        {"site": "mesh.rank_death", "kind": "partial", "call": 2}]}))
    runs: list[dict] = []
    for run in range(2):
        procs = {
            r: _spawn_elastic_rank(
                r, world, tmp,
                ["--difficulty", "12", "--blocks", "8",
                 "--batch-pow2", "12",
                 "--fault-plan", str(plan_path),
                 "--events-dump", str(tmp / f"run{run}_r{r}.json")])
            for r in range(world)}
        rcs, summaries = {}, {}
        try:
            for r, p in procs.items():
                out, err = p.communicate(timeout=240)
                rcs[r] = p.returncode
                summaries[r] = _last_json(out) if p.returncode == 0 else {}
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
                    p.wait()
        victims = [r for r, rc in rcs.items() if rc == 137]
        assert len(victims) == 1, f"run {run}: exit codes {rcs}"
        victim = victims[0]
        assert victim != 0, "the anchor rank must never be the victim"
        assert all(rc == 0 for r, rc in rcs.items() if r != victim), rcs
        for r, summary in summaries.items():
            if r == victim:
                continue
            mesh = summary.get("mesh") or {}
            ev = [(e["rank"], e["reason"], e["height"])
                  for e in mesh.get("evicted", [])]
            assert ev == [(victim, "rank_death", 3)], (r, mesh)
            assert victim not in mesh.get("live", []), (r, mesh)
        runs.append({"victim": victim})
    assert runs[0]["victim"] == runs[1]["victim"]
    victim = runs[0]["victim"]
    for r in range(world):
        d0, d1 = tmp / f"run0_r{r}.json", tmp / f"run1_r{r}.json"
        if r == victim:
            # os._exit skips the dump path — exactly like SIGKILL.
            assert not d0.exists() and not d1.exists(), r
            continue
        assert d0.read_bytes() == d1.read_bytes(), \
            f"rank {r}: same-seed mesh.rank_death dumps diverge"
    return (f"elastic determinism ok (seeded victim {victim} died at "
            f"step 3 in both runs; survivor causal dumps byte-identical)")


def cmd_elastic_smoke(args) -> int:
    with tempfile.TemporaryDirectory() as td:
        tmp = pathlib.Path(td)
        for phase in (smoke_elastic_sigkill, smoke_elastic_determinism):
            print(f"elastic-smoke: {phase(tmp)}", flush=True)
    return 0


def cmd_plan(args) -> int:
    from .faultplan import FaultPlan
    print(json.dumps(FaultPlan.from_seed(args.seed,
                                         n_faults=args.faults).to_dict(),
                     indent=2, sort_keys=True))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_blockchain_tpu.resilience",
        description="chaos gate: deterministic fault injection, "
                    "kill+resume recovery, degradation ladder")
    sub = parser.add_subparsers(dest="command", required=True)
    p_smoke = sub.add_parser("smoke", help="run the chaos-smoke gate "
                                           "(make chaos-smoke)")
    p_smoke.set_defaults(fn=cmd_smoke)
    p_elastic = sub.add_parser(
        "elastic-smoke",
        help="run the elastic-mesh gate (make elastic-smoke): 4-rank "
             "striped world, one rank SIGKILL'd -> staleness eviction + "
             "re-stripe + rc 0, plus byte-identical same-seed "
             "mesh.rank_death runs")
    p_elastic.set_defaults(fn=cmd_elastic_smoke)
    p_plan = sub.add_parser("plan", help="print the plan --fault-plan "
                                         "seed:N would arm")
    p_plan.add_argument("--seed", type=int, default=0)
    p_plan.add_argument("--faults", type=int, default=3)
    p_plan.set_defaults(fn=cmd_plan)
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
