"""The process-global fault-injection arming point.

One armed ``FaultPlan`` at a time; every instrumented layer calls
``check(site)`` on each dispatch/IO attempt. Unarmed, the check is a
single lock-free attribute read — the production fast path costs one
``is None`` test. Armed, each site keeps a call counter (reset at arm
time, so runs are reproducible) and a matching fault either

* raises here (``raise`` — ``FaultInjected``; ``hang`` — a real
  ``time.sleep(seconds)`` so heartbeats go stale and /healthz flips,
  then ``FaultTimeout``), or
* is returned to the hook, which applies the site-specific damage
  (``corrupt`` / ``partial`` mean different things to a bus delivery
  than to a checkpoint write — see docs/resilience.md).

Every fired fault is counted (``faults_injected_total{site,kind}``)
and emitted as a ``fault_injected`` event, so the flight recorder and
the perfwatch /events tail show exactly which injections a post-mortem
run absorbed.

This module must stay importable from ``core/build.py`` (the native-load
hook), so it imports only the standard library + telemetry (stdlib-only
by contract) — never jax, never core.
"""
from __future__ import annotations

import threading
import time

from . import FaultInjected, FaultPlanError, FaultTimeout
from .faultplan import FaultPlan, FaultSpec

_lock = threading.Lock()
_plan: FaultPlan | None = None
_counts: dict[str, int] = {}
_fired: dict[int, int] = {}   # fault index in plan -> times fired


def arm(plan: FaultPlan) -> None:
    """Arms ``plan`` process-wide and resets all site call counters —
    arming is the reproducibility epoch."""
    global _plan
    with _lock:
        _plan = plan
        _counts.clear()
        _fired.clear()


def disarm(strict: bool = False) -> None:
    """Disarms. With ``strict=True`` and a strict plan, raises
    ``FaultPlanError`` if any fault never fired (the run ended without
    exhausting the plan — the injected scenario was not exercised)."""
    global _plan
    with _lock:
        plan, fired = _plan, dict(_fired)
        _plan = None
        _counts.clear()
        _fired.clear()
    if strict and plan is not None and plan.strict:
        unfired = [i for i in range(len(plan.faults)) if i not in fired]
        if unfired:
            specs = ", ".join(
                f"#{i} {plan.faults[i].site}/{plan.faults[i].kind}"
                f"@{plan.faults[i].call}" for i in unfired)
            raise FaultPlanError(
                f"fault plan not exhausted: fault(s) {specs} never fired "
                f"(the run ended before reaching their call index)")


def active() -> bool:
    return _plan is not None


def armed_plan() -> FaultPlan | None:
    return _plan


def call_counts() -> dict[str, int]:
    """Per-site call counters since arming (test/forensics surface)."""
    with _lock:
        return dict(_counts)


def check(site: str, **ctx) -> FaultSpec | None:
    """The hook every instrumented layer calls once per attempt.

    Returns None (no plan / no match), raises (``raise``/``hang``
    kinds), or returns the matching ``FaultSpec`` for the hook to apply
    (``corrupt``/``partial`` kinds). ``ctx`` fields land in the
    ``fault_injected`` event for forensics.
    """
    plan = _plan
    if plan is None:
        return None
    with _lock:
        index = _counts.get(site, 0)
        _counts[site] = index + 1
        matched = plan.match_all(site, index)
        # Apply the FIRST matching fault, but credit every overlapping
        # window as fired — strict exhaustion must count shadowed specs.
        for i, _ in matched:
            _fired[i] = _fired.get(i, 0) + 1
        fault = matched[0][1] if matched else None
    if fault is None:
        return None
    _record(site, fault, index, ctx)
    if fault.kind == "raise":
        raise FaultInjected(site, "raise", fault.message)
    if fault.kind == "hang":
        # A real sleep, not a mock: the heartbeat gauges go stale for
        # `seconds`, which is exactly what the /healthz watchdog and the
        # span timeline must witness for a hang to be debuggable.
        time.sleep(fault.seconds)
        raise FaultTimeout(site, "hang",
                           fault.message or f"simulated hang at {site} "
                           f"exceeded its {fault.seconds}s watchdog")
    return fault


def _record(site: str, fault: FaultSpec, index: int, ctx: dict) -> None:
    from ..telemetry import counter
    from ..telemetry.events import emit_event

    counter("faults_injected_total",
            help="injected faults fired, by site and kind",
            site=site, kind=fault.kind).inc()
    emit_event({"event": "fault_injected", "site": site,
                "kind": fault.kind, "call": index, **ctx})
